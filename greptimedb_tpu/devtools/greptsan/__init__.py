"""greptsan: vector-clock happens-before data-race detector.

The dynamic tier above greptlint (syntactic) and the lock-order
detector (lock graph): greptsan watches *shared-state accesses* and
reports a race when two threads touch the same variable, at least one
writes, and NO chain of synchronization edges orders the accesses —
ThreadSanitizer's happens-before model (Serebryany et al.), rebuilt in
pure Python over this repo's existing instrumentation choke points.

Happens-before edges (see detector.py):

- ``TrackedLock``/``TrackedRLock`` release -> next acquire of the same
  lock instance (common/locks.py calls the hooks; Condition.wait/notify
  synchronize through the underlying tracked lock's release/reacquire).
- thread spawn -> child start, child end -> ``join()`` (all
  ``threading.Thread`` users, including ``runtime.new_thread``).
- pool ``submit()`` -> task start, task end -> ``Future.result()`` —
  every sanctioned pool path (spawn_bg/read/write, parallel_map/imap,
  the dist fan-out) runs through these.

Shared state opts in via :func:`tracked_state` (state.py) — a dict/
list/set subclass that records each access with the accessing thread's
vector clock and checks it against prior accesses. When the detector is
off (production), ``tracked_state`` returns its argument unchanged:
zero overhead, the TrackedLock/failpoint factory pattern.

Enablement mirrors common/locks.py: ``GREPTIME_RACE_CHECK=1`` forces
on, ``=0`` forces off, otherwise auto-on under pytest. Races are
*recorded*, not raised — execution continues, and the pytest session
gate (tests/conftest.py) fails the run if any unsuppressed race was
observed. The suppression baseline (.greptsan-baseline.json, crc-keyed
like greptlint's) exists for emergencies only and is kept at ZERO
entries: real races get fixed, not suppressed.
"""

from __future__ import annotations

from .detector import (RaceReport, drain_races, enabled, join_edges,
                       load_suppressions, races, reset, unsuppressed)
from .state import TrackedDict, TrackedList, TrackedSet, tracked_state

__all__ = ["enabled", "tracked_state", "TrackedDict", "TrackedList",
           "TrackedSet", "RaceReport", "races", "drain_races", "reset",
           "unsuppressed", "load_suppressions", "join_edges"]
