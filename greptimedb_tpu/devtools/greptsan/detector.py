"""Vector-clock happens-before engine + synchronization-edge hooks.

Model (FastTrack-flavored, Serebryany et al.'s ThreadSanitizer core):

- every thread carries a vector clock ``C_t: tid -> epoch``; its own
  component advances at each *release point* (lock release, spawn,
  submit, Event.set, task completion);
- a synchronization object (tracked lock, Thread, Future, Event)
  carries the clock snapshot of its last release point; the matching
  *acquire point* (lock acquire, join, result, wait) joins that
  snapshot into the acquirer's clock;
- an access by thread ``u`` at epoch ``e`` happens-before the current
  operation of thread ``t`` iff ``e <= C_t[u]``. Two accesses to the
  same variable, at least one a write, neither ordered — that is a
  data race, reported with both stacks and both sides' held locks.

Races are recorded (deduplicated by a crc key, the suppression-baseline
key), never raised: the run continues and the pytest session gate
(tests/conftest.py) fails if any unsuppressed race was seen.

Generation resets: the pytest fixture calls :func:`new_generation`
between tests, clearing variable metadata and lazily resetting thread
clocks. Clocks would otherwise accumulate one component per thread ever
spawned (a full tier-1 run spawns thousands), making every clock join
O(session) instead of O(test). Sound for intra-test races: an edge can
only order accesses that come after it, and accesses + the edges that
order them always live in the same test, hence the same generation.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

logger = logging.getLogger(__name__)

__all__ = ["enabled", "RaceReport", "races", "drain_races", "reset",
           "new_generation", "unsuppressed", "load_suppressions",
           "record_access", "join_edges", "DEFAULT_BASELINE"]

DEFAULT_BASELINE = ".greptsan-baseline.json"

#: synchronization edges that create happens-before (the README table)
join_edges = (
    "TrackedLock/TrackedRLock release -> acquire (Condition wait/notify "
    "synchronizes through the lock's release/reacquire)",
    "threading.Thread start -> child run, child exit -> join()",
    "Executor.submit -> task start, task end -> Future.result()",
    "threading.Event set -> wait()",
)


def _env_enabled() -> bool:
    v = os.environ.get("GREPTIME_RACE_CHECK")
    if v is not None:
        return v.strip().lower() not in ("", "0", "false", "off", "no")
    if "pytest" not in sys.modules:
        return False
    # pytest auto-on is conditional on lock tracking: the lock
    # release->acquire edges ride common/locks' hooks, so if the
    # operator explicitly disabled that detector (GREPTIME_LOCK_CHECK=0)
    # running raceless would report every lock-protected access as a
    # race — a false-positive storm, not a safety net
    from ...common import locks
    return locks.enabled()


_ENABLED: bool = _env_enabled()


def enabled() -> bool:
    return _ENABLED


# ---------------------------------------------------------------------
# per-thread state: tid + vector clock, generation-scoped
# ---------------------------------------------------------------------

_tls = threading.local()
_san_lock = threading.Lock()          # guards _vars/_races/_tid_seq
_tid_seq = [0]
_gen = [0]


def _ctx() -> Any:
    """This thread's (tid, clock), lazily created and generation-fresh."""
    tid = getattr(_tls, "tid", None)
    if tid is None:
        with _san_lock:
            _tid_seq[0] += 1
            tid = _tls.tid = _tid_seq[0]
        _tls.gen = _gen[0]
        _tls.clock = {tid: 1}
    elif getattr(_tls, "gen", -1) != _gen[0]:
        _tls.gen = _gen[0]
        _tls.clock = {tid: 1}
    return _tls


def _tick() -> None:
    st = _ctx()
    st.clock[st.tid] += 1


def snapshot() -> Tuple[int, Dict[int, int]]:
    """(generation, clock copy) of this thread at a release point; the
    thread's own component then advances so later events are not covered
    by the snapshot."""
    st = _ctx()
    snap = (st.gen, dict(st.clock))
    st.clock[st.tid] += 1
    return snap


def join(snap: Optional[Tuple[int, Dict[int, int]]]) -> None:
    """Acquire point: merge a release-point snapshot into this thread's
    clock. Snapshots from an earlier generation are stale (their edges
    cannot order any current-generation access) and are ignored."""
    if not snap:
        return
    gen, clock = snap
    st = _ctx()
    if gen != st.gen:
        return
    mine = st.clock
    for tid, epoch in clock.items():
        if mine.get(tid, 0) < epoch:
            mine[tid] = epoch


def new_generation() -> None:
    """Forget variable metadata and lazily reset clocks (between-test
    hygiene; recorded races are kept — the session gate reads those)."""
    with _san_lock:
        _gen[0] += 1
        _vars.clear()


def reset() -> None:
    """new_generation + drop recorded races (selftest isolation)."""
    with _san_lock:
        _gen[0] += 1
        _vars.clear()
        _races.clear()
        _reported.clear()


# ---------------------------------------------------------------------
# race reports
# ---------------------------------------------------------------------

@dataclass
class Access:
    tid: int
    epoch: int
    thread_name: str
    write: bool
    stack: Tuple[Tuple[str, int, str], ...]
    held: Tuple[str, ...]

    def render(self) -> str:
        frames = " <- ".join(f"{os.path.basename(f)}:{ln} in {fn}"
                             for f, ln, fn in self.stack) or "<no frames>"
        held = ", ".join(self.held) if self.held else "none"
        rw = "write" if self.write else "read"
        return (f"{rw} by thread {self.thread_name!r} (locks held: "
                f"{held})\n      at {frames}")


@dataclass
class RaceReport:
    state: str
    key: object
    kind: str                       # write-write / read-write / write-read
    prior: Access
    current: Access

    def suppression_key(self) -> str:
        """crc-keyed like greptlint's baseline: stable across line moves
        elsewhere, specific enough to never mask a different race."""
        sig = "|".join([self.state, self.kind] +
                       [f"{os.path.basename(f)}:{fn}"
                        for f, _ln, fn in self.prior.stack] +
                       [f"{os.path.basename(f)}:{fn}"
                        for f, _ln, fn in self.current.stack])
        crc = zlib.crc32(sig.encode()) & 0xFFFFFFFF
        return f"{self.state}:{crc:08x}"

    def render(self) -> str:
        both_held = set(self.prior.held) & set(self.current.held)
        if both_held:
            edge = (f"both sides hold {sorted(both_held)} yet no "
                    f"release->acquire edge ordered them (lock taken "
                    f"after the access?)")
        else:
            edge = ("no happens-before edge orders the accesses: the "
                    "sides share no lock, and no thread-join / "
                    "Future.result / Event.wait chain connects them — "
                    "guard the state with one TrackedLock on BOTH sides "
                    "or hand it off through a pool result/join")
        return (f"DATA RACE ({self.kind}) on {self.state}"
                f"{f'[{self.key!r}]' if self.key is not None else ''}\n"
                f"  prior   {self.prior.render()}\n"
                f"  current {self.current.render()}\n"
                f"  missing edge: {edge}\n"
                f"  suppression key: {self.suppression_key()}")


class _Var:
    __slots__ = ("write", "reads")

    def __init__(self) -> None:
        self.write: Optional[Access] = None
        self.reads: Dict[int, Access] = {}


_vars: Dict[Tuple[int, object], _Var] = {}
_races: List[RaceReport] = []
_reported: Set[str] = set()


def races() -> List[RaceReport]:
    with _san_lock:
        return list(_races)


def drain_races() -> List[RaceReport]:
    with _san_lock:
        out = list(_races)
        _races.clear()
        _reported.clear()
        return out


#: the detector's own machinery frames, skipped in captured stacks —
#: exact paths, NOT a substring: races seeded under greptsan/selftest/
#: must render their real frames (and key their suppression crc off
#: them), or distinct races would collapse onto one threading.py key
_OWN_FILES = frozenset({
    __file__,
    os.path.join(os.path.dirname(__file__), "state.py"),
})


def _capture_stack(skip: int) -> Tuple[Tuple[str, int, str], ...]:
    """Innermost 4 caller frames as (file, line, func) — cheap enough
    for per-access capture, informative enough for a report."""
    frames = []
    try:
        f = sys._getframe(skip)
    except ValueError:
        return ()
    while f is not None and len(frames) < 4:
        code = f.f_code
        if code.co_filename not in _OWN_FILES:
            frames.append((code.co_filename, f.f_lineno, code.co_name))
        f = f.f_back
    return tuple(frames)


def _held_lock_names() -> Tuple[str, ...]:
    try:
        from ...common import locks
        return tuple(locks.held_locks())
    except Exception:  # noqa: BLE001 — introspection only, never fail
        return ()


def _report(state: str, key: object, kind: str, prior: Access,
            current: Access) -> Optional[RaceReport]:
    """Record a deduplicated race under _san_lock; caller logs OUTSIDE
    the lock (the logging module takes its own handler lock — nesting it
    under ours would hand the two-lock-cycle bug to the race detector
    itself)."""
    r = RaceReport(state, key, kind, prior, current)
    skey = r.suppression_key()
    if skey in _reported:
        return None
    # invariant: only record_access calls _report, under _san_lock
    _reported.add(skey)      # greptlint: disable=GL08
    _races.append(r)         # greptlint: disable=GL08
    return r


def record_access(state_name: str, state_id: int, key: object,
                  write: bool, *, skip: int = 2) -> None:
    """The state.py proxies call this on every tracked access."""
    if not _ENABLED:
        return
    st = _ctx()
    me, clock = st.tid, st.clock
    acc = Access(me, clock[me], threading.current_thread().name, write,
                 _capture_stack(skip), _held_lock_names())
    try:
        vkey = (state_id, key)
        hash(vkey)
    except TypeError:
        vkey = (state_id, repr(key))
    found: List[RaceReport] = []
    with _san_lock:
        var = _vars.get(vkey)
        if var is None:
            var = _vars[vkey] = _Var()
        w = var.write
        if w is not None and w.tid != me and w.epoch > clock.get(w.tid, 0):
            rep = _report(state_name, key,
                          "write-write" if write else "write-read", w, acc)
            if rep is not None:
                found.append(rep)
        if write:
            for rt, r in var.reads.items():
                if rt != me and r.epoch > clock.get(rt, 0):
                    rep = _report(state_name, key, "read-write", r, acc)
                    if rep is not None:
                        found.append(rep)
            var.write = acc
            var.reads.clear()
        else:
            var.reads[me] = acc
    for rep in found:
        logger.error("greptsan: %s", rep.render())


# ---------------------------------------------------------------------
# suppression baseline (kept at ZERO entries; emergencies only)
# ---------------------------------------------------------------------

def load_suppressions(path: Optional[str] = None) -> Dict[str, str]:
    """{suppression_key: justification}. Missing file = no suppressions."""
    if path is None:
        path = DEFAULT_BASELINE
    if not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("version") != 1:
        raise ValueError(f"unsupported greptsan baseline format in {path}")
    return {str(k): str(v) for k, v in doc.get("suppressions", {}).items()}


def unsuppressed(reports: List[RaceReport],
                 path: Optional[str] = None) -> List[RaceReport]:
    sup = load_suppressions(path)
    return [r for r in reports if r.suppression_key() not in sup]


# ---------------------------------------------------------------------
# happens-before hooks: locks (via common/locks), threads, pools, events
# ---------------------------------------------------------------------

def _on_lock_acquire(lock: Any) -> None:
    join(getattr(lock, "_san_clock", None))


def _on_lock_release(lock: Any) -> None:
    gen_clock = getattr(lock, "_san_clock", None)
    snap = snapshot()
    if gen_clock and gen_clock[0] == snap[0]:
        merged = gen_clock[1]
        for tid, epoch in snap[1].items():
            if merged.get(tid, 0) < epoch:
                merged[tid] = epoch
        lock._san_clock = (snap[0], merged)
    else:
        lock._san_clock = snap


def _install_lock_hooks() -> None:
    from ...common import locks
    locks.set_race_hooks(_on_lock_acquire, _on_lock_release)


_PATCHED = False


def _install_patches() -> None:
    """Interpose the stdlib synchronization points, the way TSan wraps
    pthread_create/join — test-mode only, guarded by enabled()."""
    global _PATCHED
    if _PATCHED:
        return
    _PATCHED = True

    import concurrent.futures as _cf

    # ---- thread spawn/join edges ----
    _orig_start = threading.Thread.start
    _orig_run = threading.Thread.run
    _orig_join = threading.Thread.join

    def start(self: threading.Thread) -> None:
        self._gsan_spawn = snapshot()
        _orig_start(self)

    def run(self: threading.Thread) -> None:
        join(getattr(self, "_gsan_spawn", None))
        try:
            _orig_run(self)
        finally:
            self._gsan_final = snapshot()

    def join_(self: threading.Thread,
              timeout: Optional[float] = None) -> None:
        _orig_join(self, timeout)
        if not self.is_alive():
            join(getattr(self, "_gsan_final", None))

    threading.Thread.start = start                 # type: ignore[method-assign]
    threading.Thread.run = run                     # type: ignore[method-assign]
    threading.Thread.join = join_                  # type: ignore[method-assign]

    # Timer overrides run() (so the Thread.run patch never executes);
    # give it the same spawn-edge join + final snapshot
    _orig_timer_run = threading.Timer.run

    def timer_run(self: threading.Timer) -> None:
        join(getattr(self, "_gsan_spawn", None))
        try:
            _orig_timer_run(self)
        finally:
            self._gsan_final = snapshot()

    threading.Timer.run = timer_run                # type: ignore[method-assign]

    # ---- pool submit -> task start, task end -> result() edges ----
    _orig_submit = _cf.ThreadPoolExecutor.submit

    def submit(self: Any, fn: Callable, /, *args: Any,
               **kwargs: Any) -> Any:
        snap = snapshot()
        import functools

        @functools.wraps(fn)
        def task(*a: Any, **k: Any) -> Any:
            join(snap)
            return fn(*a, **k)

        return _orig_submit(self, task, *args, **kwargs)

    _cf.ThreadPoolExecutor.submit = submit         # type: ignore[method-assign]

    _orig_set_result = _cf.Future.set_result
    _orig_set_exc = _cf.Future.set_exception
    _orig_result = _cf.Future.result
    _orig_exception = _cf.Future.exception

    def set_result(self: Any, result: Any) -> None:
        self._gsan_done = snapshot()
        _orig_set_result(self, result)

    def set_exception(self: Any, exc: Any) -> None:
        self._gsan_done = snapshot()
        _orig_set_exc(self, exc)

    def result(self: Any, timeout: Optional[float] = None) -> Any:
        try:
            return _orig_result(self, timeout)
        finally:
            join(getattr(self, "_gsan_done", None))

    def exception(self: Any, timeout: Optional[float] = None) -> Any:
        try:
            return _orig_exception(self, timeout)
        finally:
            join(getattr(self, "_gsan_done", None))

    _cf.Future.set_result = set_result             # type: ignore[method-assign]
    _cf.Future.set_exception = set_exception       # type: ignore[method-assign]
    _cf.Future.result = result                     # type: ignore[method-assign]
    _cf.Future.exception = exception               # type: ignore[method-assign]

    # ---- Event set -> wait edge (JobHandle/stop-flag handoffs) ----
    _orig_event_set = threading.Event.set
    _orig_event_wait = threading.Event.wait

    def event_set(self: threading.Event) -> None:
        self._gsan_set = snapshot()
        _orig_event_set(self)

    def event_wait(self: threading.Event,
                   timeout: Optional[float] = None) -> bool:
        ok = _orig_event_wait(self, timeout)
        if ok:
            join(getattr(self, "_gsan_set", None))
        return ok

    threading.Event.set = event_set                # type: ignore[method-assign]
    threading.Event.wait = event_wait              # type: ignore[method-assign]


if _ENABLED:
    _install_lock_hooks()
    _install_patches()
