"""Seeded concurrency bugs greptsan MUST catch (tests/test_greptsan.py).

Each function plants one classic unsynchronized-sharing bug on its own
dedicated tracked structure and runs it to completion; the test asserts
a race report naming that structure fired. A detector that stops firing
on these is a silently-dead invariant — the same contract as
greptlint's selftest fixtures, but dynamic.

This directory is in greptlint's SKIP_DIRS (deliberate bugs must not
count against the repo scan) and excluded from mypy.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ....common.locks import TrackedLock
from .. import tracked_state


def unlocked_dict_mutation() -> str:
    """Two threads mutate one shared dict with NO common lock — the
    textbook unsynchronized read-modify-write both greptlint GL08 (when
    a module lock exists) and code review keep missing when the dict
    hides behind an attribute."""
    name = "greptsan.selftest.unlocked_dict"
    shared = tracked_state({}, name)
    barrier = threading.Barrier(2)

    def bump(tag: str) -> None:
        barrier.wait()
        for i in range(50):
            shared[tag] = i            # distinct keys: GIL-atomic...
            shared["total"] = shared.get("total", 0) + 1   # ...this isn't

    t1 = threading.Thread(target=bump, args=("a",), name="san-dict-a")
    t2 = threading.Thread(target=bump, args=("b",), name="san-dict-b")
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    return name


def notify_without_lock() -> str:
    """Producer notifies the consumer FIRST and publishes the payload
    after — the waiter can wake, reacquire the lock and read state the
    producer has not written yet. The happens-before chain through the
    condition's lock covers only what preceded the producer's release,
    which the late write does not."""
    name = "greptsan.selftest.notify_state"
    state = tracked_state({}, name)
    lk = TrackedLock("greptsan.selftest.notify_lock", force=True)
    cond = threading.Condition(lk)
    consumer_in_wait = threading.Barrier(2)

    def producer() -> None:
        consumer_in_wait.wait()
        time.sleep(0.05)               # let the consumer park in wait()
        with cond:
            cond.notify()
        state["ready"] = 1             # BUG: published after the wakeup

    def consumer() -> None:
        with cond:
            consumer_in_wait.wait()
            cond.wait(timeout=5)
        state.get("ready")             # unordered vs the late publish

    t1 = threading.Thread(target=producer, name="san-notify-producer")
    t2 = threading.Thread(target=consumer, name="san-notify-consumer")
    t2.start()
    t1.start()
    t1.join()
    t2.join()
    return name


def pool_result_before_join() -> str:
    """The caller polls ``future.done()`` and reads the task's output
    state WITHOUT calling ``result()`` — ``done()`` is a completion
    *flag*, not a synchronization edge, so nothing orders the worker's
    writes before the caller's read."""
    name = "greptsan.selftest.pool_state"
    state = tracked_state({}, name)
    pool = ThreadPoolExecutor(max_workers=1,
                              thread_name_prefix="san-pool")
    try:
        fut = pool.submit(lambda: state.__setitem__("x", 1))
        while not fut.done():          # BUG: done() instead of result()
            time.sleep(0.005)
        state.get("x")
        fut.result()                   # too late: the read already raced
    finally:
        pool.shutdown(wait=True)
    return name
