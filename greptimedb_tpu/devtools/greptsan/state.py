"""tracked_state(): shared-structure access recording for greptsan.

``tracked_state(obj, name)`` wraps a dict/list/set/OrderedDict in a
subclass whose accesses flow through :func:`detector.record_access`.
When the detector is off it returns ``obj`` unchanged — the
TrackedLock/failpoint zero-overhead factory pattern (bench.py's
``greptsan_inactive_overhead`` asserts the differential is noise).

Granularity (what counts as "the same variable"):

- dict item get/set/del race per *key* — two threads updating different
  keys are GIL-atomic and independent by design in this codebase;
- operations that change or observe the *key set* (inserting a new key,
  deleting, clear, len, iteration, keys/values/items, containment)
  share one ``<shape>`` variable — an unsynchronized key-set change
  concurrent with iteration is exactly the "dict changed size during
  iteration" crash, so shape-write vs shape-read is a reported race;
- lists and sets are one variable each (their idiomatic uses here —
  scheduler queues, worker lists, mailbox lists — are whole-structure).

The proxies subclass the builtins, so isinstance checks, json encoding
and repr all behave; only the access-recording methods are overridden.
"""

from __future__ import annotations

import itertools
import threading
from collections import OrderedDict
from typing import Any, Iterator, Tuple

from . import detector

__all__ = ["tracked_state", "TrackedDict", "TrackedOrderedDict",
           "TrackedList", "TrackedSet", "SHAPE"]

#: sentinel variable key for key-set shape accesses
SHAPE = "<shape>"

_ids = itertools.count(1)
_ids_lock = threading.Lock()


def _next_id() -> int:
    with _ids_lock:
        return next(_ids)


class _TrackedBase:
    """Mixin holding the (name, id) identity + record shorthands."""

    _san_name: str
    _san_id: int

    def _san_init(self, name: str) -> None:
        # object.__setattr__: subclasses of dict/list/set have no
        # __slots__ conflict, but keep the write explicit and cheap
        self._san_name = name
        self._san_id = _next_id()

    def _rec(self, key: object, write: bool) -> None:
        detector.record_access(self._san_name, self._san_id, key, write,
                               skip=3)


class TrackedDict(_TrackedBase, dict):
    def __init__(self, name: str, *args: Any, **kwargs: Any):
        dict.__init__(self, *args, **kwargs)
        self._san_init(name)

    # -- per-key accesses --------------------------------------------
    def __getitem__(self, key: object) -> Any:
        self._rec(key, False)
        return dict.__getitem__(self, key)

    def get(self, key: object, default: Any = None) -> Any:
        self._rec(key, False)
        return dict.get(self, key, default)

    def __setitem__(self, key: object, value: Any) -> None:
        if not dict.__contains__(self, key):
            self._rec(SHAPE, True)
        self._rec(key, True)
        dict.__setitem__(self, key, value)

    def setdefault(self, key: object, default: Any = None) -> Any:
        if not dict.__contains__(self, key):
            self._rec(SHAPE, True)
            self._rec(key, True)
        else:
            self._rec(key, False)
        return dict.setdefault(self, key, default)

    def __delitem__(self, key: object) -> None:
        self._rec(SHAPE, True)
        self._rec(key, True)
        dict.__delitem__(self, key)

    def pop(self, key: object, *default: Any) -> Any:
        if dict.__contains__(self, key):
            self._rec(SHAPE, True)
        self._rec(key, True)
        return dict.pop(self, key, *default)

    def popitem(self) -> Tuple[Any, Any]:
        self._rec(SHAPE, True)
        return dict.popitem(self)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._rec(SHAPE, True)
        dict.update(self, *args, **kwargs)

    def clear(self) -> None:
        self._rec(SHAPE, True)
        dict.clear(self)

    # -- shape observations ------------------------------------------
    def __contains__(self, key: object) -> bool:
        self._rec(SHAPE, False)
        return dict.__contains__(self, key)

    def __iter__(self) -> Iterator:
        self._rec(SHAPE, False)
        return dict.__iter__(self)

    def __len__(self) -> int:
        self._rec(SHAPE, False)
        return dict.__len__(self)

    def keys(self):  # type: ignore[no-untyped-def]
        self._rec(SHAPE, False)
        return dict.keys(self)

    def values(self):  # type: ignore[no-untyped-def]
        self._rec(SHAPE, False)
        return dict.values(self)

    def items(self):  # type: ignore[no-untyped-def]
        self._rec(SHAPE, False)
        return dict.items(self)

    def copy(self) -> dict:
        self._rec(SHAPE, False)
        return dict(self)


class TrackedOrderedDict(_TrackedBase, OrderedDict):
    """OrderedDict twin (the LRU caches): move_to_end is a write to the
    *order*, which iteration observes — modeled as a shape write."""

    def __init__(self, name: str, *args: Any, **kwargs: Any):
        OrderedDict.__init__(self, *args, **kwargs)
        self._san_init(name)

    def __getitem__(self, key: object) -> Any:
        self._rec(key, False)
        return OrderedDict.__getitem__(self, key)

    def get(self, key: object, default: Any = None) -> Any:
        self._rec(key, False)
        return OrderedDict.get(self, key, default)

    def __setitem__(self, key: object, value: Any) -> None:
        if not dict.__contains__(self, key):
            self._rec(SHAPE, True)
        self._rec(key, True)
        OrderedDict.__setitem__(self, key, value)

    def setdefault(self, key: object, default: Any = None) -> Any:
        if not dict.__contains__(self, key):
            self._rec(SHAPE, True)
            self._rec(key, True)
        else:
            self._rec(key, False)
        return OrderedDict.setdefault(self, key, default)

    def __delitem__(self, key: object) -> None:
        self._rec(SHAPE, True)
        self._rec(key, True)
        OrderedDict.__delitem__(self, key)

    def pop(self, key: object, *default: Any) -> Any:
        if dict.__contains__(self, key):
            self._rec(SHAPE, True)
        self._rec(key, True)
        return OrderedDict.pop(self, key, *default)

    def popitem(self, last: bool = True) -> Tuple[Any, Any]:
        self._rec(SHAPE, True)
        return OrderedDict.popitem(self, last)

    def move_to_end(self, key: object, last: bool = True) -> None:
        self._rec(SHAPE, True)
        OrderedDict.move_to_end(self, key, last)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._rec(SHAPE, True)
        OrderedDict.update(self, *args, **kwargs)

    def clear(self) -> None:
        self._rec(SHAPE, True)
        OrderedDict.clear(self)

    def __contains__(self, key: object) -> bool:
        self._rec(SHAPE, False)
        return dict.__contains__(self, key)

    def __iter__(self) -> Iterator:
        self._rec(SHAPE, False)
        return OrderedDict.__iter__(self)

    def __len__(self) -> int:
        self._rec(SHAPE, False)
        return dict.__len__(self)

    def keys(self):  # type: ignore[no-untyped-def]
        self._rec(SHAPE, False)
        return OrderedDict.keys(self)

    def values(self):  # type: ignore[no-untyped-def]
        self._rec(SHAPE, False)
        return OrderedDict.values(self)

    def items(self):  # type: ignore[no-untyped-def]
        self._rec(SHAPE, False)
        return OrderedDict.items(self)

    def copy(self) -> OrderedDict:
        # OrderedDict.copy() builds self.__class__(self) — whose first
        # positional here is the tracker NAME, so the inherited copy
        # would TypeError only under the detector. Return a plain
        # OrderedDict (the TrackedDict.copy contract).
        self._rec(SHAPE, False)
        out: OrderedDict = OrderedDict()
        for k in OrderedDict.keys(self):
            out[k] = OrderedDict.__getitem__(self, k)
        return out


class TrackedList(_TrackedBase, list):
    def __init__(self, name: str, *args: Any):
        list.__init__(self, *args)
        self._san_init(name)

    def _read(self) -> None:
        self._rec(SHAPE, False)

    def _write(self) -> None:
        self._rec(SHAPE, True)

    def __getitem__(self, i: Any) -> Any:
        self._read()
        return list.__getitem__(self, i)

    def __setitem__(self, i: Any, v: Any) -> None:
        self._write()
        list.__setitem__(self, i, v)

    def __delitem__(self, i: Any) -> None:
        self._write()
        list.__delitem__(self, i)

    def __iter__(self) -> Iterator:
        self._read()
        return list.__iter__(self)

    def __len__(self) -> int:
        self._read()
        return list.__len__(self)

    def __contains__(self, v: object) -> bool:
        self._read()
        return list.__contains__(self, v)

    def append(self, v: Any) -> None:
        self._write()
        list.append(self, v)

    def extend(self, it: Any) -> None:
        self._write()
        list.extend(self, it)

    def insert(self, i: int, v: Any) -> None:
        self._write()
        list.insert(self, i, v)

    def pop(self, i: int = -1) -> Any:
        self._write()
        return list.pop(self, i)

    def remove(self, v: Any) -> None:
        self._write()
        list.remove(self, v)

    def clear(self) -> None:
        self._write()
        list.clear(self)

    def sort(self, **kw: Any) -> None:
        self._write()
        list.sort(self, **kw)


class TrackedSet(_TrackedBase, set):
    def __init__(self, name: str, *args: Any):
        set.__init__(self, *args)
        self._san_init(name)

    def _read(self) -> None:
        self._rec(SHAPE, False)

    def _write(self) -> None:
        self._rec(SHAPE, True)

    def __contains__(self, v: object) -> bool:
        self._read()
        return set.__contains__(self, v)

    def __iter__(self) -> Iterator:
        self._read()
        return set.__iter__(self)

    def __len__(self) -> int:
        self._read()
        return set.__len__(self)

    def add(self, v: Any) -> None:
        self._write()
        set.add(self, v)

    def discard(self, v: Any) -> None:
        self._write()
        set.discard(self, v)

    def remove(self, v: Any) -> None:
        self._write()
        set.remove(self, v)

    def clear(self) -> None:
        self._write()
        set.clear(self)

    def update(self, *others: Any) -> None:
        self._write()
        set.update(self, *others)


def tracked_state(obj: Any, name: str) -> Any:
    """Wrap a shared structure for race detection; identity when off.

    ``name`` is the report label ("storage.engine.regions") — one name
    per structure *class*, like TrackedLock names. Apply at creation:

        self._regions = tracked_state({}, "storage.engine.regions")

    Supported: dict, OrderedDict, list, set. Anything else returns
    unchanged (with a one-time warning under the detector) so a caller
    never breaks when a structure changes type."""
    if not detector.enabled():
        return obj
    if isinstance(obj, OrderedDict):
        out: Any = TrackedOrderedDict(name)
        OrderedDict.update(out, obj)
        return out
    if isinstance(obj, dict):
        out = TrackedDict(name)
        dict.update(out, obj)
        return out
    if isinstance(obj, list):
        out = TrackedList(name)
        list.extend(out, obj)
        return out
    if isinstance(obj, set):
        out = TrackedSet(name)
        set.update(out, obj)
        return out
    import logging
    logging.getLogger(__name__).warning(
        "tracked_state(%s): unsupported type %s — not tracked",
        name, type(obj).__name__)
    return obj
