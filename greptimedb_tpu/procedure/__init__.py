"""Durable procedure framework.

Reference behavior: src/common/procedure — multi-step operations (DDL)
persist each step so a crash mid-procedure resumes instead of leaving
half-applied state: `Procedure` trait with `execute → Status`
(procedure.rs:84), `LocalManager` + `Runner` with retry/backoff
(local.rs:307, local/runner.rs), `ObjectStateStore` writing step JSON to
the object store (store/state_store.rs), `Watcher` for completion
(watcher.rs), and recovery of in-flight procedures on restart
(local.rs:383-417).
"""

from .framework import (
    Procedure, ProcedureManager, RetryLater, Status, Watcher)

__all__ = ["Procedure", "ProcedureManager", "RetryLater", "Status",
           "Watcher"]
