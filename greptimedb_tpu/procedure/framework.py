"""Procedure trait, local manager/runner, object-store state persistence.

Reference mapping:
- `Procedure` / `Status::{Executing, Done}` — procedure.rs:84
- `LocalManager.submit` + `Runner` retry loop — local.rs:307, runner
- `ObjectStateStore`: step JSON at procedures/{id}/{step}.step, commit
  marker on completion — store/state_store.rs
- `Watcher` — watcher.rs
- recovery: load the latest persisted step of uncommitted procedures and
  re-run from there — local.rs:383-417

Single-process semantics: a procedure's `execute(ctx)` is called
repeatedly; each return of `Status.executing(persist=True)` checkpoints
`dump()`. Exceptions marked retryable (`RetryLater`) back off and retry;
other exceptions fail the procedure (state kept for inspection).
"""

from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..errors import GreptimeError

logger = logging.getLogger(__name__)

PROC_PREFIX = "procedures"


class RetryLater(GreptimeError):
    """Raise from execute() to request a backoff retry (reference:
    Error::retry_later / Status::retry_later)."""


@dataclass
class Status:
    state: str                       # "executing" | "done"
    persist: bool = True

    @staticmethod
    def executing(persist: bool = True) -> "Status":
        return Status("executing", persist)

    @staticmethod
    def done() -> "Status":
        return Status("done", False)

    @property
    def is_done(self) -> bool:
        return self.state == "done"


class Procedure:
    """One resumable multi-step operation."""

    #: registry key for recovery (reference: type_name())
    type_name: str = "Procedure"

    def execute(self, ctx: "Context") -> Status:
        raise NotImplementedError

    def dump(self) -> dict:
        """JSON state sufficient for the loader to reconstruct."""
        raise NotImplementedError

    def lock_key(self) -> Optional[str]:
        """Procedures sharing a key run serialized (reference: LockMap)."""
        return None

    def rollback(self, ctx: "Context") -> None:
        """Best-effort undo when the procedure fails permanently."""


@dataclass
class Context:
    procedure_id: str


class Watcher:
    def __init__(self):
        self._event = threading.Event()
        self._error: Optional[BaseException] = None

    def _finish(self, error: Optional[BaseException]) -> None:
        self._error = error
        self._event.set()

    def wait(self, timeout: Optional[float] = 30.0) -> None:
        if not self._event.wait(timeout):
            raise TimeoutError("procedure did not finish in time")
        if self._error is not None:
            raise self._error


class ProcedureManager:
    """LocalManager: submit/run/persist/recover procedures."""

    def __init__(self, store, max_retries: int = 3,
                 retry_delay_s: float = 0.05, run_async: bool = False,
                 state_prefix: str = ""):
        self.store = store
        self._prefix = state_prefix + PROC_PREFIX
        self.max_retries = max_retries
        self.retry_delay_s = retry_delay_s
        self.run_async = run_async
        self._loaders: Dict[str, Callable[[dict], Procedure]] = {}
        self._locks: Dict[str, threading.Lock] = {}
        self._locks_guard = threading.Lock()

    # ---- registry ----
    def register_loader(self, type_name: str,
                        loader: Callable[[dict], Procedure]) -> None:
        self._loaders[type_name] = loader

    # ---- state store ----
    def _step_key(self, pid: str, step: int) -> str:
        return f"{self._prefix}/{pid}/{step:010d}.step"

    def _commit_key(self, pid: str) -> str:
        return f"{self._prefix}/{pid}/commit"

    def _persist(self, pid: str, step: int, proc: Procedure) -> None:
        self.store.write(self._step_key(pid, step), json.dumps({
            "type": proc.type_name, "step": step, "data": proc.dump(),
        }).encode())

    def _cleanup(self, pid: str) -> None:
        for key in self.store.list(f"{self._prefix}/{pid}/"):
            self.store.delete(key)

    # ---- execution ----
    def submit(self, proc: Procedure,
               procedure_id: Optional[str] = None) -> Watcher:
        pid = procedure_id or uuid.uuid4().hex
        watcher = Watcher()
        if self.run_async:
            from ..common.runtime import new_thread
            t = new_thread(self._run, name=f"procedure-{pid}",
                           args=(proc, pid, watcher), daemon=True)
            t.start()
        else:
            self._run(proc, pid, watcher)
        return watcher

    def _lock_for(self, key: str) -> threading.Lock:
        with self._locks_guard:
            return self._locks.setdefault(key, threading.Lock())

    def _run(self, proc: Procedure, pid: str, watcher: Watcher) -> None:
        ctx = Context(procedure_id=pid)
        lock = self._lock_for(proc.lock_key()) \
            if proc.lock_key() is not None else None
        if lock is not None:
            lock.acquire()
        try:
            self._persist(pid, 0, proc)       # submitted state survives
            step = 1
            retries = 0
            while True:
                try:
                    status = proc.execute(ctx)
                except RetryLater:
                    retries += 1
                    if retries > self.max_retries:
                        raise
                    time.sleep(self.retry_delay_s * (2 ** (retries - 1)))
                    continue
                retries = 0
                if status.is_done:
                    self.store.write(self._commit_key(pid), b"done")
                    self._cleanup(pid)
                    watcher._finish(None)
                    return
                if status.persist:
                    self._persist(pid, step, proc)
                    step += 1
        # a SimulatedCrash lands in watcher.wait(), which re-raises it in
        # the submitter — delivery, not survival
        except BaseException as e:  # greptlint: disable=GL02
            logger.exception("procedure %s (%s) failed", pid,
                             proc.type_name)
            try:
                proc.rollback(ctx)
            except Exception:  # noqa: BLE001
                logger.exception("rollback of %s failed", pid)
            watcher._finish(e)
        finally:
            if lock is not None:
                lock.release()

    # ---- recovery ----
    def recover(self) -> List[str]:
        """Resume every uncommitted procedure from its last persisted
        step. Returns the recovered procedure ids."""
        by_pid: Dict[str, List[str]] = {}
        skip = len(self._prefix.split("/")) - 1
        for key in self.store.list(f"{self._prefix}/"):
            parts = key.split("/")[skip:]
            if len(parts) >= 3:
                by_pid.setdefault(parts[1], []).append(key)
        recovered = []
        for pid, keys in sorted(by_pid.items()):
            if any(k.endswith("/commit") for k in keys):
                self._cleanup(pid)            # finished; late GC
                continue
            steps = sorted(k for k in keys if k.endswith(".step"))
            if not steps:
                continue
            doc = json.loads(self.store.read(steps[-1]))
            loader = self._loaders.get(doc["type"])
            if loader is None:
                logger.warning("no loader for procedure type %r; leaving "
                               "%s for manual inspection", doc["type"], pid)
                continue
            proc = loader(doc["data"])
            watcher = self.submit(proc, procedure_id=pid)
            if not self.run_async:
                try:
                    watcher.wait(timeout=None)
                except Exception:  # noqa: BLE001
                    logger.exception("recovered procedure %s failed", pid)
            recovered.append(pid)
        return recovered
