"""Python coprocessor / UDF engine.

Reference behavior: src/script/src/python/ — the `@copr(args, returns,
sql=...)` decorator marks a Python function as a coprocessor
(ffi_types/copr.rs:40-120, decorator parse ffi_types/copr/parse.rs);
vectors bridge zero-copy into the script (ffi_types/vector.rs); scripts
persist in a `scripts` system table (table.rs:51) and register as UDFs
into the query engine (python/engine.rs:44-80). The reference needs a
RustPython/PyO3 VM to host Python; this framework *is* Python, so the
engine compiles scripts natively and hands them numpy/JAX vectors.
"""

from .copr import copr, coprocessor, Coprocessor
from .engine import ScriptEngine

__all__ = ["copr", "coprocessor", "Coprocessor", "ScriptEngine"]
