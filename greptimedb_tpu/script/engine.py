"""Script engine: compile, persist, run, and SQL-register coprocessors.

Reference behavior: src/script/src/python/engine.rs:44-80 — `PyEngine`
compiles a script into a `PyScript`, exposes `execute` (optionally running
the copr's bound `sql` first to produce input vectors), and registers the
coprocessor as a UDF in the query engine; src/script/src/table.rs:51 —
scripts persist to a `scripts` system table keyed by (schema, name) so
they survive restarts. The script executes in a namespace pre-loaded with
`copr`/`coprocessor`, numpy, and `jax.numpy` (the TPU path: a coprocessor
body written with jnp ops runs on device under jit).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..datatypes import data_type as dt
from ..datatypes.record_batch import RecordBatch
from ..datatypes.schema import ColumnSchema, Schema
from ..errors import GreptimeError, InvalidArgumentsError
from ..query.output import Output
from ..session import QueryContext
from .copr import Coprocessor, as_vectors, copr, coprocessor

logger = logging.getLogger(__name__)

SCRIPTS_TABLE = "scripts"


class ScriptEngine:
    """Owns compiled coprocessors + the scripts system table."""

    def __init__(self, frontend):
        self.frontend = frontend
        self._compiled: Dict[str, Coprocessor] = {}   # schema.name -> copr

    # ---- compile ----
    @staticmethod
    def compile(script: str, name: Optional[str] = None) -> Coprocessor:
        """Execute the script text; the (single) @copr function is the
        entry point (reference: parse.rs finds the decorated fn)."""
        import jax.numpy as jnp
        namespace = {"copr": copr, "coprocessor": coprocessor,
                     "np": np, "numpy": np, "jnp": jnp}
        try:
            exec(compile(script, name or "<script>", "exec"), namespace)
        except SyntaxError as e:
            raise InvalidArgumentsError(f"script syntax error: {e}") from e
        coprs = [v for v in namespace.values()
                 if isinstance(v, Coprocessor)]
        if not coprs:
            raise InvalidArgumentsError(
                "script defines no @copr/@coprocessor function")
        if name is not None and len(coprs) > 1:
            named = [c for c in coprs if c.name == name]
            if named:
                return named[0]
        return coprs[0]

    # ---- persistence (scripts system table) ----
    def _ensure_scripts_table(self, ctx: QueryContext):
        from .. import DEFAULT_CATALOG_NAME
        table = self.frontend.catalog.table(
            DEFAULT_CATALOG_NAME, ctx.current_schema, SCRIPTS_TABLE)
        if table is not None:
            return table
        self.frontend.do_query(
            f"CREATE TABLE IF NOT EXISTS {SCRIPTS_TABLE} ("
            "schema_name STRING, name STRING, script STRING,"
            " engine STRING, timestamp TIMESTAMP TIME INDEX,"
            " PRIMARY KEY(schema_name, name))", ctx)
        return self.frontend.catalog.table(
            DEFAULT_CATALOG_NAME, ctx.current_schema, SCRIPTS_TABLE)

    def insert_script(self, name: str, script: str,
                      ctx: Optional[QueryContext] = None) -> None:
        """Compile (validating) + persist + register as a SQL UDF."""
        ctx = ctx or QueryContext()
        compiled = self.compile(script, name)
        table = self._ensure_scripts_table(ctx)
        table.insert({
            "schema_name": [ctx.current_schema], "name": [name],
            "script": [script], "engine": ["python"],
            "timestamp": [int(time.time() * 1000)]})
        self._register(ctx.current_schema, name, compiled)

    def _register(self, schema_name: str, name: str,
                  compiled: Coprocessor) -> None:
        self._compiled[f"{schema_name}.{name}"] = compiled
        from ..query.functions import register_udf
        register_udf(name, _udf_adapter(compiled))

    def load_scripts(self, ctx: Optional[QueryContext] = None) -> int:
        """Recompile + re-register every persisted script (restart path;
        reference recompiles from the scripts table on access)."""
        ctx = ctx or QueryContext()
        from .. import DEFAULT_CATALOG_NAME
        table = self.frontend.catalog.table(
            DEFAULT_CATALOG_NAME, ctx.current_schema, SCRIPTS_TABLE)
        if table is None:
            return 0
        n = 0
        for batch in table.scan_batches(
                projection=["schema_name", "name", "script"]):
            for schema_name, name, script in batch.rows():
                try:
                    self._register(schema_name, name,
                                   self.compile(script, name))
                    n += 1
                except GreptimeError:
                    logger.exception("failed to recompile script %s", name)
        return n

    def get_script(self, name: str,
                   ctx: Optional[QueryContext] = None) -> Optional[str]:
        ctx = ctx or QueryContext()
        from .. import DEFAULT_CATALOG_NAME
        table = self.frontend.catalog.table(
            DEFAULT_CATALOG_NAME, ctx.current_schema, SCRIPTS_TABLE)
        if table is None:
            return None
        for batch in table.scan_batches(
                projection=["schema_name", "name", "script"]):
            for schema_name, nm, script in batch.rows():
                if nm == name and schema_name == ctx.current_schema:
                    return script
        return None

    # ---- execution ----
    def run(self, name_or_script: str, params: Optional[Dict] = None,
            ctx: Optional[QueryContext] = None,
            is_script_text: bool = False) -> Output:
        ctx = ctx or QueryContext()
        if is_script_text:
            compiled = self.compile(name_or_script)
        else:
            key = f"{ctx.current_schema}.{name_or_script}"
            compiled = self._compiled.get(key)
            if compiled is None:
                script = self.get_script(name_or_script, ctx)
                if script is None:
                    raise GreptimeError(
                        f"script {name_or_script!r} not found")
                compiled = self.compile(script, name_or_script)
                self._register(ctx.current_schema, name_or_script, compiled)
        return self._execute(compiled, params or {}, ctx)

    def _execute(self, compiled: Coprocessor, params: Dict,
                 ctx: QueryContext) -> Output:
        args: List = []
        if compiled.sql:
            outputs = self.frontend.do_query(compiled.sql, ctx)
            out = outputs[-1]
            if not out.is_batches or not out.batches:
                raise GreptimeError("coprocessor sql returned no rows")
            batch = RecordBatch.concat(out.batches)
            cols = batch.to_pydict()
            for arg in compiled.arg_names:
                if arg not in cols:
                    raise InvalidArgumentsError(
                        f"coprocessor arg {arg!r} not in sql result "
                        f"columns {sorted(cols)}")
                args.append(np.asarray(cols[arg]))
        else:
            for arg in compiled.arg_names:
                if arg not in params:
                    raise InvalidArgumentsError(
                        f"missing coprocessor param {arg!r}")
                v = params[arg]
                args.append(np.asarray(v) if isinstance(v, (list, tuple))
                            else v)
        result = compiled(*args)
        names = compiled.output_names()
        vectors = as_vectors(result, len(names))
        schema = Schema([ColumnSchema(n, _np_dtype(v))
                         for n, v in zip(names, vectors)])
        rb = RecordBatch.from_pydict(
            schema, {n: np.asarray(v).tolist()
                     for n, v in zip(names, vectors)})
        return Output.record_batches([rb], schema)


def _np_dtype(arr: np.ndarray):
    kind = np.asarray(arr).dtype.kind
    if kind == "b":
        return dt.BOOLEAN
    if kind == "i":
        return dt.INT64
    if kind == "u":
        return dt.UINT64
    if kind == "f":
        return dt.FLOAT64
    return dt.STRING


def _udf_adapter(compiled: Coprocessor):
    """Expose a coprocessor as a scalar SQL function: its args come from
    the call site instead of the bound sql (reference: engine.rs registers
    each coprocessor as a DataFusion UDF)."""
    def call(*arrays):
        out = as_vectors(compiled(*[np.asarray(a) for a in arrays]),
                         len(compiled.output_names()))
        return out[0]
    return call
