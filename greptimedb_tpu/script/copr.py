"""The @copr decorator and coprocessor call protocol.

Reference behavior: src/script/src/python/ffi_types/copr.rs:40-120 — a
coprocessor declares `args` (input column names, bound from `sql`'s
result or from caller-supplied params), `returns` (output column names),
and optionally `sql` (the query whose columns feed the args). The wrapped
function receives one vector per arg and returns one vector (or a tuple,
one per return name).
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..errors import InvalidArgumentsError


@dataclass
class Coprocessor:
    name: str
    fn: Callable
    arg_names: List[str] = field(default_factory=list)
    returns: List[str] = field(default_factory=list)
    sql: Optional[str] = None
    backend: str = "native"          # reference: rspy | pyo3; here native

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)

    def output_names(self) -> List[str]:
        if self.returns:
            return list(self.returns)
        return [self.name]


def copr(args: Sequence[str] = (), returns: Sequence[str] = (),
         sql: Optional[str] = None, name: Optional[str] = None):
    """Mark a function as a coprocessor:

        @copr(args=["cpu", "mem"], returns=["load"], sql="select * from m")
        def load(cpu, mem):
            return cpu + mem
    """
    def wrap(fn: Callable) -> Coprocessor:
        arg_names = list(args)
        if not arg_names:
            sig = inspect.signature(fn)
            arg_names = [p.name for p in sig.parameters.values()
                         if p.kind in (p.POSITIONAL_ONLY,
                                       p.POSITIONAL_OR_KEYWORD)]
        return Coprocessor(name=name or fn.__name__, fn=fn,
                           arg_names=arg_names, returns=list(returns),
                           sql=sql)
    return wrap


#: reference alias (both spellings exist in the reference decorator parser)
coprocessor = copr


def as_vectors(result, n_expected_cols: int) -> List[np.ndarray]:
    """Normalize a coprocessor's return value into output columns."""
    if isinstance(result, tuple):
        cols = list(result)
    else:
        cols = [result]
    if n_expected_cols and len(cols) != n_expected_cols:
        raise InvalidArgumentsError(
            f"coprocessor returned {len(cols)} columns, "
            f"declared {n_expected_cols} returns")
    out = []
    for c in cols:
        arr = np.asarray(c)
        if arr.ndim == 0:
            arr = arr.reshape(1)
        out.append(arr)
    lens = {len(a) for a in out}
    if len(lens) > 1:
        # scalars broadcast against vector outputs
        n = max(lens)
        out = [np.full(n, a[0]) if len(a) == 1 else a for a in out]
        if {len(a) for a in out} != {n}:
            raise InvalidArgumentsError(
                f"ragged coprocessor output lengths: {sorted(lens)}")
    return out
