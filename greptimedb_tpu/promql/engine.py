"""PromQL evaluation engine on the TPU window kernels.

Reference behavior: src/promql/src/planner.rs compiles PromQL to DataFusion
plans with custom streaming nodes (SeriesNormalize / SeriesDivide / Instant-
and RangeManipulate) plus per-window scalar UDFs (functions/*.rs); the
servers shape results to Prometheus JSON (src/servers/src/prom.rs:150-400).

TPU design (original): selectors materialize a dense padded [series, time]
matrix straight from the region scan cache (query/tpu_exec.py MergedScan —
sorted, MVCC-deduped, device-resident). Instant selection and every range
function are single vmapped device passes over an aligned step grid
(ops/window.py); label grouping, vector matching, and JSON shaping stay on
the host where cardinality is small. Steps outside the data span are
masked on host so rebased int32 device timestamps never overflow.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..datatypes import data_type as dt
from ..datatypes.record_batch import RecordBatch
from ..datatypes.schema import ColumnSchema, Schema, SemanticType
from ..errors import GreptimeError, TableNotFoundError, UnsupportedError
from ..query.output import Output
from ..session import QueryContext
from ..sql import ast as sqlast
from .ast import (
    Aggregate, Binary, Call, Matcher, NumberLiteral, PromExpr, StringLiteral,
    SubqueryExpr, Unary, VectorSelector,
)
from .parser import PromqlParseError, parse_duration_ms, parse_promql

DEFAULT_LOOKBACK_MS = 300_000           # Prometheus 5m lookback delta

_RANGE_FUNCS = {
    "rate", "increase", "delta", "idelta", "irate", "changes", "resets",
    "sum_over_time", "count_over_time", "avg_over_time", "min_over_time",
    "max_over_time", "stddev_over_time", "stdvar_over_time",
    "last_over_time", "first_over_time", "present_over_time",
    "quantile_over_time", "mad_over_time", "absent_over_time", "deriv",
    "predict_linear", "holt_winters",
}
# which drop the metric name from results (all except last_over_time)
_KEEP_NAME_RANGE_FUNCS = {"last_over_time"}


def _fetch_pair(v, ok):
    """One batched device fetch for a (values, ok) kernel result — two
    sequential np.asarray calls each pay a full device round trip."""
    import jax
    if hasattr(v, "addressable_shards") or hasattr(ok, "addressable_shards"):
        v, ok = jax.device_get((v, ok))
    return _from_device_f32(v), np.asarray(ok)


def _from_device_f32(v) -> np.ndarray:
    """Bring device results to host float64, honestly.

    The device path computes in float32 (TPU has no f64); a raw cast to
    float64 fabricates noise digits (f32 of 2.0/60 → 1.9999998807907104…).
    A single f32 carries ~7.2 significant decimal digits and the window/rate
    chains accumulate a few ulps, so quantize to 6 — emitted samples then
    read as the values they actually are at device precision (rate of a
    steady counter prints 2.0, not 1.9999998807907104)."""
    a = np.asarray(v)
    if a.dtype != np.float32:
        return np.asarray(a, dtype=np.float64)
    out = np.asarray(a, dtype=np.float64)
    finite = np.isfinite(out) & (out != 0.0)
    mag = np.floor(np.log10(np.abs(out, where=finite, out=np.ones_like(out))))
    dec = 5.0 - mag
    scale = np.power(10.0, dec, where=finite, out=np.ones_like(out))
    good = finite & np.isfinite(scale) & (scale != 0)
    return np.where(good, np.round(out * scale) / scale, out)

_SIMPLE_FUNCS = {
    "abs": np.abs, "ceil": np.ceil, "floor": np.floor, "exp": np.exp,
    "ln": np.log, "log2": np.log2, "log10": np.log10, "sqrt": np.sqrt,
    "sgn": np.sign, "acos": np.arccos, "asin": np.arcsin,
    "atan": np.arctan, "cos": np.cos, "sin": np.sin, "tan": np.tan,
    "cosh": np.cosh, "sinh": np.sinh, "tanh": np.tanh,
    "acosh": np.arccosh, "asinh": np.arcsinh, "atanh": np.arctanh,
    "rad": np.radians, "deg": np.degrees,
}

_CMP_NP = {"==": np.equal, "!=": np.not_equal, "<": np.less,
           "<=": np.less_equal, ">": np.greater, ">=": np.greater_equal}
_SET_OPS = {"and", "or", "unless"}
_ARITH_NP = {
    "+": np.add, "-": np.subtract, "*": np.multiply,
    # PromQL % is Go math.Mod (truncated toward zero) = C fmod
    "/": np.divide, "%": np.fmod, "^": np.power, "atan2": np.arctan2,
}


# ---------------------------------------------------------------------------
# value types
# ---------------------------------------------------------------------------

@dataclass
class ScalarVal:
    v: np.ndarray                       # [T] float64


@dataclass
class StringVal:
    v: str


@dataclass
class VectorVal:
    """Instant vector evaluated on the step grid."""
    labels: List[Dict[str, str]]        # per series
    values: np.ndarray                  # [S, T] float64
    ok: np.ndarray                      # [S, T] bool

    @property
    def num_series(self) -> int:
        return len(self.labels)

    def drop_name(self) -> "VectorVal":
        labels = [{k: v for k, v in l.items() if k != "__name__"}
                  for l in self.labels]
        return VectorVal(labels, self.values, self.ok)


@dataclass
class MatrixVal:
    """Raw range samples (top-level matrix selector in an instant query)."""
    labels: List[Dict[str, str]]
    sample_ts: List[np.ndarray]         # per series, ms
    sample_vals: List[np.ndarray]


# ---------------------------------------------------------------------------
# series selection
# ---------------------------------------------------------------------------

@dataclass
class _Selection:
    labels: List[Dict[str, str]]
    matrix: object                      # ops.window.SeriesMatrix or None
    data_min: int = 0
    data_max: int = -1

    @property
    def empty(self) -> bool:
        return self.matrix is None


def _compile_anchored(pattern: str) -> "re.Pattern":
    """Fully-anchored user regex; invalid patterns are a query error
    (Prometheus returns 400 bad_data), not a server crash."""
    try:
        return re.compile(f"^(?:{pattern})$")
    except re.error as e:
        raise PromqlParseError(f"invalid regex {pattern!r}: {e}") from e


def _matcher_keep(values: List[str], m: Matcher) -> np.ndarray:
    if m.op == "=":
        return np.asarray([v == m.value for v in values])
    if m.op == "!=":
        return np.asarray([v != m.value for v in values])
    rx = _compile_anchored(m.value)
    hit = np.asarray([bool(rx.match(v)) for v in values])
    return hit if m.op == "=~" else ~hit


class PromqlEngine:
    """Evaluates PromQL over catalog tables (metric name = table name,
    tags = labels, field column(s) = values)."""

    def __init__(self, catalog):
        self.catalog = catalog

    # ------------------------------------------------------------------
    # public entry points
    # ------------------------------------------------------------------
    def execute_tql(self, stmt: sqlast.Tql, ctx: QueryContext) -> Output:
        if stmt.kind not in ("eval", "evaluate", "explain", "analyze"):
            raise UnsupportedError(f"TQL {stmt.kind.upper()} not supported")
        start_ms = _parse_tql_time(stmt.start)
        end_ms = _parse_tql_time(stmt.end)
        step_ms = _parse_tql_duration(stmt.step)
        lookback = _parse_tql_duration(stmt.lookback) if stmt.lookback \
            else DEFAULT_LOOKBACK_MS
        expr = parse_promql(stmt.query)
        ev = _Eval(self, ctx, start_ms, end_ms, step_ms, lookback)
        if stmt.kind == "explain":
            return self._explain_output(expr, None, ev=ev)
        if stmt.kind == "analyze":
            import time as _time

            from ..common import exec_stats
            stats = exec_stats.ExecStats()
            t0 = _time.perf_counter()
            with exec_stats.collect(stats):
                val = ev.eval(expr)
            elapsed_ms = (_time.perf_counter() - t0) * 1e3
            nseries = len(getattr(val, "labels", [])) or 1
            return self._explain_output(expr, {
                "elapsed_ms": round(elapsed_ms, 2),
                "series": nseries, "steps": len(ev.steps),
                "stats": stats}, ev=ev)
        val = ev.eval(expr)
        return _to_record_batches(val, ev.steps)

    def explain_lines(self, query: str, start_ms: int, end_ms: int,
                      step_ms: int, ctx: Optional[QueryContext] = None,
                      lookback_ms: int = DEFAULT_LOOKBACK_MS) -> List[str]:
        """The plan/dispatch lines TQL EXPLAIN renders, as a list — the
        HTTP API's ?explain=1 surface (servers/prom_api)."""
        ctx = ctx or QueryContext()
        expr = parse_promql(query)
        ev = _Eval(self, ctx, start_ms, end_ms, step_ms, lookback_ms)
        return self._plan_lines(expr, ev)

    def _plan_lines(self, expr, ev: Optional["_Eval"]) -> List[str]:
        """The EXPLAIN text: the evaluation plan tree, one node per
        line, then the same dispatch stages SQL's EXPLAIN prints for
        the statement's lowered (or row-path) scan."""
        lines: List[str] = []

        def walk(e, depth):
            pad = "  " * depth
            name = type(e).__name__
            if isinstance(e, VectorSelector):
                sel = ", ".join(f"{m.name}{m.op}{m.value!r}"
                                for m in e.matchers)
                rng = f"[{e.range_ms}ms]" if getattr(e, "range_ms", None) \
                    else ""
                lines.append(f"{pad}PromSeriesScan: {e.metric}{rng}"
                             f" {{{sel}}}")
            elif isinstance(e, Call):
                lines.append(f"{pad}PromCall: {e.func}")
            elif isinstance(e, Aggregate):
                mod = ""
                if e.by:
                    mod = f" by ({', '.join(e.by)})"
                elif e.without:
                    mod = f" without ({', '.join(e.without)})"
                lines.append(f"{pad}PromAggregate: {e.op}{mod}")
            elif isinstance(e, Binary):
                lines.append(f"{pad}PromBinary: {e.op}")
            elif isinstance(e, NumberLiteral):
                lines.append(f"{pad}Literal: {e.value}")
            else:
                lines.append(f"{pad}{name}")
            for child in list(getattr(e, "args", []) or []):
                if isinstance(child, PromExpr):
                    walk(child, depth + 1)
            for attr in ("expr", "lhs", "rhs"):
                child = getattr(e, attr, None)
                if isinstance(child, PromExpr):
                    walk(child, depth + 1)

        walk(expr, 0)
        if ev is not None:
            from . import lowering
            lines.extend(lowering.explain_lines(ev, expr))
        return lines

    def _explain_output(self, expr, analyze: Optional[dict],
                        ev: Optional["_Eval"] = None) -> Output:
        """TQL EXPLAIN / ANALYZE (reference: tql_parser.rs parses all
        three verbs; EXPLAIN shows the plan the planner built)."""
        lines = self._plan_lines(expr, ev)
        rows = {"plan_type": ["logical_plan"], "plan": ["\n".join(lines)]}
        if analyze is not None:
            analyzed = (f"elapsed: {analyze['elapsed_ms']}ms, series: "
                        f"{analyze['series']}, steps: {analyze['steps']}")
            stats = analyze.get("stats")
            if stats is not None:
                # the executed dispatch + per-stage breakdown, same
                # collector SQL's EXPLAIN ANALYZE renders
                tbl = stats.rows_table()
                for st, rows_, ms, detail in zip(
                        tbl.get("stage", []), tbl.get("rows", []),
                        tbl.get("elapsed_ms", []),
                        tbl.get("detail", [])):
                    analyzed += (f"\n{st}: rows={rows_}, "
                                 f"elapsed: {ms}ms"
                                 f"{', ' + detail if detail else ''}")
            rows["plan_type"].append("analyze")
            rows["plan"].append(analyzed)
        schema = Schema([ColumnSchema("plan_type", dt.STRING),
                         ColumnSchema("plan", dt.STRING)])
        return Output.record_batches(
            [RecordBatch.from_pydict(schema, rows)], schema)

    def query_range(self, query: str, start_ms: int, end_ms: int,
                    step_ms: int, ctx: Optional[QueryContext] = None,
                    lookback_ms: int = DEFAULT_LOOKBACK_MS):
        ctx = ctx or QueryContext()
        expr = parse_promql(query)
        ev = _Eval(self, ctx, start_ms, end_ms, step_ms, lookback_ms)
        return ev.eval(expr), ev.steps

    def query_to_prom_json(self, query: str, start_ms: int, end_ms: int,
                           step_ms: int, ctx: Optional[QueryContext] = None,
                           *, instant: bool = False,
                           lookback_ms: int = DEFAULT_LOOKBACK_MS) -> dict:
        ctx = ctx or QueryContext()
        expr = parse_promql(query)
        if instant:
            end_ms = start_ms
            step_ms = max(step_ms, 1)
        ev = _Eval(self, ctx, start_ms, end_ms, step_ms, lookback_ms,
                   raw_matrix_ok=instant)
        val = ev.eval(expr)
        return _to_prom_json(val, ev.steps, instant=instant)

    # ------------------------------------------------------------------
    # data access
    # ------------------------------------------------------------------
    def select(self, sel: VectorSelector, lo_ms: int, hi_ms: int,
               ctx: QueryContext) -> _Selection:
        """Fetch samples for a selector in the closed window [lo_ms, hi_ms]
        as a dense SeriesMatrix sorted by time within each series.

        All data access lives in promql/lowering.py — the one module
        under promql/ sanctioned (greptlint GL14) to touch regions, the
        device scan cache and raw scan_batches."""
        from . import lowering
        return lowering.select_series(self, sel, lo_ms, hi_ms, ctx)


def _label_str(v) -> str:
    if v is None:
        return ""
    return str(v)


def _matches_empty(m: Matcher) -> bool:
    if m.op == "=":
        return m.value == ""
    if m.op == "!=":
        return m.value != ""
    rx = _compile_anchored(m.value)
    hit = bool(rx.match(""))
    return hit if m.op == "=~" else not hit


def _is_sorted(gids: np.ndarray, ts: np.ndarray) -> bool:
    if len(gids) < 2:
        return True
    g1, g0 = gids[1:], gids[:-1]
    return bool(np.all((g1 > g0) | ((g1 == g0) & (ts[1:] >= ts[:-1]))))


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------

class _Eval:
    def __init__(self, engine: PromqlEngine, ctx: QueryContext,
                 start_ms: int, end_ms: int, step_ms: int, lookback_ms: int,
                 raw_matrix_ok: bool = False):
        if step_ms <= 0:
            raise PromqlParseError("step must be positive")
        if end_ms < start_ms:
            raise PromqlParseError("end is before start")
        self.engine = engine
        self.ctx = ctx
        self.start = int(start_ms)
        self.end = int(end_ms)
        self.step = int(step_ms)
        self.lookback = int(lookback_ms)
        self.steps = np.arange(self.start, self.end + 1, self.step,
                               dtype=np.int64)
        self.nsteps = len(self.steps)
        self.raw_matrix_ok = raw_matrix_ok
        # per-evaluation device caches: matrices stay resident in HBM and
        # window bounds are shared across range functions over the same
        # selector (rate + avg_over_time recompute identical bounds
        # otherwise — the dominant cost at 10k-series scale)
        self._dev_cache: Dict[int, tuple] = {}
        self._bounds_cache: Dict[tuple, tuple] = {}

    # -- top-level dispatch --
    def eval(self, e: PromExpr):
        if isinstance(e, NumberLiteral):
            return ScalarVal(np.full(self.nsteps, e.value, dtype=np.float64))
        if isinstance(e, StringLiteral):
            return StringVal(e.value)
        if isinstance(e, VectorSelector):
            if e.range_ms:
                if self.raw_matrix_ok and self.nsteps == 1:
                    return self._raw_matrix(e)
                raise PromqlParseError(
                    "matrix selector must be wrapped in a range function")
            return self._instant(e)
        if isinstance(e, Unary):
            v = self.eval(e.expr)
            if isinstance(v, ScalarVal):
                return ScalarVal(-v.v)
            if isinstance(v, VectorVal):
                return VectorVal(v.drop_name().labels, -v.values, v.ok)
            raise UnsupportedError("unary minus on non-numeric")
        if isinstance(e, Call):
            return self._call(e)
        if isinstance(e, Aggregate):
            return self._aggregate(e)
        if isinstance(e, Binary):
            return self._binary(e)
        if isinstance(e, SubqueryExpr):
            raise UnsupportedError("subqueries are not supported yet")
        raise UnsupportedError(f"cannot evaluate {type(e).__name__}")

    # -- selector evaluation --
    def _grid(self, offset_ms: int, at_ms) -> np.ndarray:
        """Step ends adjusted for offset/@ (evaluation times)."""
        if at_ms is None:
            ends = self.steps - offset_ms
        elif at_ms == "start":
            ends = np.full(self.nsteps, self.start - offset_ms, np.int64)
        elif at_ms == "end":
            ends = np.full(self.nsteps, self.end - offset_ms, np.int64)
        else:
            ends = np.full(self.nsteps, int(at_ms) - offset_ms, np.int64)
        return ends

    def _window_eval(self, sel: VectorSelector, win_ms: int, kernel):
        """Shared instant/range evaluation: fetch, clip the step grid to the
        data span, run the device kernel on the in-range steps, mask the
        rest. kernel(matrix, t0_rel, nsteps) -> (vals [S,T'], ok [S,T'])."""
        ends = self._grid(sel.offset_ms, sel.at_ms)
        fixed = sel.at_ms is not None
        lo = int(ends.min()) - win_ms + 1
        hi = int(ends.max())
        selection = self.engine.select(sel, lo, hi, self.ctx)
        S = len(selection.labels)
        out_vals = np.full((S, self.nsteps), np.nan, dtype=np.float64)
        out_ok = np.zeros((S, self.nsteps), dtype=bool)
        if selection.empty or S == 0:
            return VectorVal(selection.labels, out_vals, out_ok)
        dmin, dmax = selection.data_min, selection.data_max

        if fixed:
            t = int(ends[0])
            if t < dmin or t - win_ms > dmax:
                return VectorVal(selection.labels, out_vals, out_ok)
            v, ok = kernel(selection.matrix, np.int64(t), 1)
            v, ok = _fetch_pair(v, ok)
            v = v[:, :1]
            ok = ok[:, :1]
            out_vals[:] = np.repeat(v, self.nsteps, axis=1)
            out_ok[:] = np.repeat(ok, self.nsteps, axis=1)
            return VectorVal(selection.labels, out_vals, out_ok)

        t0 = int(ends[0])
        # in-range steps: end >= dmin and end - win <= dmax
        j0 = max(0, -(-(dmin - t0) // self.step))
        j1 = min(self.nsteps - 1, (dmax + win_ms - t0) // self.step)
        if j0 > j1:
            return VectorVal(selection.labels, out_vals, out_ok)
        n_eval = j1 - j0 + 1
        n_pad = 1 << (n_eval - 1).bit_length() if n_eval > 1 else 1
        v, ok = kernel(selection.matrix, np.int64(t0 + j0 * self.step),
                       n_pad)
        v, ok = _fetch_pair(v, ok)
        v = v[:, :n_eval]
        ok = ok[:, :n_eval]
        out_vals[:, j0:j1 + 1] = v
        out_ok[:, j0:j1 + 1] = ok
        return VectorVal(selection.labels, out_vals, out_ok)

    def _device_args(self, matrix, t0: np.int64, nsteps: int):
        """Rebase (ts2d, t0) for int32 device transfer; arrays are
        device_put once per matrix and reused across range functions."""
        # the cache entry holds `matrix` itself: id() keys are only unique
        # while the object is alive, so pin it for the evaluation
        ent = self._dev_cache.get(id(matrix))
        if ent is None:
            import jax
            ts2d, val2d, lengths, base = matrix.device_arrays()
            if val2d.dtype == np.float64 and not jax.config.jax_enable_x64:
                val2d = val2d.astype(np.float32)
            if ts2d.dtype != np.int64:   # int64 stays host for the safety net
                ts2d = jax.device_put(ts2d)
                val2d = jax.device_put(val2d)
                lengths = jax.device_put(lengths)
            ent = (matrix, ts2d, val2d, lengths, base)
            self._dev_cache[id(matrix)] = ent
        _, ts2d, val2d, lengths, base = ent
        return ts2d, val2d, lengths, np.int64(t0) - base

    def _cached_bounds(self, matrix, ts2d, t0r, win: int, nsteps: int):
        """Window bounds shared across range functions on one selector."""
        from ..ops.window import compute_window_bounds
        key = (id(matrix), int(t0r), int(win), nsteps)
        ent = self._bounds_cache.get(key)
        if ent is None:
            b = compute_window_bounds(ts2d, t0r, step=self.step,
                                      range_ms=int(win), nsteps=nsteps)
            ent = (matrix, b)   # pin matrix: id() keys need it alive
            self._bounds_cache[key] = ent
        return ent[1]

    #: widest extended grid (nsteps + range/step) the aligned fast path may
    #: build — beyond this (wide-range instant queries like rate(x[1d]) at
    #: one step) the O(nsteps) two-pass bounds form is both faster and
    #: bounded in memory
    _ALIGNED_MAX_EXT = 4096

    def _aligned_ok(self, win: int, nsteps: int) -> bool:
        return (win % self.step == 0 and win >= 0 and
                win // self.step + nsteps <= self._ALIGNED_MAX_EXT)

    def _aligned_eval(self, matrix, ts2d, val2d, lengths, t0r, win: int,
                      nsteps: int):
        """AlignedWindowEval shared across range functions on one selector
        (step-aligned windows): one bounds pass + one stacked gather serve
        rate, avg_over_time, and the rest of the cumsum family."""
        from ..ops.window import AlignedWindowEval
        key = ("awe", id(matrix), int(t0r), int(win), nsteps)
        ent = self._bounds_cache.get(key)
        if ent is None:
            awe = AlignedWindowEval(ts2d, val2d, lengths, t0r, self.step,
                                    int(win), nsteps)
            ent = (matrix, awe)   # pin matrix: id() keys need it alive
            self._bounds_cache[key] = ent
        return ent[1]

    def _bounds_for(self, matrix, ts2d, val2d, lengths, t0r, win: int,
                    nsteps: int):
        """Window bounds for any kernel path (None when ts stays host
        int64 for the safety net)."""
        if ts2d.dtype == np.int64:
            return None
        if self._aligned_ok(win, nsteps):
            return self._aligned_eval(matrix, ts2d, val2d, lengths, t0r,
                                      win, nsteps).bounds()
        return self._cached_bounds(matrix, ts2d, t0r, win, nsteps)

    def _instant(self, sel: VectorSelector) -> VectorVal:
        from ..ops.window import instant_select

        def kernel(matrix, t0, nsteps):
            ts2d, val2d, lengths, t0r = self._device_args(matrix, t0, nsteps)
            return instant_select(ts2d, val2d, t0r, self.step, self.lookback,
                                  nsteps=nsteps)

        return self._window_eval(sel, self.lookback, kernel)

    def _range_func(self, func: str, sel: VectorSelector,
                    param: float = 0.0, param2: float = 0.0) -> VectorVal:
        from ..ops.window import (
            CUMSUM_OPS, GATHER_OPS, range_aggregate_cumsum,
            range_aggregate_gather)

        win = sel.range_ms
        if not win:
            raise PromqlParseError(f"{func} expects a range vector")
        op = func
        if func == "irate":
            op = "irate_num"            # reset-corrected idelta / sample gap
        if func == "absent_over_time":
            op = "count_over_time"

        def kernel(matrix, t0, nsteps):
            ts2d, val2d, lengths, t0r = self._device_args(matrix, t0, nsteps)
            if op in CUMSUM_OPS and ts2d.dtype != np.int64 \
                    and self._aligned_ok(win, nsteps):
                awe = self._aligned_eval(matrix, ts2d, val2d, lengths, t0r,
                                         win, nsteps)
                return awe.eval(op)
            bounds = self._bounds_for(matrix, ts2d, val2d, lengths, t0r,
                                      win, nsteps)
            if op in CUMSUM_OPS:
                return range_aggregate_cumsum(
                    ts2d, val2d, lengths, t0r, self.step, win,
                    op=op, nsteps=nsteps, param=param, bounds=bounds)
            if op in GATHER_OPS:
                maxw = int(matrix.max_len)
                return range_aggregate_gather(
                    ts2d, val2d, t0r, self.step, win, op=op, nsteps=nsteps,
                    maxw=max(maxw, 2), param=param, param2=param2,
                    bounds=bounds)
            raise UnsupportedError(f"range function {func} not implemented")

        out = self._window_eval(sel, win, kernel)
        if func == "irate":
            # irate = last difference / gap seconds; approximate gap from
            # idelta pair — recompute via two instant gathers host-side
            gap = self._range_func_gap(sel)
            with np.errstate(divide="ignore", invalid="ignore"):
                out = VectorVal(out.labels, out.values / gap.values,
                                out.ok & gap.ok & (gap.values > 0))
        if func not in _KEEP_NAME_RANGE_FUNCS:
            out = out.drop_name()
        if func == "absent_over_time":
            return self._absent_like(out, sel)
        return out

    def _range_func_gap(self, sel: VectorSelector) -> VectorVal:
        """Seconds between the last two samples in each window (for irate)."""
        from ..ops.window import range_aggregate_cumsum
        win = sel.range_ms

        def kernel(matrix, t0, nsteps):
            import jax
            ts2d, val2d, lengths, t0r = self._device_args(matrix, t0, nsteps)
            bounds = self._bounds_for(matrix, ts2d, val2d, lengths, t0r,
                                      win, nsteps)
            # idelta over *rebased* sample times: absolute epoch seconds
            # (~1.7e9) as float32 device values would cancel to 0 between
            # adjacent samples; a gap of relative seconds is exact
            rel = np.asarray(ts2d, dtype=np.float64) / 1000.0
            rel = np.where(np.asarray(matrix.ts) == _ts_pad(), 0.0, rel)
            return range_aggregate_cumsum(
                ts2d, jax.device_put(rel.astype(np.float32)
                                     if val2d.dtype == np.float32 else rel),
                lengths, t0r, self.step, win, op="idelta", nsteps=nsteps,
                bounds=bounds)

        return self._window_eval(sel, win, kernel)

    def _raw_matrix(self, sel: VectorSelector) -> MatrixVal:
        ends = self._grid(sel.offset_ms, sel.at_ms)
        t = int(ends[0])
        selection = self.engine.select(sel, t - sel.range_ms + 1, t,
                                       self.ctx)
        if selection.empty:
            return MatrixVal([], [], [])
        sm = selection.matrix
        labels, s_ts, s_vals = [], [], []
        for s in range(sm.num_series):
            L = int(sm.lengths[s])
            if L == 0:
                continue
            labels.append(selection.labels[s])
            s_ts.append(np.asarray(sm.ts[s, :L]))
            s_vals.append(np.asarray(sm.values[s, :L]))
        return MatrixVal(labels, s_ts, s_vals)

    # -- functions --
    def _call(self, e: Call):
        f = e.func
        if f in _RANGE_FUNCS:
            return self._eval_range_call(e)
        if f == "time":
            return ScalarVal(self.steps.astype(np.float64) / 1000.0)
        if f == "pi":
            return ScalarVal(np.full(self.nsteps, math.pi))
        if f == "scalar":
            v = self._vec_arg(e, 0)
            if v.num_series == 1:
                out = np.where(v.ok[0], v.values[0], np.nan)
            else:
                out = np.full(self.nsteps, np.nan)
            return ScalarVal(out.astype(np.float64))
        if f == "vector":
            s = self.eval(e.args[0])
            if not isinstance(s, ScalarVal):
                raise PromqlParseError("vector() expects a scalar")
            return VectorVal([{}], s.v[None, :].copy(),
                             np.ones((1, self.nsteps), dtype=bool))
        if f == "absent":
            arg = e.args[0] if e.args else None
            sel = arg if isinstance(arg, VectorSelector) else None
            return self._absent_like(self._vec_arg(e, 0), sel)
        if f == "timestamp":
            v = self._vec_arg(e, 0)
            arg = e.args[0]
            if isinstance(arg, VectorSelector) and not arg.range_ms:
                ts_v = self._instant_ts(arg)
                return VectorVal(v.drop_name().labels, ts_v.values, v.ok)
            # fall back: the step time where the sample is present
            tsec = np.broadcast_to(self.steps.astype(np.float64) / 1000.0,
                                   v.values.shape)
            return VectorVal(v.drop_name().labels, tsec.copy(), v.ok)
        if f in _SIMPLE_FUNCS:
            v = self._vec_arg(e, 0)
            with np.errstate(all="ignore"):
                out = _SIMPLE_FUNCS[f](v.values)
            return VectorVal(v.drop_name().labels, out, v.ok)
        if f == "round":
            v = self._vec_arg(e, 0)
            to = 1.0
            if len(e.args) > 1:
                s = self.eval(e.args[1])
                if not isinstance(s, ScalarVal):
                    raise PromqlParseError("round() nearest must be scalar")
                to = float(s.v[0])
            if to <= 0:
                raise PromqlParseError("round() nearest must be positive")
            out = np.floor(v.values / to + 0.5) * to
            return VectorVal(v.drop_name().labels, out, v.ok)
        if f in ("clamp", "clamp_min", "clamp_max"):
            v = self._vec_arg(e, 0)
            out = v.values.copy()
            with np.errstate(invalid="ignore"):
                if f == "clamp":
                    lo, hi = (self._scalar_arg(e, i) for i in (1, 2))
                    out = np.minimum(np.maximum(out, lo[None, :]),
                                     hi[None, :])
                elif f == "clamp_min":
                    out = np.maximum(out, self._scalar_arg(e, 1)[None, :])
                else:
                    out = np.minimum(out, self._scalar_arg(e, 1)[None, :])
            return VectorVal(v.drop_name().labels, out, v.ok)
        if f in ("sort", "sort_desc"):
            v = self._vec_arg(e, 0)
            lastcol = v.values[:, -1] if v.values.size else \
                np.zeros(v.num_series)
            key = np.where(v.ok[:, -1] if v.ok.size else False,
                           lastcol, -np.inf if f == "sort" else np.inf)
            order = np.argsort(-key if f == "sort_desc" else key,
                               kind="stable")
            return VectorVal([v.labels[i] for i in order],
                             v.values[order], v.ok[order])
        if f == "histogram_quantile":
            phi = self._scalar_arg(e, 0)
            v = self._vec_arg(e, 1)
            return self._histogram_quantile(phi, v)
        if f == "label_replace":
            return self._label_replace(e)
        if f == "label_join":
            return self._label_join(e)
        if f in ("minute", "hour", "day_of_week", "day_of_month",
                 "day_of_year", "days_in_month", "month", "year"):
            return self._time_component(e, f)
        raise UnsupportedError(f"function {f} is not supported")

    def _eval_range_call(self, e: Call):
        f = e.func
        param = param2 = 0.0
        if f == "quantile_over_time":
            if len(e.args) != 2:
                raise PromqlParseError(f"{f} expects (q, range-vector)")
            param = float(self._scalar_arg(e, 0)[0])
            sel = e.args[1]
        elif f == "predict_linear":
            if len(e.args) != 2:
                raise PromqlParseError(f"{f} expects (range-vector, t)")
            sel = e.args[0]
            param = float(self._scalar_arg(e, 1)[0])
        elif f == "holt_winters":
            if len(e.args) != 3:
                raise PromqlParseError(f"{f} expects (range-vector, sf, tf)")
            sel = e.args[0]
            param = float(self._scalar_arg(e, 1)[0])
            param2 = float(self._scalar_arg(e, 2)[0])
        else:
            if len(e.args) != 1:
                raise PromqlParseError(f"{f} expects one range vector")
            sel = e.args[0]
        if not isinstance(sel, VectorSelector) or not sel.range_ms:
            raise PromqlParseError(f"{f} expects a matrix selector argument")
        return self._range_func(f, sel, param, param2)

    def _vec_arg(self, e: Call, i: int) -> VectorVal:
        if i >= len(e.args):
            raise PromqlParseError(f"{e.func} missing argument {i}")
        v = self.eval(e.args[i])
        if not isinstance(v, VectorVal):
            raise PromqlParseError(
                f"{e.func} argument {i} must be an instant vector")
        return v

    def _scalar_arg(self, e: Call, i: int) -> np.ndarray:
        v = self.eval(e.args[i])
        if not isinstance(v, ScalarVal):
            raise PromqlParseError(f"{e.func} argument {i} must be scalar")
        return v.v

    def _absent_like(self, v: VectorVal,
                     sel: Optional[VectorSelector] = None) -> VectorVal:
        present = v.ok.any(axis=0) if v.num_series else \
            np.zeros(self.nsteps, dtype=bool)
        vals = np.ones((1, self.nsteps), dtype=np.float64)
        # prometheus derives the result labels from the selector's equality
        # matchers (absent(up{job="api"}) -> {job="api"})
        labels: Dict[str, str] = {}
        if sel is not None:
            for m in sel.matchers:
                if m.op == "=" and m.name != "__name__":
                    labels[m.name] = m.value
        return VectorVal([labels], vals, ~present[None, :])

    def _instant_ts(self, sel: VectorSelector) -> VectorVal:
        """Instant select over the sample timestamps (seconds)."""
        from ..ops.window import instant_select
        import jax
        base_holder = {}

        def kernel(matrix, t0, nsteps):
            ts2d, val2d, lengths, t0r = self._device_args(matrix, t0, nsteps)
            # relative seconds on device (absolute epoch seconds lose up to
            # ~128s as float32); the base is added back on host below
            _, _, _, base = matrix.device_arrays()
            base_holder["base"] = base
            rel = np.asarray(ts2d, dtype=np.float64) / 1000.0
            rel = np.where(np.asarray(matrix.ts) == _ts_pad(), 0.0, rel)
            return instant_select(ts2d,
                                  jax.device_put(rel.astype(np.float32)
                                                 if val2d.dtype == np.float32
                                                 else rel),
                                  t0r, self.step, self.lookback,
                                  nsteps=nsteps)

        out = self._window_eval(sel, self.lookback, kernel)
        base_sec = base_holder.get("base", 0) / 1000.0
        return VectorVal(out.labels, out.values + base_sec, out.ok)

    def _time_component(self, e: Call, f: str) -> VectorVal:
        import pandas as pd
        if e.args:
            v = self._vec_arg(e, 0)
            secs = v.values
            labels, ok = v.drop_name().labels, v.ok
        else:
            secs = (self.steps.astype(np.float64) / 1000.0)[None, :]
            labels = [{}]
            ok = np.ones_like(secs, dtype=bool)
        flat = pd.to_datetime((secs * 1000).ravel(), unit="ms", utc=True)
        comp = {
            "minute": flat.minute, "hour": flat.hour,
            "day_of_week": flat.dayofweek, "day_of_month": flat.day,
            "day_of_year": flat.dayofyear, "days_in_month": flat.daysinmonth,
            "month": flat.month, "year": flat.year,
        }[f]
        out = np.asarray(comp, dtype=np.float64).reshape(secs.shape)
        if f == "day_of_week":
            out = (out + 1) % 7        # prometheus: Sunday = 0
        return VectorVal(labels, out, ok)

    def _histogram_quantile(self, phi: np.ndarray, v: VectorVal) -> VectorVal:
        groups: Dict[tuple, List[Tuple[float, int]]] = {}
        glabels: Dict[tuple, Dict[str, str]] = {}
        for i, lbl in enumerate(v.labels):
            le = lbl.get("le")
            if le is None:
                continue
            try:
                bound = float("inf") if le in ("+Inf", "Inf", "inf") \
                    else float(le)
            except ValueError:
                continue
            key = tuple(sorted((k, val) for k, val in lbl.items()
                               if k not in ("le", "__name__")))
            groups.setdefault(key, []).append((bound, i))
            glabels[key] = {k: val for k, val in lbl.items()
                            if k not in ("le", "__name__")}
        labels, rows, oks = [], [], []
        T = self.nsteps
        for key, buckets in groups.items():
            buckets.sort()
            bounds = np.asarray([b for b, _ in buckets])
            idx = [i for _, i in buckets]
            counts = v.values[idx]                     # [B, T] cumulative
            bok = v.ok[idx]
            counts = np.where(bok, counts, 0.0)
            counts = np.maximum.accumulate(counts, axis=0)  # enforce monotone
            total = counts[-1]
            # prometheus requires >= 2 buckets with an +Inf upper bound
            if len(bounds) < 2 or not math.isinf(bounds[-1]):
                ok = np.zeros(T, dtype=bool)
            else:
                ok = bok.any(axis=0) & (total > 0)
            rank = np.clip(phi, 0.0, 1.0) * total
            b = np.argmax(counts >= rank[None, :], axis=0)  # first >= rank
            b = np.clip(b, 0, len(bounds) - 1)
            hi = bounds[b]
            lo = np.where(b > 0, bounds[np.maximum(b - 1, 0)], 0.0)
            c_hi = np.take_along_axis(counts, b[None, :], axis=0)[0]
            c_lo = np.where(b > 0,
                            np.take_along_axis(counts,
                                               np.maximum(b - 1, 0)[None, :],
                                               axis=0)[0], 0.0)
            # highest bucket (+Inf): return lower bound of it
            inf_b = np.isinf(hi)
            with np.errstate(divide="ignore", invalid="ignore"):
                frac = np.where(c_hi > c_lo, (rank - c_lo) / (c_hi - c_lo),
                                0.0)
                res = lo + (hi - lo) * frac
            res = np.where(inf_b, lo, res)
            res = np.where(np.isnan(phi) | (phi < 0), -np.inf,
                           np.where(phi > 1, np.inf, res))
            labels.append(glabels[key])
            rows.append(res)
            oks.append(ok)
        if not labels:
            return VectorVal([], np.zeros((0, T)), np.zeros((0, T), bool))
        return VectorVal(labels, np.asarray(rows), np.asarray(oks))

    def _label_replace(self, e: Call) -> VectorVal:
        if len(e.args) != 5:
            raise PromqlParseError(
                "label_replace expects (v, dst, repl, src, regex)")
        v = self._vec_arg(e, 0)
        dst, repl, src, regex = (self._str_arg(e, i) for i in (1, 2, 3, 4))
        rx = _compile_anchored(regex)
        labels = []
        for lbl in v.labels:
            cur = dict(lbl)
            m = rx.match(cur.get(src, ""))
            if m:
                val = m.expand(_go_template_to_py(repl))
                if val:
                    cur[dst] = val
                else:
                    cur.pop(dst, None)
            labels.append(cur)
        return VectorVal(labels, v.values, v.ok)

    def _label_join(self, e: Call) -> VectorVal:
        if len(e.args) < 3:
            raise PromqlParseError(
                "label_join expects (v, dst, sep, src...)")
        v = self._vec_arg(e, 0)
        dst = self._str_arg(e, 1)
        sep = self._str_arg(e, 2)
        srcs = [self._str_arg(e, i) for i in range(3, len(e.args))]
        labels = []
        for lbl in v.labels:
            cur = dict(lbl)
            val = sep.join(cur.get(s, "") for s in srcs)
            if val:
                cur[dst] = val
            else:
                cur.pop(dst, None)
            labels.append(cur)
        return VectorVal(labels, v.values, v.ok)

    def _str_arg(self, e: Call, i: int) -> str:
        v = self.eval(e.args[i])
        if not isinstance(v, StringVal):
            raise PromqlParseError(f"{e.func} argument {i} must be a string")
        return v.v

    # -- aggregation --
    def _aggregate(self, e: Aggregate):
        # lowered fast path: aggregate-over-selector shapes rebuild the
        # inner instant vector from the plan IR's moment fold (per-group
        # frames instead of raw samples); anything the lowering declines
        # — or that the executor degrades (cost-based raw-pull, version
        # skew, sketch decode) — evaluates on the proven row path
        from . import lowering
        v = lowering.try_lowered_inner(self, e)
        if v is None:
            v = self.eval(e.expr)
        if not isinstance(v, VectorVal):
            raise PromqlParseError(f"{e.op} expects an instant vector")
        param = None
        if e.param is not None:
            p = self.eval(e.param)
            if isinstance(p, ScalarVal):
                param = p.v
            elif isinstance(p, StringVal):
                param = p.v
        T = self.nsteps

        # group key per series
        def key_of(lbl: Dict[str, str]) -> tuple:
            if e.by is not None:
                return tuple((k, lbl.get(k, "")) for k in sorted(e.by))
            if e.without is None:
                return ()              # no modifier: one group, no labels
            drop = set(e.without) | {"__name__"}
            return tuple(sorted((k, val) for k, val in lbl.items()
                                if k not in drop))

        if e.op in ("topk", "bottomk"):
            if param is None:
                raise PromqlParseError(f"{e.op} needs a scalar parameter")
            k = int(param[0])
            groups: Dict[tuple, List[int]] = {}
            for i, lbl in enumerate(v.labels):
                groups.setdefault(key_of(lbl), []).append(i)
            ok = np.zeros_like(v.ok)
            sign = -1.0 if e.op == "topk" else 1.0
            for idxs in groups.values():
                vals = v.values[idxs]
                gok = v.ok[idxs]
                rank_vals = np.where(gok, sign * vals, np.inf)
                order = np.argsort(rank_vals, axis=0, kind="stable")
                ranks = np.empty_like(order)
                np.put_along_axis(ranks, order,
                                  np.arange(len(idxs))[:, None] *
                                  np.ones_like(order), axis=0)
                keep = (ranks < k) & gok
                for r, i in enumerate(idxs):
                    ok[i] = keep[r]
            return VectorVal(v.labels, v.values, ok)

        if e.op == "count_values":
            if not isinstance(param, str):
                raise PromqlParseError("count_values needs a label name")
            out: Dict[tuple, Tuple[Dict[str, str], np.ndarray]] = {}
            for i, lbl in enumerate(v.labels):
                base_key = key_of(lbl)
                for t in range(T):
                    if not v.ok[i, t]:
                        continue
                    vs = _fmt_float(v.values[i, t])
                    key = base_key + ((param, vs),)
                    if key not in out:
                        glbl = dict(base_key)
                        glbl[param] = vs
                        out[key] = (glbl, np.zeros(T))
                    out[key][1][t] += 1
            if not out:
                return VectorVal([], np.zeros((0, T)),
                                 np.zeros((0, T), bool))
            labels = [lv[0] for lv in out.values()]
            vals = np.asarray([lv[1] for lv in out.values()])
            return VectorVal(labels, vals, vals > 0)

        groups: Dict[tuple, List[int]] = {}
        for i, lbl in enumerate(v.labels):
            groups.setdefault(key_of(lbl), []).append(i)
        labels, rows, oks = [], [], []
        for key, idxs in groups.items():
            vals = v.values[idxs]
            gok = v.ok[idxs]
            cnt = gok.sum(axis=0)
            any_ok = cnt > 0
            z = np.where(gok, vals, 0.0)
            with np.errstate(all="ignore"):
                if e.op == "sum":
                    r = z.sum(axis=0)
                elif e.op == "count":
                    r = cnt.astype(np.float64)
                elif e.op == "group":
                    r = np.ones(T)
                elif e.op == "avg":
                    r = z.sum(axis=0) / np.maximum(cnt, 1)
                elif e.op == "min":
                    r = np.where(gok, vals, np.inf).min(axis=0)
                elif e.op == "max":
                    r = np.where(gok, vals, -np.inf).max(axis=0)
                elif e.op in ("stddev", "stdvar"):
                    n = np.maximum(cnt, 1)
                    mean = z.sum(axis=0) / n
                    var = (np.where(gok, (vals - mean[None, :]) ** 2, 0.0)
                           .sum(axis=0)) / n
                    r = var if e.op == "stdvar" else np.sqrt(var)
                elif e.op == "quantile":
                    if param is None:
                        raise PromqlParseError("quantile needs a parameter")
                    r = _masked_quantile_np(vals, gok, float(param[0]))
                else:
                    raise UnsupportedError(f"aggregate {e.op}")
            labels.append({k: v for k, v in key if v != ""})
            rows.append(r)
            oks.append(any_ok)
        if not labels:
            return VectorVal([], np.zeros((0, T)), np.zeros((0, T), bool))
        return VectorVal(labels, np.asarray(rows, dtype=np.float64),
                         np.asarray(oks))

    # -- binary operators --
    def _binary(self, e: Binary):
        lhs = self.eval(e.lhs)
        rhs = self.eval(e.rhs)
        op = e.op

        if isinstance(lhs, ScalarVal) and isinstance(rhs, ScalarVal):
            if op in _SET_OPS:
                raise PromqlParseError(f"{op} not defined between scalars")
            with np.errstate(all="ignore"):
                if op in _CMP_NP:
                    if not e.return_bool:
                        raise PromqlParseError(
                            "comparisons between scalars must use bool")
                    return ScalarVal(
                        _CMP_NP[op](lhs.v, rhs.v).astype(np.float64))
                return ScalarVal(_ARITH_NP[op](lhs.v, rhs.v))

        if op in _SET_OPS:
            if not (isinstance(lhs, VectorVal) and isinstance(rhs, VectorVal)):
                raise PromqlParseError(f"{op} requires vector operands")
            return self._set_op(op, lhs, rhs, e.matching)

        if isinstance(lhs, VectorVal) and isinstance(rhs, ScalarVal):
            return self._vec_scalar(op, lhs, rhs.v, e.return_bool,
                                    scalar_on_left=False)
        if isinstance(lhs, ScalarVal) and isinstance(rhs, VectorVal):
            return self._vec_scalar(op, rhs, lhs.v, e.return_bool,
                                    scalar_on_left=True)
        if isinstance(lhs, VectorVal) and isinstance(rhs, VectorVal):
            return self._vec_vec(e, lhs, rhs)
        raise PromqlParseError(f"invalid operands for {op}")

    def _vec_scalar(self, op, v: VectorVal, s: np.ndarray, ret_bool: bool,
                    scalar_on_left: bool) -> VectorVal:
        with np.errstate(all="ignore"):
            if op in _CMP_NP:
                a, b = (s[None, :], v.values) if scalar_on_left else \
                    (v.values, s[None, :])
                cond = _CMP_NP[op](a, b)
                if ret_bool:
                    return VectorVal(v.drop_name().labels,
                                     cond.astype(np.float64), v.ok.copy())
                return VectorVal(v.labels, v.values, v.ok & cond)
            a, b = (s[None, :], v.values) if scalar_on_left else \
                (v.values, s[None, :])
            out = _ARITH_NP[op](a, b)
        return VectorVal(v.drop_name().labels, out, v.ok.copy())

    def _sig(self, lbl: Dict[str, str], matching) -> tuple:
        if matching is not None and matching.on is not None:
            return tuple((k, lbl.get(k, "")) for k in sorted(matching.on))
        drop = {"__name__"}
        if matching is not None and matching.ignoring:
            drop |= set(matching.ignoring)
        return tuple(sorted((k, v) for k, v in lbl.items() if k not in drop))

    def _set_op(self, op, lhs: VectorVal, rhs: VectorVal,
                matching) -> VectorVal:
        T = self.nsteps
        rsigs: Dict[tuple, np.ndarray] = {}
        for i, lbl in enumerate(rhs.labels):
            s = self._sig(lbl, matching)
            rsigs[s] = rsigs.get(s, np.zeros(T, dtype=bool)) | rhs.ok[i]
        if op == "and":
            ok = np.zeros_like(lhs.ok)
            for i, lbl in enumerate(lhs.labels):
                have = rsigs.get(self._sig(lbl, matching))
                if have is not None:
                    ok[i] = lhs.ok[i] & have
            return VectorVal(lhs.labels, lhs.values, ok)
        if op == "unless":
            ok = lhs.ok.copy()
            for i, lbl in enumerate(lhs.labels):
                have = rsigs.get(self._sig(lbl, matching))
                if have is not None:
                    ok[i] = lhs.ok[i] & ~have
            return VectorVal(lhs.labels, lhs.values, ok)
        # or
        lsigs: Dict[tuple, np.ndarray] = {}
        for i, lbl in enumerate(lhs.labels):
            s = self._sig(lbl, matching)
            lsigs[s] = lsigs.get(s, np.zeros(T, dtype=bool)) | lhs.ok[i]
        labels = list(lhs.labels)
        values = [lhs.values]
        oks = [lhs.ok]
        radd_ok = np.zeros_like(rhs.ok)
        for i, lbl in enumerate(rhs.labels):
            have = lsigs.get(self._sig(lbl, matching))
            radd_ok[i] = rhs.ok[i] & ~(have if have is not None
                                       else np.zeros(T, dtype=bool))
        keep = radd_ok.any(axis=1)
        for i in np.nonzero(keep)[0]:
            labels.append(rhs.labels[i])
        values.append(rhs.values[keep])
        oks.append(radd_ok[keep])
        return VectorVal(labels, np.concatenate(values, axis=0),
                         np.concatenate(oks, axis=0))

    def _vec_vec(self, e: Binary, lhs: VectorVal, rhs: VectorVal
                 ) -> VectorVal:
        """Vector/vector binary with label matching. The "many" side drives
        iteration (lhs unless group_right); the "one" side must have unique
        signatures. The operator is always applied in (lhs, rhs) order."""
        op = e.op
        m = e.matching
        group_left = bool(m and m.group_left)
        group_right = bool(m and m.group_right)
        many, one = (rhs, lhs) if group_right else (lhs, rhs)

        one_side: Dict[tuple, int] = {}
        for i, lbl in enumerate(one.labels):
            s = self._sig(lbl, m)
            if s in one_side:
                side = "left" if group_right else "right"
                raise GreptimeError(
                    "many-to-many matching not allowed: duplicate series on "
                    f"the {side} side")
            one_side[s] = i

        labels, vals, oks = [], [], []
        seen_result: Dict[tuple, int] = {}
        for i, lbl in enumerate(many.labels):
            j = one_side.get(self._sig(lbl, m))
            if j is None:
                continue
            if group_right:
                lv, rv = one.values[j], many.values[i]
                lok, rok = one.ok[j], many.ok[i]
            else:
                lv, rv = many.values[i], one.values[j]
                lok, rok = many.ok[i], one.ok[j]
            filter_keep = many.values[i]   # filter comparisons keep the
            with np.errstate(all="ignore"):  # many-side sample values
                if op in _CMP_NP:
                    cond = _CMP_NP[op](lv, rv)
                    if e.return_bool:
                        out = cond.astype(np.float64)
                        ok = lok & rok
                        rl = {k: v for k, v in lbl.items()
                              if k != "__name__"}
                    else:
                        out = filter_keep
                        ok = lok & rok & cond
                        rl = dict(lbl)
                else:
                    out = _ARITH_NP[op](lv, rv)
                    ok = lok & rok
                    rl = {k: v for k, v in lbl.items() if k != "__name__"}
            if m and m.include:
                for k in m.include:
                    inc = one.labels[j].get(k)
                    if inc is not None:
                        rl[k] = inc
                    else:
                        rl.pop(k, None)
            if not (group_left or group_right):
                # one-to-one: result labels are the match signature
                if not (op in _CMP_NP and not e.return_bool):
                    rl = dict(self._sig(lbl, m))
                rkey = tuple(sorted(rl.items()))
                if rkey in seen_result:
                    raise GreptimeError(
                        "multiple matches for labels: many-to-one matching "
                        "must use group_left/group_right")
                seen_result[rkey] = i
            labels.append(rl)
            vals.append(out)
            oks.append(ok)
        T = self.nsteps
        if not labels:
            return VectorVal([], np.zeros((0, T)), np.zeros((0, T), bool))
        return VectorVal(labels, np.asarray(vals), np.asarray(oks))


def _ts_pad():
    from ..ops.window import TS_PAD
    return TS_PAD


def _masked_quantile_np(vals: np.ndarray, ok: np.ndarray, q: float
                        ) -> np.ndarray:
    big = np.where(ok, vals, np.inf)
    sv = np.sort(big, axis=0)
    n = ok.sum(axis=0)
    if math.isnan(q) or q < 0:
        return np.full(vals.shape[1], -np.inf)
    if q > 1:
        return np.full(vals.shape[1], np.inf)
    pos = q * np.maximum(n - 1, 0)
    lo = np.floor(pos).astype(int)
    hi = np.minimum(lo + 1, np.maximum(n - 1, 0))
    frac = pos - lo
    idx = np.arange(vals.shape[1])
    lo_v = sv[np.clip(lo, 0, sv.shape[0] - 1), idx]
    hi_v = sv[np.clip(hi, 0, sv.shape[0] - 1), idx]
    return lo_v + (hi_v - lo_v) * frac


def _go_template_to_py(repl: str) -> str:
    """Convert Go regexp replacement ($1, ${name}) to Python (\\1, \\g<name>)."""
    out = re.sub(r"\$\{(\w+)\}", r"\\g<\1>", repl)
    out = re.sub(r"\$(\d+)", r"\\\1", out)
    out = re.sub(r"\$(\w+)", r"\\g<\1>", out)
    return out


# ---------------------------------------------------------------------------
# result shaping
# ---------------------------------------------------------------------------

def _fmt_float(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e17:
        return str(int(v))
    return repr(float(v))


def _to_prom_json(val, steps: np.ndarray, *, instant: bool) -> dict:
    tsec = steps.astype(np.float64) / 1000.0
    if isinstance(val, StringVal):
        return {"resultType": "string",
                "result": [tsec[-1], val.v]}
    if isinstance(val, ScalarVal):
        if instant:
            return {"resultType": "scalar",
                    "result": [tsec[-1], _fmt_float(float(val.v[-1]))]}
        return {"resultType": "matrix", "result": [{
            "metric": {},
            "values": [[t, _fmt_float(float(v))]
                       for t, v in zip(tsec, val.v)],
        }]}
    if isinstance(val, MatrixVal):
        return {"resultType": "matrix", "result": [{
            "metric": lbl,
            "values": [[ts / 1000.0, _fmt_float(float(v))]
                       for ts, v in zip(sts, svs)],
        } for lbl, sts, svs in zip(val.labels, val.sample_ts,
                                   val.sample_vals)]}
    assert isinstance(val, VectorVal)
    if instant:
        result = []
        for i, lbl in enumerate(val.labels):
            if not val.ok[i, -1]:
                continue
            result.append({"metric": lbl,
                           "value": [tsec[-1],
                                     _fmt_float(float(val.values[i, -1]))]})
        return {"resultType": "vector", "result": result}
    result = []
    for i, lbl in enumerate(val.labels):
        oksteps = np.nonzero(val.ok[i])[0]
        if len(oksteps) == 0:
            continue
        result.append({
            "metric": lbl,
            "values": [[tsec[j], _fmt_float(float(val.values[i, j]))]
                       for j in oksteps],
        })
    return {"resultType": "matrix", "result": result}


def _to_record_batches(val, steps: np.ndarray) -> Output:
    """Shape an evaluation result as record batches for TQL EVAL (the
    reference returns tags + ts + value columns)."""
    if isinstance(val, ScalarVal):
        schema = Schema([
            ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                         semantic_type=SemanticType.TIMESTAMP),
            ColumnSchema("value", dt.FLOAT64),
        ])
        rb = RecordBatch.from_pydict(schema, {
            "ts": steps.tolist(), "value": val.v.tolist()})
        return Output.record_batches([rb])
    if isinstance(val, MatrixVal):
        label_keys = sorted({k for lbl in val.labels for k in lbl})
        cols: Dict[str, list] = {k: [] for k in label_keys}
        ts_out, v_out = [], []
        for lbl, sts, svs in zip(val.labels, val.sample_ts, val.sample_vals):
            for t, v in zip(sts, svs):
                for k in label_keys:
                    cols[k].append(lbl.get(k, ""))
                ts_out.append(int(t))
                v_out.append(float(v))
        schema = Schema(
            [ColumnSchema(k, dt.STRING) for k in label_keys] +
            [ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                          semantic_type=SemanticType.TIMESTAMP),
             ColumnSchema("value", dt.FLOAT64)])
        data = dict(cols)
        data["ts"] = ts_out
        data["value"] = v_out
        return Output.record_batches([RecordBatch.from_pydict(schema, data)])
    if not isinstance(val, VectorVal):
        raise UnsupportedError("TQL result must be a vector or scalar")
    label_keys = sorted({k for lbl in val.labels for k in lbl})
    cols: Dict[str, list] = {k: [] for k in label_keys}
    ts_out, v_out = [], []
    for i, lbl in enumerate(val.labels):
        for j in np.nonzero(val.ok[i])[0]:
            for k in label_keys:
                cols[k].append(lbl.get(k, ""))
            ts_out.append(int(steps[j]))
            v_out.append(float(val.values[i, j]))
    schema = Schema(
        [ColumnSchema(k, dt.STRING) for k in label_keys] +
        [ColumnSchema("ts", dt.TIMESTAMP_MILLISECOND, nullable=False,
                      semantic_type=SemanticType.TIMESTAMP),
         ColumnSchema("value", dt.FLOAT64)])
    data = dict(cols)
    data["ts"] = ts_out
    data["value"] = v_out
    return Output.record_batches([RecordBatch.from_pydict(schema, data)])


# TQL (start, end, step) share the Prometheus API parameter grammar
from ..common.time import parse_prom_duration as _parse_tql_duration  # noqa: E402
from ..common.time import parse_prom_time as _parse_tql_time  # noqa: E402
