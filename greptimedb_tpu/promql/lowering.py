"""PromQL → plan-IR lowering, plus the engine's sanctioned data access.

Reference behavior: src/promql/src/planner.rs lowers PromQL into the
same DataFusion LogicalPlan SQL uses, so PromQL range queries ride
every pushdown the SQL optimizer knows. This module is the equivalent
seam for the TPU build: aggregate-over-selector shapes lower into the
shared plan IR (query/ir.py) and execute through the ONE aggregate
executor — cost-based scatter on DistTables, resident / streamed-cold /
indexed-point dispatch on local tables — while every non-lowerable
shape keeps the proven row path behind the same selector, fed by an IR
`RawScan` that still gets region pruning and wire filter pushdown.

This is also the ONLY module under promql/ allowed to touch region
internals (`table.regions`, the device scan cache, raw `scan_batches`)
— greptlint GL14 flags such access anywhere else, so every byte the
PromQL engine reads flows through the IR's two leaves.

Lowered shapes (everything else → row path):

  agg(selector)                 agg ∈ sum/avg/min/max/count [by/without]
  agg(fn(selector[R]))          fn ∈ rate/increase/delta/
                                sum|count|avg|min|max|last_over_time,
                                and the window tumbles (R == step)

with plain equality/inequality matchers on string tags, a single
numeric field, no @, and any offset. The inner selector/function is
rebuilt as a per-series instant vector from the finalized moment frame
(counter resets ride the `reset_corr` moment; extrapolation replicates
ops/window.py exactly), then the engine's ordinary host grouping
aggregates it — outer semantics are shared with the row path by
construction.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..errors import UnsupportedError
from ..sql.ast import BinaryOp, Column, IsNull, Literal
from .ast import Aggregate, Call, PromExpr, VectorSelector

#: outer aggregates whose inner vector we lower (topk/quantile/
#: count_values keep the row path: they need per-sample semantics the
#: moment frame cannot carry for arbitrary params)
LOWERABLE_AGG_OPS = frozenset({"sum", "avg", "min", "max", "count"})

#: range functions with an exact moment decomposition over one
#: tumbling window (range == step): value and ok-mask reconstruct
#: from first/last/min_ts/max_ts/count (+ reset_corr for counters)
LOWERABLE_RANGE_FUNCS = frozenset({
    "rate", "increase", "delta", "sum_over_time", "count_over_time",
    "avg_over_time", "min_over_time", "max_over_time", "last_over_time",
})

#: sentinel: the matchers statically match nothing — the lowered
#: answer is an empty vector, no scan needed
EMPTY = object()


@dataclass
class LoweredSelect:
    """One aggregate-over-selector shape lowered onto the plan IR."""
    table: object
    plan: object                       # query.ir TpuPlan
    func: Optional[str]                # None = instant selector
    metric: str
    field: str
    tag_names: List[str]
    t0: int                            # first window end (offset applied)
    ends: np.ndarray                   # [nsteps] window ends, int64
    win: int                           # window width (lookback or range)


def resolve_metric_table(engine, sel: VectorSelector, ctx):
    """(metric name, table or None) — shared by the lowering and the
    row path so both resolve `__name__` overrides identically."""
    metric = sel.metric
    for m in sel.matchers:
        if m.name == "__name__" and m.op == "=":
            metric = m.value
    if not metric:
        raise UnsupportedError(
            "selector without metric name is not supported")
    table = engine.catalog.table(ctx.current_catalog, ctx.current_schema,
                                 metric)
    return metric, table


def _numeric_fields(schema, matchers) -> List[str]:
    from .engine import _matcher_keep
    fields = [f for f in schema.field_names()
              if not schema.column_schema(f).dtype.is_string and
              not schema.column_schema(f).dtype.is_binary]
    for m in matchers:
        if m.name == "__field__":
            keep = _matcher_keep(fields, m)
            fields = [f for f, k in zip(fields, keep) if k]
    return fields


# ---------------------------------------------------------------------------
# shape analysis: Aggregate node -> LoweredSelect | EMPTY | None
# ---------------------------------------------------------------------------

def try_lower(ev, e: Aggregate):
    """Decide whether the inner vector of this aggregate lowers onto
    the IR. Returns (LoweredSelect, "") on success, (EMPTY, "") when
    the matchers statically match nothing, or (None, reason) when the
    statement keeps the row path."""
    from ..query import ir, tpu_exec
    from .engine import _matches_empty

    if e.op not in LOWERABLE_AGG_OPS or e.param is not None:
        return None, f"outer aggregate {e.op} keeps per-sample semantics"
    inner = e.expr
    func = None
    if isinstance(inner, Call):
        if inner.func not in LOWERABLE_RANGE_FUNCS or \
                len(inner.args) != 1 or \
                not isinstance(inner.args[0], VectorSelector):
            return None, f"function {getattr(inner, 'func', '?')} has " \
                "no moment decomposition"
        sel = inner.args[0]
        func = inner.func
        if not sel.range_ms:
            return None, f"{func} needs a range selector"
        if sel.range_ms != ev.step:
            return None, (f"window does not tumble "
                          f"(range={sel.range_ms}ms != step={ev.step}ms)")
    elif isinstance(inner, VectorSelector):
        sel = inner
        if sel.range_ms:
            return None, "raw matrix selector"
    else:
        return None, f"inner {type(inner).__name__} is not a selector"
    if sel.at_ms is not None:
        return None, "@ modifier pins one evaluation time"

    metric, table = resolve_metric_table(ev.engine, sel, ev.ctx)
    if table is None or not hasattr(table, "schema"):
        return None, f"table {metric} not found"
    is_dist = hasattr(table, "execute_tpu_plan")
    if not is_dist and not hasattr(table, "regions"):
        return None, f"{metric} is not a region-backed table"
    if is_dist and not tpu_exec._PARTIAL_PUSHDOWN[0]:
        return None, "SET dist_partial_agg = 0"
    if not is_dist:
        # same floor SQL's try_execute applies: small local tables are
        # faster (and float64-exact) on the existing row path
        est = tpu_exec._estimated_table_rows(table)
        if est is not None and est < tpu_exec.TPU_DISPATCH_MIN_ROWS:
            return None, (f"est_rows={est} < dispatch_floor="
                          f"{tpu_exec.TPU_DISPATCH_MIN_ROWS}")

    schema = table.schema
    if schema.timestamp_column is None:
        return None, f"{metric} has no time index"
    tag_names = schema.tag_names()
    tagset = set(tag_names)
    fields = _numeric_fields(schema, sel.matchers)
    if not fields:
        return EMPTY, ""
    if len(fields) > 1:
        return None, "multi-field table needs per-field series"

    preds = []
    for m in sel.matchers:
        if m.name == "__name__":
            if m.op != "=":
                return None, "non-equality __name__ matcher"
            continue
        if m.name == "__field__":
            continue
        if m.name not in tagset:
            # matching a non-existent label: ""-matching ops are
            # vacuously true, anything else statically matches nothing
            if _matches_empty(m):
                continue
            return EMPTY, ""
        if not schema.column_schema(m.name).dtype.is_string:
            return None, f"matcher on non-string tag {m.name}"
        col = Column(m.name)
        if m.op == "=":
            if m.value == "":
                # = "" keeps absent-or-empty labels; the stored-null
                # rendering only the row path implements
                return None, 'matcher = "" selects absent labels'
            preds.append(BinaryOp("=", col, Literal(m.value)))
        elif m.op == "!=":
            if m.value == "":
                preds.append(BinaryOp("!=", col, Literal("")))
            else:
                # a stored NULL renders as "" and "" != value, so keep
                # null rows explicitly (SQL != drops nulls)
                preds.append(BinaryOp("or", IsNull(col),
                                      BinaryOp("!=", col,
                                               Literal(m.value))))
        else:
            return None, f"regex matcher on {m.name}"

    ends = ev._grid(sel.offset_ms, None)
    t0 = int(ends[0])
    win = int(sel.range_ms) if func else int(ev.lookback)
    field = fields[0]
    aggs = [("__n", "count", field)]
    mspec: List[Tuple[str, str, str]] = []
    if func is None:
        aggs.append(("__v", "last", field))
        mspec.append(("__t", "max_ts", field))
    elif func in ("rate", "increase", "delta"):
        aggs += [("__first", "first", field), ("__last", "last", field)]
        mspec += [("__mnt", "min_ts", field), ("__mxt", "max_ts", field)]
        if func != "delta":
            mspec.append(("__corr", "reset_corr", field))
    elif func in ("last_over_time",):
        aggs.append(("__v", "last", field))
    elif func != "count_over_time":
        aggs.append(("__v", func[:-len("_over_time")], field))

    from ..query.tpu_exec import BucketGroup
    plan = ir.plan_from_specs(
        schema, aggs,
        group_tags=tag_names,          # per-series: full tag key
        bucket=BucketGroup(ev.step, t0 - ev.step + 1, "__promql_window"),
        time_lo=t0 - win + 1,          # _window_eval's matrix bound
        time_hi=int(ends[-1]) + 1,     # closed hi -> exclusive
        tag_predicates=preds,
        moment_specs=mspec)
    return LoweredSelect(table, plan, func, metric, field, tag_names,
                         t0, ends, win), ""


# ---------------------------------------------------------------------------
# executing a lowered shape and rebuilding the inner instant vector
# ---------------------------------------------------------------------------

def _key_str(v) -> str:
    from .engine import _label_str
    if isinstance(v, float) and np.isnan(v):
        return ""
    return _label_str(v)


def eval_lowered(ev, low: LoweredSelect):
    """Run the lowered plan and rebuild the inner instant vector —
    per-series values over the step grid with Prometheus staleness /
    extrapolation semantics replicated from ops/window.py."""
    from ..query import ir
    from .engine import _KEEP_NAME_RANGE_FUNCS, VectorVal

    df = ir.execute_agg_plan(low.table, low.plan)
    T = ev.nsteps
    if df is None or not len(df):
        return VectorVal([], np.zeros((0, T)), np.zeros((0, T), bool))
    from ..query.planner import _group_slot
    # buckets whose rows were all-null carry no sample: drop them so a
    # -inf max_ts sentinel never forward-fills
    df = df[df["__n"].to_numpy() > 0]
    if not len(df):
        return VectorVal([], np.zeros((0, T)), np.zeros((0, T), bool))

    rendered = [[_key_str(v) for v in df[_group_slot(t)]]
                for t in low.tag_names]
    n = len(df)
    keys = list(zip(*rendered)) if rendered else [()] * n
    uniq = sorted(set(keys))
    sid_of = {k: i for i, k in enumerate(uniq)}
    sids = np.fromiter((sid_of[k] for k in keys), dtype=np.int64, count=n)
    S = len(uniq)
    step = ev.step
    bv = df[_group_slot("__promql_window")].to_numpy().astype(np.int64)
    # bucket lower edge -> window end -> step index (negative = the
    # instant path's lookback prefix, filled forward below)
    k = ((bv + step - 1) - low.t0) // step
    cnt = df["__n"].to_numpy().astype(np.float64)

    out_vals = np.full((S, T), np.nan)
    out_ok = np.zeros((S, T), dtype=bool)
    if low.func is None:
        last_v = df["__v"].to_numpy(dtype=np.float64)
        last_t = df["__t"].to_numpy(dtype=np.float64)
        off = -min(int(k.min()), 0)
        K = off + T
        pos = k + off
        inb = (pos >= 0) & (pos < K)
        val_g = np.full((S, K), np.nan)
        ts_g = np.full((S, K), -np.inf)
        val_g[sids[inb], pos[inb]] = last_v[inb]
        ts_g[sids[inb], pos[inb]] = last_t[inb]
        idx = np.where(ts_g > -np.inf, np.arange(K)[None, :], -1)
        idx = np.maximum.accumulate(idx, axis=1)
        has = idx >= 0
        gather = np.clip(idx, 0, None)
        vf = np.take_along_axis(val_g, gather, 1)
        tf = np.take_along_axis(ts_g, gather, 1)
        out_vals = vf[:, off:off + T]
        # same closed staleness bound instant_select applies on device
        out_ok = has[:, off:off + T] & \
            (tf[:, off:off + T] >= low.ends[None, :] - ev.lookback)
        out_vals = np.where(out_ok, out_vals, np.nan)
    else:
        inb = (k >= 0) & (k < T)
        with np.errstate(all="ignore"):
            if low.func in ("rate", "increase", "delta"):
                rowvals, rowok = _window_rate(df, low, k, cnt)
            elif low.func == "count_over_time":
                rowvals, rowok = cnt, cnt >= 1
            else:
                rowvals = df["__v"].to_numpy(dtype=np.float64)
                rowok = cnt >= 1
        out_vals[sids[inb], k[inb]] = rowvals[inb]
        out_ok[sids[inb], k[inb]] = rowok[inb]

    keep_name = low.func is None or low.func in _KEEP_NAME_RANGE_FUNCS
    labels: List[Dict[str, str]] = []
    for ukey in uniq:
        lbl: Dict[str, str] = {}
        if keep_name:
            lbl["__name__"] = low.metric
        for tn, tv in zip(low.tag_names, ukey):
            if tv != "":
                lbl[tn] = tv
        labels.append(lbl)
    return VectorVal(labels, out_vals, out_ok)


def _window_rate(df, low: LoweredSelect, k: np.ndarray, cnt: np.ndarray):
    """rate/increase/delta from per-window moments: the Prometheus
    extrapolation epilogue of ops/window.py `_extrapolate`, replicated
    on the frontend over merged first/last/min_ts/max_ts (+ the
    reset_corr moment for counters)."""
    first_v = df["__first"].to_numpy(dtype=np.float64)
    last_v = df["__last"].to_numpy(dtype=np.float64)
    first_t = df["__mnt"].to_numpy(dtype=np.float64)
    last_t = df["__mxt"].to_numpy(dtype=np.float64)
    rng = float(low.win)
    end_abs = (low.t0 + k * low.win).astype(np.float64)
    if low.func == "delta":
        raw = last_v - first_v
    else:
        raw = last_v - first_v + df["__corr"].to_numpy(dtype=np.float64)
    dur_to_start = first_t - (end_abs - rng)
    dur_to_end = end_abs - last_t
    sampled = last_t - first_t
    avg_dur = sampled / np.maximum(cnt - 1, 1)
    threshold = avg_dur * 1.1
    if low.func != "delta":
        # counters never extrapolate below zero
        dur_to_zero = np.where(
            (raw > 0) & (first_v >= 0),
            sampled * (first_v / np.where(raw == 0, 1.0, raw)), np.inf)
        dur_to_start = np.minimum(dur_to_start, dur_to_zero)
    ext_start = np.where(dur_to_start < threshold, dur_to_start,
                         avg_dur / 2)
    ext_end = np.where(dur_to_end < threshold, dur_to_end, avg_dur / 2)
    factor = (sampled + ext_start + ext_end) / \
        np.where(sampled == 0, 1.0, sampled)
    out = raw * factor
    if low.func == "rate":
        out = out / (rng / 1000.0)
    return out, (cnt >= 2) & (sampled > 0)


def try_lowered_inner(ev, e: Aggregate):
    """The engine's hook: the inner instant vector of this aggregate
    via the IR, or None to keep the row path. Degrades (never errors)
    when the executor rejects the plan — cost-based raw-pull, a
    version-skewed datanode, a sketch decode failure."""
    from .engine import VectorVal
    low, _reason = try_lower(ev, e)
    if low is EMPTY:
        T = ev.nsteps
        return VectorVal([], np.zeros((0, T)), np.zeros((0, T), bool))
    if low is None:
        return None
    try:
        return eval_lowered(ev, low)
    except UnsupportedError:
        return None


# ---------------------------------------------------------------------------
# EXPLAIN: the same dispatch stages SQL prints
# ---------------------------------------------------------------------------

def explain_lines(ev, expr) -> List[str]:
    """Plan/dispatch lines for TQL EXPLAIN — built by the same helpers
    SQL's EXPLAIN uses (dispatch_decision_for_pushdown /
    local_dispatch_decision), so the two surfaces cannot drift."""
    from ..query import tpu_exec

    aggs: List[Aggregate] = []
    sels: List[VectorSelector] = []

    def walk(node):
        if isinstance(node, Aggregate):
            aggs.append(node)
        if isinstance(node, VectorSelector):
            sels.append(node)
        for child in list(getattr(node, "args", []) or []):
            if isinstance(child, PromExpr):
                walk(child)
        for attr in ("expr", "lhs", "rhs"):
            child = getattr(node, attr, None)
            if isinstance(child, PromExpr):
                walk(child)

    walk(expr)
    lines: List[str] = []
    covered = set()
    for agg in aggs:
        low, reason = try_lower(ev, agg)
        if isinstance(low, LoweredSelect):
            covered.update(id(s) for s in sels
                           if s is agg.expr or
                           s in list(getattr(agg.expr, "args", []) or []))
            lines.append("TpuAggregateExec: " + low.plan.describe())
            if hasattr(low.table, "execute_tpu_plan"):
                lines.append("  Dispatch: " +
                             tpu_exec.dispatch_decision_for_pushdown(
                                 low.table, low.plan))
            else:
                lines.append("  Dispatch: " +
                             tpu_exec.local_dispatch_decision(
                                 low.table, plan=low.plan))
        elif low is EMPTY:
            lines.append("EmptyExec: matchers select no series")
        else:
            lines.append("  Dispatch: promql-row-path (" + reason + ")")
    for sel in sels:
        if id(sel) in covered:
            continue
        desc = _raw_scan_describe(ev, sel)
        if desc is not None:
            lines.append(desc)
    return lines


def _raw_scan_describe(ev, sel: VectorSelector) -> Optional[str]:
    """The RawScan leaf a row-path selector turns into."""
    from ..query import ir
    try:
        metric, table = resolve_metric_table(ev.engine, sel, ev.ctx)
    except UnsupportedError:
        return None
    if table is None or not hasattr(table, "schema"):
        return None
    schema = table.schema
    tc = schema.timestamp_column
    if tc is None:
        return None
    fields = _numeric_fields(schema, sel.matchers)
    ends = ev._grid(sel.offset_ms, sel.at_ms)
    win = int(sel.range_ms) if sel.range_ms else int(ev.lookback)
    lo = int(ends.min()) - win + 1
    hi = int(ends.max()) + 1
    tagset = set(schema.tag_names())
    n_push = sum(1 for m in sel.matchers
                 if m.op == "=" and m.name in tagset and m.value)
    scan = ir.RawScan(
        projection=list(schema.tag_names()) + [tc.name] + fields,
        time_range=(lo, hi), filters=[None] * n_push)
    return scan.describe()


# ---------------------------------------------------------------------------
# sanctioned data access: the engine's row-path selector
# ---------------------------------------------------------------------------

def select_series(engine, sel: VectorSelector, lo_ms: int, hi_ms: int,
                  ctx):
    """Fetch samples for a selector in the closed window [lo_ms, hi_ms]
    as a dense SeriesMatrix sorted by time within each series (the
    engine's `select`). In-process regions are read directly (device
    scan cache / streamed cold reads / SST-index sid pruning); a
    DistTable whose datanodes are remote has no in-process regions, so
    the same selector is served by an IR RawScan over the wire —
    pruned, filter-pushed, never silently empty."""
    from ..ops.window import SeriesMatrix
    from .engine import (
        _is_sorted, _label_str, _matcher_keep, _matches_empty, _Selection,
    )

    metric, table = resolve_metric_table(engine, sel, ctx)
    if table is None:
        return _Selection([], None)
    if not hasattr(table, "regions"):
        raise UnsupportedError(f"{metric} is not a region-backed table")

    schema = table.schema
    tag_names = schema.tag_names()
    tagset = set(tag_names)
    fields = _numeric_fields(schema, sel.matchers)
    if not fields:
        return _Selection([], None)
    multi_field = len(fields) > 1

    regions = table.regions
    if not regions and hasattr(table, "execute_tpu_plan"):
        return _wire_scan_selection(table, sel, metric, tag_names,
                                    fields, multi_field, lo_ms, hi_ms)

    key_to_gid: Dict[tuple, int] = {}
    glabels: List[Dict[str, str]] = []
    parts: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []

    eq_matchers = [m for m in sel.matchers
                   if m.op == "=" and m.name in tagset and m.value]
    # tag columns the matchers actually reference: the keep mask only
    # needs these decoded; everything else decodes later, and only for
    # the series that survive
    ref_idx = sorted({tag_names.index(m.name) for m in sel.matchers
                      if m.name in tagset})
    for region in regions.values():
        sid_set = matcher_sids(region, tag_names, eq_matchers)
        if sid_set is not None and len(sid_set) == 0:
            continue                 # no series of this region match
        scan = region_scan(region, fields, lo_ms, hi_ms, sid_set=sid_set)
        if scan is None or scan.num_rows == 0:
            continue
        sd = scan.series_dict
        S = sd.num_series
        if S == 0:
            continue
        ids = np.arange(S, dtype=np.int32)
        tag_strs: Dict[int, List[str]] = {
            i: [_label_str(v) for v in sd.decode_tag_column(ids, i)]
            for i in ref_idx}
        keep = np.ones(S, dtype=bool)
        for m in sel.matchers:
            if m.name in ("__name__", "__field__"):
                continue
            if m.name not in tagset:
                # matching a non-existent label: only ""-matching ops keep
                if not _matches_empty(m):
                    keep[:] = False
                continue
            keep &= _matcher_keep(tag_strs[tag_names.index(m.name)], m)
        if not keep.any():
            continue
        row_keep = keep[scan.series_ids] & (scan.ts >= lo_ms) & \
            (scan.ts <= hi_ms)
        if not row_keep.any():
            continue

        # decode the remaining tag columns only for surviving series
        survivors = np.unique(scan.series_ids[row_keep]).astype(np.int32)
        label_of: Dict[int, tuple] = {}
        cols = {i: tag_strs[i] if i in tag_strs else
                [_label_str(v) for v in
                 sd.decode_tag_column(survivors, i)]
                for i in range(len(tag_names))}
        for j, s in enumerate(survivors):
            label_of[int(s)] = tuple(
                cols[i][int(s)] if i in ref_idx else cols[i][j]
                for i in range(len(tag_names)))

        for fname in fields:
            vals, valid = scan.fields[fname]
            rk = row_keep if valid is None else (row_keep & valid)
            if not rk.any():
                continue
            sids = scan.series_ids[rk]
            ts = scan.ts[rk]
            v = vals[rk].astype(np.float64)
            # map region series → global series ids
            uniq = np.unique(sids)
            remap = np.full(S, -1, dtype=np.int32)
            for s in uniq:
                lbl_key = label_of[int(s)]
                gkey = lbl_key + ((fname,) if multi_field else ())
                gid = key_to_gid.get(gkey)
                if gid is None:
                    gid = len(glabels)
                    key_to_gid[gkey] = gid
                    lbl = {"__name__": metric}
                    for tn, tv in zip(tag_names, lbl_key):
                        if tv != "":
                            lbl[tn] = tv
                    if multi_field:
                        lbl["__field__"] = fname
                    glabels.append(lbl)
                remap[s] = gid
            parts.append((remap[sids], ts, v))

    if not parts:
        return _Selection([], None)
    gids = np.concatenate([p[0] for p in parts])
    ts = np.concatenate([p[1] for p in parts])
    vals = np.concatenate([p[2] for p in parts])
    # already sorted when a single region/field contributed in order
    if len(parts) > 1 or not _is_sorted(gids, ts):
        order = np.lexsort((ts, gids))
        gids, ts, vals = gids[order], ts[order], vals[order]
    sm = SeriesMatrix.build(gids, ts, vals, len(glabels))
    return _Selection(glabels, sm, int(ts.min()), int(ts.max()))


def _wire_scan_selection(table, sel: VectorSelector, metric: str,
                         tag_names: List[str], fields: List[str],
                         multi_field: bool, lo_ms: int, hi_ms: int):
    """Row-path selection over remote datanodes: an IR RawScan through
    DistTable.scan_batches — region pruning and equality-matcher wire
    pushdown apply; the remaining matchers filter the rows here."""
    from ..ops.window import SeriesMatrix
    from ..query import ir
    from .engine import (
        _is_sorted, _label_str, _matcher_keep, _matches_empty, _Selection,
    )

    schema = table.schema
    tagset = set(tag_names)
    preds = []
    for m in sel.matchers:
        if m.op == "=" and m.name in tagset and m.value and \
                schema.column_schema(m.name).dtype.is_string:
            preds.append(BinaryOp("=", Column(m.name), Literal(m.value)))
    tc = schema.timestamp_column
    scan = ir.RawScan(
        projection=list(tag_names) + [tc.name] + list(fields),
        time_range=(lo_ms, hi_ms + 1), filters=preds)
    try:
        batches = ir.execute_raw_scan(table, scan)
    except NotImplementedError as e:
        raise UnsupportedError(
            f"PromQL over {metric}: its datanode client implements "
            "neither in-process regions nor the wire scan path; the "
            "lowered aggregate path (SET dist_partial_agg = 1) is the "
            "only route to these datanodes") from e

    key_to_gid: Dict[tuple, int] = {}
    glabels: List[Dict[str, str]] = []
    gid_parts: List[np.ndarray] = []
    ts_parts: List[np.ndarray] = []
    val_parts: List[np.ndarray] = []
    for rb in batches:
        if rb.num_rows == 0:
            continue
        data = rb.to_pydict()
        n = rb.num_rows
        tag_strs = [[_label_str(v) for v in data[t]] for t in tag_names]
        keep = np.ones(n, dtype=bool)
        for m in sel.matchers:
            if m.name in ("__name__", "__field__"):
                continue
            if m.name not in tagset:
                if not _matches_empty(m):
                    keep[:] = False
                continue
            keep &= _matcher_keep(tag_strs[tag_names.index(m.name)], m)
        ts = np.asarray(data[tc.name], dtype=np.int64)
        keep &= (ts >= lo_ms) & (ts <= hi_ms)
        if not keep.any():
            continue
        rows = np.nonzero(keep)[0]
        for fname in fields:
            fcol = data[fname]
            for i in rows:
                fv = fcol[i]
                if fv is None:
                    continue
                lbl_key = tuple(col[i] for col in tag_strs)
                gkey = lbl_key + ((fname,) if multi_field else ())
                gid = key_to_gid.get(gkey)
                if gid is None:
                    gid = len(glabels)
                    key_to_gid[gkey] = gid
                    lbl = {"__name__": metric}
                    for tn, tv in zip(tag_names, lbl_key):
                        if tv != "":
                            lbl[tn] = tv
                    if multi_field:
                        lbl["__field__"] = fname
                    glabels.append(lbl)
                gid_parts.append(gid)
                ts_parts.append(ts[i])
                val_parts.append(float(fv))
    if not gid_parts:
        return _Selection([], None)
    gids = np.asarray(gid_parts, dtype=np.int32)
    tsa = np.asarray(ts_parts, dtype=np.int64)
    vals = np.asarray(val_parts, dtype=np.float64)
    if not _is_sorted(gids, tsa):
        order = np.lexsort((tsa, gids))
        gids, tsa, vals = gids[order], tsa[order], vals[order]
    sm = SeriesMatrix.build(gids, tsa, vals, len(glabels))
    return _Selection(glabels, sm, int(tsa.min()), int(tsa.max()))


def matcher_sids(region, tag_names, eq_matchers):
    """Sorted candidate sid superset for the selector's equality
    matchers in one region, or None when there is nothing selective
    to resolve — what lets the cold selector path prune whole SSTs
    through their index sidecars. Label values are matched on the
    same string rendering the keep-mask uses, so numeric tags
    resolve identically on both paths."""
    from ..storage.index import sst_index_enabled
    from .engine import _label_str
    if not eq_matchers or not sst_index_enabled():
        return None
    sd = getattr(region, "series_dict", None)
    if sd is None or not sd.tag_names:
        return None
    cand = None
    for m in eq_matchers:
        ti = tag_names.index(m.name)
        # O(1) dictionary hit for string tags (the common case);
        # the O(values) rendered-label scan only runs for tags whose
        # stored values are not strings
        vid = sd.tag_dicts[ti].get(m.value)
        if vid is not None:
            ids = [vid]
        else:
            ids = [i for i, v in
                   enumerate(sd.tag_dicts[ti].values())
                   if v is not None and not isinstance(v, str) and
                   _label_str(v) == m.value]
        sids = sd.sids_for_value_ids(ti, ids)
        cand = sids if cand is None else \
            np.intersect1d(cand, sids, assume_unique=True)
        if len(cand) == 0:
            break
    return cand


def region_scan(region, fields: List[str], lo_ms: int, hi_ms: int,
                sid_set=None):
    """Rows for one region: the device-resident scan cache for warm
    regions; a window-bounded streamed cold read for regions past the
    streaming threshold. Both shapes expose
    series_ids/ts/fields/series_dict."""
    from ..common.telemetry import increment_counter
    from ..common.time import TimestampRange
    from ..query.tpu_exec import SCAN_CACHE, region_streams_cold

    if not region_streams_cold(region):
        increment_counter("promql_select_resident")
        return SCAN_CACHE.get(region)
    # cold path: merged host read of only the selector's window and
    # fields — proportional to the window, never enters the scan
    # cache, leaves no device residency behind
    increment_counter("promql_select_streamed")
    from ..common import exec_stats
    with exec_stats.stage("promql_cold_scan", region=region.name):
        # equality matchers ride the SST index: whole files whose
        # blooms exclude every candidate series never decode
        data = region.snapshot().read_merged(
            projection=list(fields),
            time_range=TimestampRange(lo_ms, hi_ms + 1),
            sid_set=sid_set)
    exec_stats.record("promql_cold_scan", rows=data.num_rows)
    return data
