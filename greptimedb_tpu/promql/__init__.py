"""Native PromQL engine.

Reference behavior: src/promql — a PromQL planner compiling to DataFusion
plans with custom streaming nodes (SeriesNormalize / SeriesDivide /
Instant- and RangeManipulate) and per-window UDFs
(src/promql/src/planner.rs, extension_plan/, functions/). Here the same
stages execute on the TPU window kernels (ops/window.py): series become a
dense [series, time] matrix in HBM; instant selection and every range
function are vmapped (series × step) device passes; label grouping,
vector matching, and JSON shaping stay on host.
"""

from .parser import parse_promql, PromqlParseError
from .engine import PromqlEngine

__all__ = ["parse_promql", "PromqlParseError", "PromqlEngine"]
