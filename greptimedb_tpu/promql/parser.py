"""PromQL parser: tokenizer + Pratt expression parser → promql.ast nodes.

Reference behavior: the reference consumes the `promql-parser` crate
(src/promql/src/planner.rs:70 takes its `EvalStmt`); this is an original
recursive-descent/Pratt implementation of the same grammar: vector/matrix
selectors with matchers, offset/@ modifiers, subqueries, functions,
aggregations with by/without (pre- or postfix), binary operators with
bool / on / ignoring / group_left / group_right modifiers, durations,
hex/float/inf/nan literals.
"""

from __future__ import annotations

import math
import re
from typing import List, Optional, Tuple

from ..errors import GreptimeError
from .ast import (
    Aggregate, Binary, Call, Matcher, NumberLiteral, PromExpr, StringLiteral,
    SubqueryExpr, Unary, VectorMatching, VectorSelector,
)


class PromqlParseError(GreptimeError):
    status_code = "InvalidArguments"


AGGREGATORS = {
    "sum", "avg", "min", "max", "count", "stddev", "stdvar", "group",
    "topk", "bottomk", "quantile", "count_values",
}
# aggregators taking a parameter before the expression
PARAM_AGGREGATORS = {"topk", "bottomk", "quantile", "count_values"}

_DUR_RX = re.compile(
    r"(?:\d+(?:\.\d+)?(?:ms|s|m|h|d|w|y))+")


def parse_duration_ms(text: str) -> int:
    """'5m' / '1h30m' / '1.5h' → milliseconds (PromQL duration grammar,
    delegating to the shared common.time parser)."""
    from ..common.time import parse_duration_ms as _common_parse
    t = str(text).strip()
    if not t or not _DUR_RX.fullmatch(t):
        raise PromqlParseError(f"invalid duration {text!r}")
    try:
        return _common_parse(t)
    except ValueError as e:
        raise PromqlParseError(f"invalid duration {text!r}") from e


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

# token kinds: NUM DUR STR IDENT OP EOF
_NUM_RX = re.compile(
    r"0[xX][0-9a-fA-F]+|(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?")
_IDENT_RX = re.compile(r"[a-zA-Z_][a-zA-Z0-9_:]*")
_OPS = ["==", "!=", "<=", ">=", "=~", "!~", "+", "-", "*", "/", "%", "^",
        "<", ">", "=", "(", ")", "{", "}", "[", "]", ",", "@", ":"]


class _Tok:
    __slots__ = ("kind", "text", "pos")

    def __init__(self, kind, text, pos):
        self.kind, self.text, self.pos = kind, text, pos

    def __repr__(self):
        return f"{self.kind}:{self.text!r}"


def _tokenize(src: str) -> List[_Tok]:
    toks: List[_Tok] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c in " \t\r\n":
            i += 1
            continue
        if c == "#":                       # comment to end of line
            while i < n and src[i] != "\n":
                i += 1
            continue
        if c in "'\"":
            j = i + 1
            buf = []
            while j < n and src[j] != c:
                if src[j] == "\\" and j + 1 < n:
                    esc = src[j + 1]
                    buf.append({"n": "\n", "t": "\t", "r": "\r",
                                "\\": "\\", "'": "'", '"': '"'}.get(esc, esc))
                    j += 2
                else:
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise PromqlParseError(f"unterminated string at {i}")
            toks.append(_Tok("STR", "".join(buf), i))
            i = j + 1
            continue
        if c == "`":
            j = src.find("`", i + 1)
            if j < 0:
                raise PromqlParseError(f"unterminated raw string at {i}")
            toks.append(_Tok("STR", src[i + 1:j], i))
            i = j + 1
            continue
        m = _DUR_RX.match(src, i)
        if m and not src[i].isalpha():
            # duration must not be a plain number: needs a unit suffix
            toks.append(_Tok("DUR", m.group(0), i))
            i = m.end()
            continue
        m = _NUM_RX.match(src, i)
        if m:
            toks.append(_Tok("NUM", m.group(0), i))
            i = m.end()
            continue
        m = _IDENT_RX.match(src, i)
        if m:
            toks.append(_Tok("IDENT", m.group(0), i))
            i = m.end()
            continue
        for op in _OPS:
            if src.startswith(op, i):
                toks.append(_Tok("OP", op, i))
                i += len(op)
                break
        else:
            raise PromqlParseError(f"unexpected character {c!r} at {i}")
    toks.append(_Tok("EOF", "", n))
    return toks


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

_PRECEDENCE = {
    "or": 1,
    "and": 2, "unless": 2,
    "==": 3, "!=": 3, "<": 3, "<=": 3, ">": 3, ">=": 3,
    "+": 4, "-": 4,
    "*": 5, "/": 5, "%": 5, "atan2": 5,
    "^": 6,
}
_RIGHT_ASSOC = {"^"}
_COMPARISONS = {"==", "!=", "<", "<=", ">", ">="}
_SET_OPS = {"and", "or", "unless"}


class _Parser:
    def __init__(self, src: str):
        self.toks = _tokenize(src)
        self.i = 0

    # -- token helpers --
    def peek(self) -> _Tok:
        return self.toks[self.i]

    def next(self) -> _Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, kind: str, text: Optional[str] = None) -> _Tok:
        t = self.peek()
        if t.kind != kind or (text is not None and t.text != text):
            want = text or kind
            raise PromqlParseError(
                f"expected {want!r}, got {t.text!r} at {t.pos}")
        return self.next()

    def at_op(self, text: str) -> bool:
        t = self.peek()
        return t.kind == "OP" and t.text == text

    def at_ident(self, text: str) -> bool:
        t = self.peek()
        return t.kind == "IDENT" and t.text == text

    def eat_op(self, text: str) -> bool:
        if self.at_op(text):
            self.next()
            return True
        return False

    # -- grammar --
    def parse(self) -> PromExpr:
        e = self.parse_expr(0)
        t = self.peek()
        if t.kind != "EOF":
            raise PromqlParseError(
                f"unexpected {t.text!r} at {t.pos}")
        return e

    def parse_expr(self, min_prec: int) -> PromExpr:
        lhs = self.parse_unary()
        while True:
            t = self.peek()
            op = t.text if (
                t.kind == "OP" or (t.kind == "IDENT" and
                                   t.text in ("and", "or", "unless", "atan2"))
            ) else None
            if op not in _PRECEDENCE or _PRECEDENCE[op] < min_prec:
                return lhs
            self.next()
            return_bool = False
            if op in _COMPARISONS and self.at_ident("bool"):
                self.next()
                return_bool = True
            matching = self._parse_matching(op)
            nxt = _PRECEDENCE[op] + (0 if op in _RIGHT_ASSOC else 1)
            rhs = self.parse_expr(nxt)
            lhs = Binary(op=op, lhs=lhs, rhs=rhs, return_bool=return_bool,
                         matching=matching)

    def _parse_matching(self, op: str) -> Optional[VectorMatching]:
        if not (self.at_ident("on") or self.at_ident("ignoring")):
            return None
        kind = self.next().text
        labels = self._label_list()
        vm = VectorMatching(on=labels if kind == "on" else None,
                            ignoring=labels if kind == "ignoring" else None)
        if self.at_ident("group_left") or self.at_ident("group_right"):
            g = self.next().text
            if g == "group_left":
                vm.group_left = True
            else:
                vm.group_right = True
            if self.at_op("("):
                vm.include = self._label_list()
        if vm.on is None and vm.ignoring is None:
            vm.ignoring = []
        return vm

    def _label_list(self) -> List[str]:
        self.expect("OP", "(")
        out = []
        while not self.at_op(")"):
            out.append(self.expect("IDENT").text)
            if not self.eat_op(","):
                break
        self.expect("OP", ")")
        return out

    def parse_unary(self) -> PromExpr:
        if self.at_op("-") or self.at_op("+"):
            op = self.next().text
            # unary binds looser than ^ only (prometheus: -1^2 == -(1^2))
            e = self.parse_expr(_PRECEDENCE["^"])
            if op == "-":
                if isinstance(e, NumberLiteral):
                    return NumberLiteral(-e.value)
                return Unary(op="-", expr=e)
            return e
        return self.parse_postfix(self.parse_primary())

    def parse_postfix(self, e: PromExpr) -> PromExpr:
        while True:
            if self.at_op("["):
                self.next()
                rng = parse_duration_ms(self.expect("DUR").text)
                if self.eat_op(":"):          # subquery [range:step]
                    step = None
                    if self.peek().kind == "DUR":
                        step = parse_duration_ms(self.next().text)
                    self.expect("OP", "]")
                    e = SubqueryExpr(expr=e, range_ms=rng, step_ms=step)
                else:
                    self.expect("OP", "]")
                    if not isinstance(e, VectorSelector) or e.range_ms:
                        raise PromqlParseError(
                            "range can only follow a vector selector")
                    e.range_ms = rng
            elif self.at_ident("offset"):
                self.next()
                neg = self.eat_op("-")
                off = parse_duration_ms(self.expect("DUR").text)
                off = -off if neg else off
                tgt = e
                if isinstance(tgt, (VectorSelector, SubqueryExpr)):
                    tgt.offset_ms = off
                else:
                    raise PromqlParseError("offset must follow a selector")
            elif self.at_op("@"):
                self.next()
                t = self.peek()
                if t.kind == "IDENT" and t.text in ("start", "end"):
                    self.next()
                    self.expect("OP", "(")
                    self.expect("OP", ")")
                    at = "start" if t.text == "start" else "end"
                elif t.kind == "NUM" or (t.kind == "OP" and t.text == "-"):
                    neg = self.eat_op("-")
                    v = float(self.expect("NUM").text)
                    at = int((-v if neg else v) * 1000)
                else:
                    raise PromqlParseError(f"invalid @ modifier at {t.pos}")
                if isinstance(e, VectorSelector):
                    e.at_ms = at
                else:
                    raise PromqlParseError("@ must follow a selector")
            else:
                return e

    def parse_primary(self) -> PromExpr:
        t = self.peek()
        if t.kind == "NUM":
            self.next()
            txt = t.text
            if txt.lower().startswith("0x"):
                return NumberLiteral(float(int(txt, 16)))
            return NumberLiteral(float(txt))
        if t.kind == "DUR":
            # durations are valid number literals (e.g. `5m` = 300 in newer
            # prometheus); accept as seconds? keep strict: reject.
            raise PromqlParseError(
                f"unexpected duration {t.text!r} at {t.pos}")
        if t.kind == "STR":
            self.next()
            return StringLiteral(t.text)
        if self.at_op("("):
            self.next()
            e = self.parse_expr(0)
            self.expect("OP", ")")
            return e
        if self.at_op("{"):
            return self._vector_selector("")
        if t.kind == "IDENT":
            name = t.text
            low = name.lower()
            if low in ("inf", "nan") and name not in AGGREGATORS:
                self.next()
                return NumberLiteral(math.inf if low == "inf" else math.nan)
            if name in AGGREGATORS:
                nxt = self.toks[self.i + 1]
                if nxt.kind == "OP" and nxt.text == "(" or \
                        (nxt.kind == "IDENT" and
                         nxt.text in ("by", "without")):
                    return self._aggregate(name)
            nxt = self.toks[self.i + 1]
            if nxt.kind == "OP" and nxt.text == "(":
                self.next()
                return self._call(name)
            self.next()
            return self._vector_selector(name)
        raise PromqlParseError(f"unexpected {t.text!r} at {t.pos}")

    def _call(self, func: str) -> Call:
        self.expect("OP", "(")
        args: List[PromExpr] = []
        while not self.at_op(")"):
            args.append(self.parse_expr(0))
            if not self.eat_op(","):
                break
        self.expect("OP", ")")
        return Call(func=func, args=args)

    def _aggregate(self, op: str) -> Aggregate:
        self.next()                         # the aggregator ident
        by = without = None
        if self.at_ident("by") or self.at_ident("without"):
            kind = self.next().text
            labels = self._label_list()
            by, without = (labels, None) if kind == "by" else (None, labels)
        self.expect("OP", "(")
        args: List[PromExpr] = []
        while not self.at_op(")"):
            args.append(self.parse_expr(0))
            if not self.eat_op(","):
                break
        self.expect("OP", ")")
        if self.at_ident("by") or self.at_ident("without"):
            kind = self.next().text
            labels = self._label_list()
            by, without = (labels, None) if kind == "by" else (None, labels)
        param = None
        if op in PARAM_AGGREGATORS:
            if len(args) != 2:
                raise PromqlParseError(f"{op} expects (param, expr)")
            param, expr = args
        else:
            if len(args) != 1:
                raise PromqlParseError(f"{op} expects one argument")
            expr = args[0]
        return Aggregate(op=op, expr=expr, by=by, without=without,
                         param=param)

    def _vector_selector(self, metric: str) -> VectorSelector:
        matchers: List[Matcher] = []
        if self.at_op("{"):
            self.next()
            while not self.at_op("}"):
                name = self.expect("IDENT").text
                t = self.peek()
                if t.kind != "OP" or t.text not in ("=", "!=", "=~", "!~"):
                    raise PromqlParseError(
                        f"expected matcher op at {t.pos}")
                self.next()
                value = self.expect("STR").text
                matchers.append(Matcher(name, t.text, value))
                if not self.eat_op(","):
                    break
            self.expect("OP", "}")
        if not metric:
            for m in matchers:
                if m.name == "__name__" and m.op == "=":
                    metric = m.value
            if not metric and not matchers:
                raise PromqlParseError("empty vector selector")
        return VectorSelector(metric=metric, matchers=matchers)


def parse_promql(src: str) -> PromExpr:
    if not src or not src.strip():
        raise PromqlParseError("empty query")
    return _Parser(src).parse()
