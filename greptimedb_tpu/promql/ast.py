"""PromQL AST nodes (mirrors the prometheus parser's expression types that
the reference consumes via the promql-parser crate)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

# matcher types
EQ, NEQ, RE, NRE = "=", "!=", "=~", "!~"


@dataclass
class Matcher:
    name: str
    op: str          # = != =~ !~
    value: str


@dataclass
class PromExpr:
    pass


@dataclass
class NumberLiteral(PromExpr):
    value: float


@dataclass
class StringLiteral(PromExpr):
    value: str


@dataclass
class VectorSelector(PromExpr):
    metric: str = ""
    matchers: List[Matcher] = field(default_factory=list)
    range_ms: Optional[int] = None       # matrix selector when set
    offset_ms: int = 0
    at_ms: Optional[int] = None          # @ modifier


@dataclass
class SubqueryExpr(PromExpr):
    expr: PromExpr = None
    range_ms: int = 0
    step_ms: Optional[int] = None
    offset_ms: int = 0


@dataclass
class Call(PromExpr):
    func: str = ""
    args: List[PromExpr] = field(default_factory=list)


@dataclass
class Aggregate(PromExpr):
    op: str = ""                          # sum avg min max count topk ...
    expr: PromExpr = None
    by: Optional[List[str]] = None        # by(...) labels
    without: Optional[List[str]] = None
    param: Optional[PromExpr] = None      # topk(k, ...) / quantile(q, ...)


@dataclass
class VectorMatching:
    on: Optional[List[str]] = None        # on(...) labels
    ignoring: Optional[List[str]] = None
    group_left: bool = False
    group_right: bool = False
    include: List[str] = field(default_factory=list)


@dataclass
class Binary(PromExpr):
    op: str = ""                          # + - * / % ^ == != < <= > >= and or unless atan2
    lhs: PromExpr = None
    rhs: PromExpr = None
    return_bool: bool = False
    matching: Optional[VectorMatching] = None


@dataclass
class Unary(PromExpr):
    op: str = "-"
    expr: PromExpr = None
