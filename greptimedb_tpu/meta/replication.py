"""Replicated metadata KV: raft-lite consensus over the meta plane.

Reference behavior: the meta-srv delegates durability + HA to an etcd
cluster (src/meta-srv/src/service/store/etcd.rs:762, election at
src/meta-srv/src/election/etcd.rs:34-70). This repo's single-node stand-in
is FileKv; this module closes the gap for multi-meta deployments: N meta
nodes replicate a command log with term-voted leader election and
majority commit, so the cluster brain survives a node loss the way the
datanode plane already does (region failover, meta/service.py:259).

Design (raft essentials, sized to the meta workload):
- Every mutation is a command appended to the leader's log, replicated
  via append_entries, committed once a majority holds it, then applied
  to the state machine (a plain dict) — on every node, in log order.
- Elections: followers time out, become candidates, request votes; a
  vote needs the candidate's log to be at least as up-to-date
  (last_term, last_index) — the raft safety rule that keeps committed
  entries on whoever wins.
- Persistence: (term, voted_for, log) go to an atomic JSON snapshot per
  node before any RPC reply, so a restarted node rejoins with its word
  kept. State is rebuilt by replay.
- Transport is pluggable: in-process handles for tests/single-process
  clusters, Flight actions (meta/flight.py) across real sockets.

The KV surface (`ReplicatedKv`) matches MemKv, so MetaSrv mounts it
unchanged; non-leader nodes raise NotLeaderError carrying the leader
hint for client-side retry.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..common.telemetry import increment_counter
from ..errors import GreptimeError

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class NotLeaderError(GreptimeError):
    def __init__(self, leader_id: Optional[int]) -> None:
        super().__init__(f"not the meta leader (leader hint: {leader_id})")
        self.leader_id = leader_id


class ProposeUncertainError(GreptimeError):
    """Commit could not be confirmed before the deadline. The entry may
    still commit later; retrying a non-idempotent op can double-apply."""

    def __init__(self) -> None:
        super().__init__("meta propose result unknown (no quorum ack "
                         "within the deadline); retry only idempotent ops")


class RaftNode:
    """One meta replica: consensus state + the applied KV dict."""

    def __init__(self, node_id: int, peer_ids: List[int],
                 *, store_path: Optional[str] = None,
                 election_timeout: Tuple[float, float] = (1.5, 3.0),
                 heartbeat_interval: float = 0.5,
                 compact_threshold: int = 256) -> None:
        self.node_id = node_id
        self.peer_ids = [p for p in peer_ids if p != node_id]
        self.transports: Dict[int, Any] = {}   # peer id -> transport
        self.store_path = store_path
        self._el_lo, self._el_hi = election_timeout
        self._hb_every = heartbeat_interval
        #: compact once the applied log tail exceeds this many entries —
        #: bounds both memory and the bytes rewritten per append (etcd
        #: compacts its revision history the same way,
        #: src/meta-srv/src/service/store/etcd.rs)
        self.compact_threshold = compact_threshold

        self._lock = threading.RLock()
        self._applied = threading.Condition(self._lock)
        # persistent
        self.term = 0
        self.voted_for: Optional[int] = None
        #: log[k] holds GLOBAL index base + k + 1; entries at or below
        #: `base` live only in the snapshot (state-at-base)
        self.log: List[dict] = []              # {term, op}
        self.base = 0                          # last compacted global idx
        self.snapshot_term = 0                 # term of the entry at base
        # volatile
        self.role = FOLLOWER
        self.leader_id: Optional[int] = None
        self.commit_idx = 0                    # global committed index
        self.applied_idx = 0
        self.state: Dict[str, bytes] = {}
        self.next_idx: Dict[int, int] = {}
        self._last_heard = time.monotonic()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        if store_path and os.path.exists(store_path):
            self._load()

    # ---- lifecycle ----
    def start(self) -> None:
        from ..common.runtime import new_thread
        self._stop.clear()
        t = new_thread(self._ticker, daemon=True,
                       name=f"raft-{self.node_id}",
                       propagate_context=False)
        t.start()
        self._threads = [t]

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []
        with self._lock:
            # a stopped node serves nothing: drop leadership so stale
            # reads/writes fail over instead of answering from a corpse
            self.role = FOLLOWER
            self.leader_id = None

    # ---- global-index helpers (caller holds the lock) ----
    def _last_index(self) -> int:
        return self.base + len(self.log)

    def _term_at(self, gidx: int) -> int:
        if gidx <= self.base:
            return self.snapshot_term if gidx == self.base else 0
        return self.log[gidx - self.base - 1]["term"]

    # ---- persistence ----
    def _write_json(self, path: str, doc: dict) -> None:
        # no fsync: this persists on EVERY log append and the raft quorum
        # (not the disk) is the durability story — the atomic rename alone
        # guarantees a reader never sees a half-written doc
        from ..utils import atomic_write
        atomic_write(path, json.dumps(doc), fsync=False,
                     tmp_prefix=".raft-")

    def _persist_locked(self) -> None:
        """Persist term/vote and the (compaction-bounded) log tail. The
        snapshot file carries everything at or below `base`, so each
        append rewrites at most compact_threshold entries — not the
        whole history."""
        if not self.store_path:
            return
        self._write_json(self.store_path, {
            "term": self.term, "voted_for": self.voted_for,
            "base": self.base, "snapshot_term": self.snapshot_term,
            "enc": "latin-1", "log": self.log})

    def _persist_snapshot_locked(self) -> None:
        if not self.store_path:
            return
        self._write_json(self.store_path + ".snap", {
            "base": self.base, "snapshot_term": self.snapshot_term,
            "state": {k: v.decode("latin-1")
                      for k, v in self.state.items()}})

    def _load(self) -> None:
        snap_path = self.store_path + ".snap"
        if os.path.exists(snap_path):
            with open(snap_path) as f:
                snap = json.load(f)
            self.base = snap["base"]
            self.snapshot_term = snap.get("snapshot_term", 0)
            self.state = {k: v.encode("latin-1")
                          for k, v in snap["state"].items()}
            self.commit_idx = self.applied_idx = self.base
        with open(self.store_path) as f:
            doc = json.load(f)
        self.term = doc["term"]
        self.voted_for = doc.get("voted_for")
        log = doc["log"]
        if doc.get("enc") != "latin-1":
            # pre-compaction logs stored values utf-8-decoded; re-bridge
            # them to the latin-1 byte-preserving representation so
            # replay applies identical bytes
            log = [self._upgrade_entry(e) for e in log]
        log_base = doc.get("base", 0)
        if log_base < self.base:
            # snapshot advanced past the log file (crash between the two
            # writes — snap is always written first): drop the overlap
            drop = self.base - log_base
            log = log[drop:] if drop < len(log) else []
        elif log_base > self.base:
            # the log references compacted entries and no snapshot covers
            # them: refusing loudly beats silently serving an empty state
            # (and install-snapshotting that emptiness onto followers)
            raise GreptimeError(
                f"raft store {self.store_path!r} has log base {log_base} "
                f"but no snapshot at or beyond it ({self.base}); refusing "
                f"to start from a truncated history")
        self.log = log

    @staticmethod
    def _upgrade_entry(entry: dict) -> dict:
        """Re-encode a legacy (utf-8-bridged) log entry's value strings
        into the latin-1 byte-preserving representation."""
        def bridge(s: object) -> object:
            return s.encode("utf-8").decode("latin-1") \
                if isinstance(s, str) else s

        op = dict(entry.get("op") or {})
        for k in ("value", "expect"):
            if op.get(k) is not None:
                op[k] = bridge(op[k])
        if op.get("guard"):
            g = dict(op["guard"])
            if g.get("expect") is not None:
                g["expect"] = bridge(g["expect"])
            op["guard"] = g
        if op.get("ops"):
            op["ops"] = [(sub, k, bridge(v)) for sub, k, v in op["ops"]]
        out = dict(entry)
        out["op"] = op
        return out

    # ---- timers ----
    def _election_deadline(self) -> float:
        return self._last_heard + random.uniform(self._el_lo, self._el_hi)

    def _ticker(self) -> None:
        while not self._stop.is_set():
            time.sleep(self._hb_every / 2)
            with self._lock:
                role = self.role
                expired = time.monotonic() > self._election_deadline()
            if role == LEADER:
                self._broadcast_heartbeat()
            elif expired:
                self._run_election()

    # ---- election ----
    def _run_election(self) -> None:
        with self._lock:
            self.role = CANDIDATE
            self.term += 1
            self.voted_for = self.node_id
            self.leader_id = None
            self._last_heard = time.monotonic()
            term = self.term
            last_idx = self._last_index()
            last_term = self._term_at(last_idx)
            self._persist_locked()
        votes = 1
        for pid in self.peer_ids:
            tr = self.transports.get(pid)
            if tr is None:
                continue
            try:
                resp = tr.request_vote(term=term, candidate=self.node_id,
                                       last_idx=last_idx,
                                       last_term=last_term)
            except Exception:  # noqa: BLE001 — unreachable peer ≠ lost
                # election; the quorum math below absorbs it
                increment_counter("raft_rpc_errors")
                continue
            with self._lock:
                if resp["term"] > self.term:
                    self._step_down(resp["term"])
                    return
            if resp.get("granted"):
                votes += 1
        quorum = (len(self.peer_ids) + 1) // 2 + 1
        with self._lock:
            if self.role != CANDIDATE or self.term != term:
                return
            if votes >= quorum:
                self.role = LEADER
                self.leader_id = self.node_id
                self.next_idx = {p: self._last_index()
                                 for p in self.peer_ids}
                # a no-op in the new term lets prior-term entries commit
                # (raft §5.4.2: only current-term entries count quorum)
                self.log.append({"term": self.term, "op": {"kind": "noop"}})
                self._persist_locked()
        if self.role == LEADER:
            self._broadcast_heartbeat()

    def _step_down(self, term: int) -> None:
        # caller holds the lock
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist_locked()
        self.role = FOLLOWER
        self._last_heard = time.monotonic()

    # ---- RPC handlers (called by peers' transports) ----
    def handle_request_vote(self, term: int, candidate: int, last_idx: int,
                            last_term: int) -> dict:
        with self._lock:
            if term > self.term:
                self._step_down(term)
            granted = False
            if term == self.term and self.voted_for in (None, candidate):
                my_last = self._last_index()
                my_last_term = self._term_at(my_last)
                up_to_date = (last_term, last_idx) >= (my_last_term,
                                                       my_last)
                if up_to_date:
                    granted = True
                    self.voted_for = candidate
                    self._last_heard = time.monotonic()
                    self._persist_locked()
            return {"term": self.term, "granted": granted}

    def handle_append_entries(self, term: int, leader: int, prev_idx: int,
                              prev_term: int, entries: List[dict],
                              commit_idx: int) -> dict:
        with self._lock:
            if term < self.term:
                return {"term": self.term, "ok": False}
            if term > self.term or self.role != FOLLOWER:
                self._step_down(term)
            self.leader_id = leader
            self._last_heard = time.monotonic()
            if prev_idx < self.base:
                # everything at or below base is committed + applied via
                # the snapshot: skip the already-covered prefix
                drop = self.base - prev_idx
                if drop >= len(entries):
                    return {"term": self.term, "ok": True}
                entries = entries[drop:]
                prev_idx = self.base
                prev_term = self.snapshot_term
            # log matching: the entry before the new ones must agree
            if prev_idx > self._last_index() or (
                    prev_idx > self.base and
                    self._term_at(prev_idx) != prev_term):
                return {"term": self.term, "ok": False,
                        "have": min(self._last_index(), prev_idx)}
            if entries:
                # truncate only from the first genuinely conflicting
                # entry (term mismatch): a delayed, shorter AppendEntries
                # must not erase newer entries a later RPC already
                # appended (raft §5.3 — committed suffixes survive)
                changed = False
                for i, ent in enumerate(entries):
                    k = prev_idx + i - self.base      # 0-based log slot
                    if k >= len(self.log):
                        self.log.extend(entries[i:])
                        changed = True
                        break
                    if self.log[k]["term"] != ent["term"]:
                        self.log = self.log[:k] + list(entries[i:])
                        changed = True
                        break
                if changed:
                    self._persist_locked()
            if commit_idx > self.commit_idx:
                self.commit_idx = min(commit_idx, self._last_index())
                self._apply_locked()
            return {"term": self.term, "ok": True}

    def handle_install_snapshot(self, term: int, leader: int, base: int,
                                snapshot_term: int,
                                state: Dict[str, str]) -> dict:
        """Replace this follower's prefix with the leader's applied
        snapshot — sent when the leader has compacted away the entries
        the follower still needs (raft §7 InstallSnapshot)."""
        with self._lock:
            if term < self.term:
                return {"term": self.term, "ok": False}
            if term > self.term or self.role != FOLLOWER:
                self._step_down(term)
            self.leader_id = leader
            self._last_heard = time.monotonic()
            if base <= self.applied_idx:
                return {"term": self.term, "ok": True}
            # keep a log tail that extends beyond the snapshot only when
            # it provably continues it (the entry AT base must carry the
            # snapshot's term); otherwise it is an uncommitted branch
            keep = base - self.base
            if keep < len(self.log) and \
                    self.log[keep - 1]["term"] == snapshot_term:
                self.log = self.log[keep:]
            else:
                self.log = []
            self.state = {k: v.encode("latin-1") for k, v in state.items()}
            self.base = base
            self.snapshot_term = snapshot_term
            self.applied_idx = base
            self.commit_idx = max(self.commit_idx, base)
            self._persist_snapshot_locked()
            self._persist_locked()
            self._applied.notify_all()
            return {"term": self.term, "ok": True}

    # ---- replication ----
    def _broadcast_heartbeat(self) -> None:
        self._replicate()

    def _replicate(self) -> bool:
        """Push log tails to every follower; recompute commit_idx.
        Returns True when a majority matches the leader's log."""
        with self._lock:
            if self.role != LEADER:
                return False
            term = self.term
            total = self._last_index()
        acked = 1
        for pid in self.peer_ids:
            tr = self.transports.get(pid)
            if tr is None:
                continue
            for _ in range(8):   # walk next_idx back on mismatch
                with self._lock:
                    if self.role != LEADER or self.term != term:
                        return False
                    nxt = self.next_idx.get(pid, total)
                    snap = None
                    if nxt < self.base:
                        # the tail this follower needs is compacted away:
                        # ship the applied snapshot instead, then resume
                        # normal appends from its index
                        snap = (self.applied_idx,
                                self._term_at(self.applied_idx),
                                {k: v.decode("latin-1")
                                 for k, v in self.state.items()})
                    else:
                        prev_idx = nxt
                        prev_term = self._term_at(nxt)
                        entries = self.log[nxt - self.base:
                                           total - self.base]
                        commit = self.commit_idx
                try:
                    if snap is not None:
                        resp = tr.install_snapshot(
                            term=term, leader=self.node_id, base=snap[0],
                            snapshot_term=snap[1], state=snap[2])
                    else:
                        resp = tr.append_entries(
                            term=term, leader=self.node_id,
                            prev_idx=prev_idx, prev_term=prev_term,
                            entries=entries, commit_idx=commit)
                except Exception:  # noqa: BLE001 — follower unreachable:
                    # end this round, the next tick retries from next_idx
                    increment_counter("raft_rpc_errors")
                    break
                with self._lock:
                    if resp["term"] > self.term:
                        self._step_down(resp["term"])
                        return False
                    if snap is not None:
                        if resp.get("ok"):
                            self.next_idx[pid] = snap[0]
                            continue   # follow with the remaining tail
                        break
                    if resp.get("ok"):
                        self.next_idx[pid] = total
                        acked += 1
                        break
                    self.next_idx[pid] = min(
                        resp.get("have", max(nxt - 1, 0)), total)
        quorum = (len(self.peer_ids) + 1) // 2 + 1
        with self._lock:
            if self.role != LEADER or self.term != term:
                return False
            # only an index whose entry is from the current term may
            # advance the commit point (raft §5.4.2); the election no-op
            # guarantees such an entry exists promptly
            if acked >= quorum and total > self.commit_idx \
                    and total > self.base \
                    and self._term_at(total) == self.term:
                self.commit_idx = total
                self._apply_locked()
            return acked >= quorum

    # ---- state machine ----
    def _apply_locked(self) -> None:
        while self.applied_idx < self.commit_idx:
            entry = self.log[self.applied_idx - self.base]
            entry["result"] = self._apply_op(entry["op"])
            self.applied_idx += 1
        self._applied.notify_all()
        if len(self.log) > self.compact_threshold \
                and self.applied_idx > self.base:
            self._compact_locked()

    def _compact_locked(self) -> None:
        """Fold the applied log prefix into the snapshot: state is
        already AT applied_idx, so compaction is a copy-free truncation
        plus one snapshot write. Bounds memory and per-append persist
        cost; lagging followers past the horizon get InstallSnapshot."""
        cut = self.applied_idx - self.base
        self.snapshot_term = self.log[cut - 1]["term"]
        self.log = self.log[cut:]
        self.base = self.applied_idx
        self._persist_snapshot_locked()
        self._persist_locked()

    def _apply_op(self, op: dict) -> object:
        kind = op["kind"]
        key = op.get("key")
        if kind == "put":
            self.state[key] = op["value"].encode("latin-1")
            return True
        if kind == "delete":
            return self.state.pop(key, None) is not None
        if kind == "cap":                      # compare_and_put
            expect = op["expect"].encode("latin-1") \
                if op["expect"] is not None else None
            if self.state.get(key) != expect:
                return False
            self.state[key] = op["value"].encode("latin-1")
            return True
        if kind == "cad":                      # compare_and_delete
            if self.state.get(key) != op["expect"].encode("latin-1"):
                return False
            del self.state[key]
            return True
        if kind == "incr":
            cur = int(self.state.get(key, str(op["start"]).encode()))
            nxt = cur + 1
            self.state[key] = str(nxt).encode()
            return nxt
        if kind == "batch":
            guard = op.get("guard")
            if guard is not None:
                expect = guard["expect"].encode("latin-1") \
                    if guard["expect"] is not None else None
                if self.state.get(guard["key"]) != expect:
                    return False
            for sub, k, v in op["ops"]:
                if sub == "put":
                    self.state[k] = v.encode("latin-1")
                elif sub == "delete":
                    self.state.pop(k, None)
                else:
                    # mirrors MemKv._apply_batch_locked; ReplicatedKv.batch
                    # validates at propose time so this can't enter the log
                    raise GreptimeError(f"unknown batch sub-op {sub!r}")
            return True
        if kind == "noop":
            return None
        raise GreptimeError(f"unknown raft op {kind!r}")

    # ---- client entry ----
    def propose(self, op: dict, timeout: float = 10.0) -> object:
        """Append on the leader, replicate to a majority, apply, return
        the op result. Raises NotLeaderError elsewhere, and
        ProposeUncertainError when commit cannot be confirmed in time —
        the entry may still commit later, so blind retries of
        non-idempotent ops (CAS, incr) are not safe on that error."""
        with self._lock:
            if self.role != LEADER:
                raise NotLeaderError(self.leader_id)
            entry = {"term": self.term, "op": op}
            self.log.append(entry)
            idx = self._last_index()
            self._persist_locked()
        self._replicate()   # best effort; heartbeats keep pushing
        with self._lock:
            deadline = time.monotonic() + timeout
            while True:
                if self.applied_idx >= idx:
                    # the entry object survives compaction, so its result
                    # is readable even after the log slot is truncated
                    return entry.get("result")
                lost = idx > self._last_index() or (
                    idx > self.base and
                    self.log[idx - self.base - 1] is not entry)
                if lost:
                    # a new leader overwrote the uncommitted entry
                    raise NotLeaderError(self.leader_id
                                         if self.leader_id != self.node_id
                                         else None)
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._applied.wait(
                        timeout=min(remaining, self._hb_every)):
                    if time.monotonic() >= deadline:
                        raise ProposeUncertainError()

    def read_state(self) -> Dict[str, bytes]:
        with self._lock:
            if self.role != LEADER:
                raise NotLeaderError(self.leader_id)
            return dict(self.state)

    def get_value(self, key: str) -> Optional[bytes]:
        """Single-key leader read without copying the state dict."""
        with self._lock:
            if self.role != LEADER:
                raise NotLeaderError(self.leader_id)
            return self.state.get(key)

    def range_prefix(self, prefix: str) -> List[Tuple[str, bytes]]:
        """Prefix scan on the leader, materializing only the matches."""
        with self._lock:
            if self.role != LEADER:
                raise NotLeaderError(self.leader_id)
            return sorted((k, v) for k, v in self.state.items()
                          if k.startswith(prefix))

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self.role == LEADER


class LocalTransport:
    """Direct in-process transport (the MemKv of transports)."""

    def __init__(self, node: RaftNode) -> None:
        self.node = node

    def request_vote(self, **kw: object) -> dict:
        return self.node.handle_request_vote(**kw)

    def append_entries(self, **kw: object) -> dict:
        return self.node.handle_append_entries(**kw)

    def install_snapshot(self, **kw: object) -> dict:
        return self.node.handle_install_snapshot(**kw)


def connect_local(nodes: List[RaftNode]) -> None:
    for a in nodes:
        for b in nodes:
            if a is not b:
                a.transports[b.node_id] = LocalTransport(b)


class FlightTransport:
    """Raft RPCs over the meta Flight plane (meta/flight.py actions
    raft_request_vote / raft_append_entries) for multi-process meta."""

    def __init__(self, address: str) -> None:
        self.address = address
        self._client = None

    def _action(self, kind: str, body: dict) -> dict:
        import json as _json

        import pyarrow.flight as flight
        if self._client is None:
            self._client = flight.FlightClient(self.address)
        results = list(self._client.do_action(
            flight.Action(kind, _json.dumps(body).encode())))
        resp = _json.loads(results[0].body.to_pybytes())
        if not resp.get("ok", False):
            raise GreptimeError(resp.get("error", "meta raft rpc failed"))
        return resp

    def request_vote(self, **kw: object) -> dict:
        return self._action("raft_request_vote", kw)

    def append_entries(self, **kw: object) -> dict:
        return self._action("raft_append_entries", kw)

    def install_snapshot(self, **kw: object) -> dict:
        return self._action("raft_install_snapshot", kw)


class HaMetaClient:
    """MetaClient facade over several replicated MetaSrv instances:
    every call retries across servers until it lands on the leader
    (reference clients iterate etcd endpoints the same way)."""

    def __init__(self, srvs: "List[object]", *, retry_delay: float = 0.15,
                 max_rounds: int = 40) -> None:
        from .service import MetaClient
        self.clients = [MetaClient(s) for s in srvs]
        self._cur = 0
        self._delay = retry_delay
        self._rounds = max_rounds

    def __getattr__(self, name: str) -> object:
        if name.startswith("_"):
            raise AttributeError(name)

        def call(*args: object, **kwargs: object) -> object:
            last: Optional[Exception] = None
            for _ in range(self._rounds):
                client = self.clients[self._cur % len(self.clients)]
                try:
                    return getattr(client, name)(*args, **kwargs)
                except NotLeaderError as e:
                    last = e
                    self._cur += 1
                    time.sleep(self._delay)
            raise last if last is not None else GreptimeError(
                "no meta leader reachable")
        return call


class ReplicatedKv:
    """MemKv-interface facade over a RaftNode, so MetaSrv mounts a
    replicated store exactly like MemKv/FileKv (meta/kv.py)."""

    def __init__(self, node: RaftNode) -> None:
        self.node = node

    # reads (leader-local, linearizable after majority-committed writes)
    def get(self, key: str) -> Optional[bytes]:
        return self.node.get_value(key)

    def range(self, prefix: str) -> List[Tuple[str, bytes]]:
        return self.node.range_prefix(prefix)

    # writes (consensus round-trips)
    def put(self, key: str, value: bytes) -> None:
        # latin-1 maps bytes<->str 1:1, so arbitrary (non-UTF-8) values
        # survive the JSON-encoded raft log — matching MemKv/FileKv
        self.node.propose({"kind": "put", "key": key,
                           "value": value.decode("latin-1")})

    def delete(self, key: str) -> bool:
        return bool(self.node.propose({"kind": "delete", "key": key}))

    def compare_and_put(self, key: str, expect: Optional[bytes],
                        value: bytes) -> bool:
        return bool(self.node.propose({
            "kind": "cap", "key": key,
            "expect": expect.decode("latin-1") if expect is not None
            else None,
            "value": value.decode("latin-1")}))

    def compare_and_delete(self, key: str, expect: bytes) -> bool:
        return bool(self.node.propose({
            "kind": "cad", "key": key,
            "expect": expect.decode("latin-1")}))

    def incr(self, key: str, start: int = 0) -> int:
        return int(self.node.propose({"kind": "incr", "key": key,
                                      "start": start}))

    def batch(self, ops: List[Tuple[str, str, Optional[bytes]]],
              guard: Optional[Tuple[str, Optional[bytes]]] = None
              ) -> bool:
        for op, k, v in ops:        # reject bad ops BEFORE they hit the log
            if op not in ("put", "delete"):
                raise ValueError(f"unknown batch op {op!r}")
            if op == "put" and not isinstance(v, bytes):
                raise ValueError(f"batch put needs bytes for {k!r}")
        g = None
        if guard is not None:
            g = {"key": guard[0],
                 "expect": guard[1].decode("latin-1")
                 if guard[1] is not None else None}
        return bool(self.node.propose({
            "kind": "batch", "guard": g,
            "ops": [(op, k, v.decode("latin-1") if v is not None
                     else None) for op, k, v in ops]}))
