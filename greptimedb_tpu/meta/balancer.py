"""RegionBalancer: meta-srv's elastic region control loop.

ROADMAP item 1 — partition layouts stop being frozen at CREATE TABLE.
A leader-only cooperative tick (`tick()`; cmd/main wraps it in a
RepeatedTask outside pytest, the FlowManager/SelfMonitor pattern) watches
the heartbeat-fed region heat (`MetaSrv.region_heat`) and lease state and
drives three multi-step, crash-safe region operations:

- **split** — a region crossing the size/ingest-rate threshold refines
  its RANGE partition rule into two child regions ON ITS OWNER (copy →
  fence → delta copy → atomic rule+route commit → swap), so a hot shard
  stops being hot forever; placement can then move a child elsewhere.
- **migrate** — snapshot the region's SSTs via the shared object store
  (flush), fence the source (it can never again ack a write the target
  misses — PR 4's adoption fencing discipline, now with a durable
  node-local marker), ship the WAL tail through the op doc, replay it on
  the target, then commit the route and release the source. Only the
  fenced window stalls writes.
- **rebalance** — a placement pass moves regions off hot/suspect/
  overloaded datanodes toward the least-loaded alive ones (the
  load_based selector's heat, applied continuously instead of only at
  CREATE TABLE).
- **replica add/remove** (PR 19) — bootstrap a read replica of a region
  on another datanode (flush-snapshot → WAL-tail bootstrap through the
  op doc → standby attach → atomic route commit → continuous-shipping
  wire-up) or detach one (route commit first, then drop). Followers
  serve bounded-staleness reads and are the failover promotion pool.

Every operation is a resumable state machine persisted in the meta KV
under ``__balancer/`` (the ``__flow/`` durability pattern): each step is
one idempotent datanode mailbox message (datanode/instance.py handlers)
acked through ``balancer_ack``, and each transition is one KV write —
the route/rule **commit is a single atomic KV batch** — so a meta crash
mid-migration resumes exactly where it stopped, and a pre-commit failure
rolls back (unfence / abort-split). Frontends learn about moved regions
lazily: a stale-route RPC raises the typed StaleRouteError and the
DistTable refreshes + retries (frontend/distributed.py).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import TYPE_CHECKING, Callable, Dict, List, Optional, Tuple

from ..common import failpoint as _fp
from ..common.runtime import env_int
from ..errors import GreptimeError, InvalidArgumentsError
from .service import Peer, RegionRoute, ROUTE_PREFIX, TINFO_PREFIX

if TYPE_CHECKING:  # circular at runtime: service constructs the balancer
    from .service import MetaSrv

logger = logging.getLogger(__name__)

_fp.register("balancer_route_commit")

OP_PREFIX = "__balancer/op/"
DONE_PREFIX = "__balancer/done/"
SEQ_KEY = "__balancer/seq"

#: op states that precede the route/rule commit: a failure there rolls
#: back; every later state must roll FORWARD (the route already moved)
_PRE_COMMIT = {"snapshot", "fence", "open", "prepare", "catchup",
               "bootstrap", "attach"}

#: op state -> the mailbox message type whose ack advances it
_STEP_MSG = {
    ("migrate", "snapshot"): "balancer_snapshot",
    ("migrate", "fence"): "balancer_fence",
    ("migrate", "open"): "balancer_open",
    ("migrate", "release"): "balancer_release",
    ("split", "prepare"): "balancer_split_prepare",
    ("split", "catchup"): "balancer_split_catchup",
    ("split", "apply"): "balancer_split_apply",
    ("replica_add", "snapshot"): "balancer_snapshot",
    ("replica_add", "bootstrap"): "repl_bootstrap",
    ("replica_add", "attach"): "repl_attach",
    ("replica_add", "wire"): "repl_set_followers",
    ("replica_remove", "drop"): "repl_drop",
    ("replica_remove", "wire"): "repl_set_followers",
}


class RegionBalancer:
    """Leader-only control loop over one MetaSrv's KV + heartbeat state."""

    def __init__(self, srv: "MetaSrv",
                 is_leader_fn: Optional[Callable[[], bool]] = None
                 ) -> None:
        self.srv = srv
        #: None = always leader (single metasrv / in-process tests)
        self.is_leader_fn = is_leader_fn
        # knobs (SET balancer_* forwards here; GREPTIME_BALANCER_* seeds)
        self.enabled = env_int("GREPTIME_BALANCER_ENABLED", 1) != 0
        self.split_size_bytes = env_int(
            "GREPTIME_BALANCER_SPLIT_SIZE_BYTES", 1 << 30)
        self.split_rate_rps = env_int(
            "GREPTIME_BALANCER_SPLIT_RATE_RPS", 0)
        self.rebalance_threshold = env_int(
            "GREPTIME_BALANCER_REBALANCE_THRESHOLD", 2)
        self.max_inflight = env_int("GREPTIME_BALANCER_MAX_INFLIGHT", 4)
        self.step_timeout_s = float(env_int(
            "GREPTIME_BALANCER_STEP_TIMEOUT_S", 300))
        self.resend_interval_s = 5.0
        from ..common.locks import TrackedLock
        from ..common.tracking import tracked_state
        #: (op_id, msg_type) -> ack dict; heartbeat threads write, the
        #: tick thread consumes
        self._acks: Dict[Tuple[str, str], dict] = tracked_state(
            {}, "meta.balancer.acks")
        self._acks_lock = TrackedLock("meta.balancer_acks")
        #: (op_id, msg_type) -> monotonic last-send time (in-memory only:
        #: after a meta restart every current step re-sends immediately,
        #: which is safe because steps are idempotent). Tick-thread only —
        #: unlike _acks it has exactly one writer, so no lock
        self._sent: Dict[Tuple[str, str], float] = tracked_state(
            {}, "meta.balancer.sent")

    # ------------------------------------------------------------------
    # knobs
    # ------------------------------------------------------------------
    KNOBS = ("enabled", "split_size_bytes", "split_rate_rps",
             "rebalance_threshold", "max_inflight", "step_timeout_s")

    def configure(self, knob: str, value: object) -> None:
        """SET balancer_<knob> = value (both frontends forward here)."""
        if knob not in self.KNOBS:
            raise InvalidArgumentsError(
                f"unknown balancer knob {knob!r} (have: "
                f"{', '.join(self.KNOBS)})")
        try:
            num = float(value)
        except (TypeError, ValueError):
            raise InvalidArgumentsError(
                f"balancer_{knob}: expected a number, got {value!r}")
        if knob == "enabled":
            self.enabled = num != 0
        elif knob == "step_timeout_s":
            self.step_timeout_s = max(1.0, num)
        else:
            setattr(self, knob, max(0, int(num)))
        logger.info("balancer knob %s = %r", knob, value)

    # ------------------------------------------------------------------
    # op store
    # ------------------------------------------------------------------
    def _alloc_id(self) -> str:
        return f"bop-{self.srv.kv.incr(SEQ_KEY):06d}"

    def _save(self, op: dict) -> None:
        op["updated_ms"] = int(time.time() * 1000)
        # first-entry timestamp per state: bench.py derives the fenced
        # handoff window (open → release) from these
        op.setdefault("times", {}).setdefault(op["state"],
                                              op["updated_ms"])
        self.srv.kv.put(f"{OP_PREFIX}{op['id']}",
                        json.dumps(op).encode())

    def ops(self) -> List[dict]:
        """In-flight operations, oldest first."""
        return [json.loads(v) for _, v in self.srv.kv.range(OP_PREFIX)]

    def done_ops(self) -> List[dict]:
        return [json.loads(v) for _, v in self.srv.kv.range(DONE_PREFIX)]

    def op(self, op_id: str) -> Optional[dict]:
        raw = self.srv.kv.get(f"{OP_PREFIX}{op_id}") or \
            self.srv.kv.get(f"{DONE_PREFIX}{op_id}")
        return json.loads(raw) if raw is not None else None

    def _finish(self, op: dict, state: str, error: Optional[str] = None
                ) -> None:
        from ..common.telemetry import increment_counter
        op["state"] = state
        if error:
            op["error"] = error
        op["updated_ms"] = int(time.time() * 1000)
        op.setdefault("times", {}).setdefault(state, op["updated_ms"])
        self.srv.kv.batch([
            ("put", f"{DONE_PREFIX}{op['id']}",
             json.dumps(op).encode()),
            ("delete", f"{OP_PREFIX}{op['id']}", None)])
        # purge the op's ack/send memos: unconsumed acks (rollback steps,
        # late arrivals after a timeout-abort) would otherwise accumulate
        # forever on a long-lived leader
        with self._acks_lock:
            for key in [k for k in self._acks if k[0] == op["id"]]:
                del self._acks[key]
        for key in [k for k in self._sent if k[0] == op["id"]]:
            del self._sent[key]
        increment_counter("balancer_ops_completed" if state == "done"
                          else "balancer_ops_failed")
        logger.info("balancer op %s (%s %s region %s) -> %s%s",
                    op["id"], op["kind"], op["table"], op["region"],
                    state, f": {error}" if error else "")

    def _inflight_tables(self) -> Dict[str, str]:
        return {o["table"]: o["id"] for o in self.ops()}

    # ------------------------------------------------------------------
    # admin entrypoints (ADMIN MIGRATE/SPLIT/REBALANCE; MetaSrv wraps)
    # ------------------------------------------------------------------
    def migrate(self, full_name: str, region: int, to_node: int,
                auto: bool = False) -> dict:
        from ..common.telemetry import increment_counter
        route = self.srv.table_route(full_name)
        if route is None:
            raise GreptimeError(f"table {full_name} has no route")
        rr = next((r for r in route.region_routes
                   if r.region_number == region), None)
        if rr is None:
            raise InvalidArgumentsError(
                f"region {region} is not in the route of {full_name} "
                f"(have {[r.region_number for r in route.region_routes]})")
        if self.srv.peer(to_node) is None:
            raise InvalidArgumentsError(
                f"datanode {to_node} is not registered")
        if rr.leader.id == to_node:
            raise InvalidArgumentsError(
                f"region {region} of {full_name} is already on datanode "
                f"{to_node}")
        self._check_can_enqueue(full_name)
        catalog, schema, table = full_name.split(".", 2)
        op = {
            "id": self._alloc_id(), "kind": "migrate",
            "catalog": catalog, "schema": schema, "table": full_name,
            "table_short": table, "region": int(region),
            "from_node": int(rr.leader.id), "to_node": int(to_node),
            "state": "snapshot", "wal_tail": None, "auto": bool(auto),
            "created_ms": int(time.time() * 1000),
        }
        self._save(op)
        increment_counter("balancer_ops_started")
        increment_counter("balancer_migrations_started")
        logger.info("balancer: enqueued %s — migrate region %s of %s "
                    "from dn%d to dn%d%s", op["id"], region, full_name,
                    op["from_node"], to_node, " (auto)" if auto else "")
        return op

    def split(self, full_name: str, region: int,
              at_value: object = None,
              auto: bool = False) -> dict:
        from ..common.telemetry import increment_counter
        from ..mito.engine import _deserialize_rule
        from ..partition.rule import refine_range_rule
        route = self.srv.table_route(full_name)
        if route is None:
            raise GreptimeError(f"table {full_name} has no route")
        rr = next((r for r in route.region_routes
                   if r.region_number == region), None)
        if rr is None:
            raise InvalidArgumentsError(
                f"region {region} is not in the route of {full_name}")
        info = self.srv.table_info(full_name)
        rule_doc = (info or {}).get("meta", {}).get("partition_rule")
        if rule_doc is None:
            raise InvalidArgumentsError(
                f"table {full_name} has no partition rule; single-region "
                f"tables cannot split (recreate with PARTITION BY RANGE)")
        rule = _deserialize_rule(rule_doc)
        from ..partition.rule import (
            HashPartitionRule, RangeColumnsPartitionRule)
        if isinstance(rule, HashPartitionRule):
            raise InvalidArgumentsError(
                f"table {full_name} is hash-partitioned; one hash bucket "
                f"cannot split locally (the modulus is global)")
        if isinstance(rule, RangeColumnsPartitionRule) and \
                len(rule.columns) > 1:
            raise InvalidArgumentsError(
                f"table {full_name} partitions on multiple columns; "
                f"only single-column range rules split")
        taken = {r.region_number for r in route.region_routes} | \
            set(rule.region_numbers())
        children = [max(taken) + 1, max(taken) + 2]
        if at_value is not None:
            # validate NOW so ADMIN SPLIT errors synchronously on a value
            # outside the region's range (the datanode probe handles the
            # at_value=None case)
            try:
                refine_range_rule(rule, region, at_value, children)
            except ValueError as e:
                raise InvalidArgumentsError(str(e))
        self._check_can_enqueue(full_name)
        catalog, schema, table = full_name.split(".", 2)
        op = {
            "id": self._alloc_id(), "kind": "split",
            "catalog": catalog, "schema": schema, "table": full_name,
            "table_short": table, "region": int(region),
            "node": int(rr.leader.id), "children": children,
            "at_value": at_value, "snapshot_seq": None,
            "state": "prepare", "auto": bool(auto),
            "created_ms": int(time.time() * 1000),
        }
        self._save(op)
        increment_counter("balancer_ops_started")
        increment_counter("balancer_splits_started")
        logger.info("balancer: enqueued %s — split region %s of %s into "
                    "%s at %r%s", op["id"], region, full_name, children,
                    at_value, " (auto)" if auto else "")
        return op

    def rebalance(self, full_name: Optional[str] = None,
                  auto: bool = False) -> List[dict]:
        """Move regions from the most- to the least-loaded alive nodes
        until the spread is <= 1 (admin) or <= rebalance_threshold
        (auto). Each move is an independent migrate op."""
        alive = self.srv.alive_datanodes()
        if len(alive) < 2:
            return []
        counts: Dict[int, int] = {p.id: 0 for p in alive}
        placed: Dict[int, List[Tuple[str, int]]] = {p.id: [] for p in alive}
        for route in self.srv.all_table_routes():
            if full_name is not None and route.table_name != full_name:
                continue
            for rr in route.region_routes:
                if rr.leader.id in counts:
                    counts[rr.leader.id] += 1
                    placed[rr.leader.id].append(
                        (route.table_name, rr.region_number))
        inflight = self._inflight_tables()
        floor = self.rebalance_threshold if auto else 1
        out: List[dict] = []
        while len(self.ops()) < self.max_inflight:
            hot = max(counts, key=lambda n: (counts[n], n))
            cold = min(counts, key=lambda n: (counts[n], -n))
            if counts[hot] - counts[cold] <= max(1, floor):
                break
            candidate = next(
                ((t, r) for t, r in placed[hot] if t not in inflight),
                None)
            if candidate is None:
                break
            table_name, region = candidate
            op = self.migrate(table_name, region, cold, auto=auto)
            out.append(op)
            inflight[table_name] = op["id"]
            placed[hot].remove(candidate)
            counts[hot] -= 1
            counts[cold] += 1
        if out:
            from ..common.telemetry import increment_counter
            increment_counter("balancer_rebalance_moves", len(out))
        return out

    def add_replica(self, full_name: str, region: int, to_node: int
                    ) -> dict:
        """ADMIN ADD REPLICA: bootstrap a read replica of the region on
        `to_node` (snapshot → WAL-tail bootstrap → standby attach →
        atomic route commit → shipper wire-up)."""
        from ..common.telemetry import increment_counter
        route = self.srv.table_route(full_name)
        if route is None:
            raise GreptimeError(f"table {full_name} has no route")
        rr = next((r for r in route.region_routes
                   if r.region_number == region), None)
        if rr is None:
            raise InvalidArgumentsError(
                f"region {region} is not in the route of {full_name} "
                f"(have {[r.region_number for r in route.region_routes]})")
        if self.srv.peer(to_node) is None:
            raise InvalidArgumentsError(
                f"datanode {to_node} is not registered")
        if rr.leader.id == to_node:
            raise InvalidArgumentsError(
                f"datanode {to_node} already leads region {region} of "
                f"{full_name}; a leader cannot follow itself")
        if any(f.id == to_node for f in rr.followers):
            raise InvalidArgumentsError(
                f"datanode {to_node} is already a replica of region "
                f"{region} of {full_name}")
        self._check_can_enqueue(full_name)
        catalog, schema, table = full_name.split(".", 2)
        op = {
            "id": self._alloc_id(), "kind": "replica_add",
            "catalog": catalog, "schema": schema, "table": full_name,
            "table_short": table, "region": int(region),
            "from_node": int(rr.leader.id), "to_node": int(to_node),
            "state": "snapshot", "wal_tail": None, "flushed_seq": 0,
            "created_ms": int(time.time() * 1000),
        }
        self._save(op)
        increment_counter("balancer_ops_started")
        increment_counter("balancer_replica_adds_started")
        logger.info("balancer: enqueued %s — add replica of region %s of "
                    "%s on dn%d (leader dn%d)", op["id"], region,
                    full_name, to_node, op["from_node"])
        return op

    def remove_replica(self, full_name: str, region: int, node: int
                       ) -> dict:
        """ADMIN REMOVE REPLICA: detach a follower — route commit first
        (reads stop scattering there), then drop its standby region."""
        from ..common.telemetry import increment_counter
        route = self.srv.table_route(full_name)
        if route is None:
            raise GreptimeError(f"table {full_name} has no route")
        rr = next((r for r in route.region_routes
                   if r.region_number == region), None)
        if rr is None:
            raise InvalidArgumentsError(
                f"region {region} is not in the route of {full_name}")
        if all(f.id != node for f in rr.followers):
            raise InvalidArgumentsError(
                f"datanode {node} is not a replica of region {region} of "
                f"{full_name} (followers: "
                f"{[f.id for f in rr.followers]})")
        self._check_can_enqueue(full_name)
        catalog, schema, table = full_name.split(".", 2)
        op = {
            "id": self._alloc_id(), "kind": "replica_remove",
            "catalog": catalog, "schema": schema, "table": full_name,
            "table_short": table, "region": int(region),
            "from_node": int(rr.leader.id), "to_node": int(node),
            "state": "commit",
            "created_ms": int(time.time() * 1000),
        }
        self._save(op)
        increment_counter("balancer_ops_started")
        increment_counter("balancer_replica_removes_started")
        logger.info("balancer: enqueued %s — remove replica of region %s "
                    "of %s from dn%d", op["id"], region, full_name, node)
        return op

    def _check_can_enqueue(self, full_name: str) -> None:
        inflight = self._inflight_tables()
        if full_name in inflight:
            raise InvalidArgumentsError(
                f"table {full_name} already has in-flight balancer "
                f"operation {inflight[full_name]}")

    # ------------------------------------------------------------------
    # acks (datanodes report step results here, via meta RPC)
    # ------------------------------------------------------------------
    def handle_ack(self, node_id: int, op_id: str, step: str, ok: bool,
                   error: Optional[str], payload: dict) -> None:
        with self._acks_lock:
            self._acks[(op_id, step)] = {
                "node": node_id, "ok": bool(ok), "error": error,
                "payload": payload or {}}

    def _take_ack(self, op_id: str, step: str) -> Optional[dict]:
        with self._acks_lock:
            return self._acks.pop((op_id, step), None)

    # ------------------------------------------------------------------
    # the control loop
    # ------------------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> dict:
        """Advance every in-flight op one step and run the auto policies.
        Cooperative: cmd/main wraps it in a RepeatedTask; tests call it
        directly. Errors are contained per op (background-loop safety)."""
        if self.is_leader_fn is not None and not self.is_leader_fn():
            return {"leader": False}
        from ..common.telemetry import span
        now = time.time() if now is None else now
        summary = {"leader": True, "advanced": 0, "auto_splits": 0,
                   "auto_moves": 0}
        from ..common import background_jobs
        with span("balancer_tick"):
            for op in self.ops():
                try:
                    # each op step is a background job rooting its own
                    # trace; the trace store ALWAYS retains traces that
                    # touched a balancer op (tail-sampling policy)
                    with background_jobs.job(
                            "balancer_op", table=op.get("table"),
                            region=str(op.get("region")),
                            op_id=op.get("id"), op_kind=op.get("kind"),
                            step=op.get("state")):
                        if self._advance(op, now):
                            summary["advanced"] += 1
                except Exception:  # noqa: BLE001 — one broken op must not
                    logger.exception(     # stall the whole control loop
                        "balancer op %s advance failed", op.get("id"))
            if self.enabled:
                try:
                    summary["auto_splits"] = len(self._auto_split(now))
                    summary["auto_moves"] = len(
                        self.rebalance(auto=True))
                except Exception:  # noqa: BLE001 — policy errors degrade
                    logger.exception("balancer auto policy failed")
        return summary

    def _advance(self, op: dict, now: float) -> bool:
        state = op["state"]
        if state == "commit":
            if op["kind"] == "migrate":
                self._commit_migrate(op)
            elif op["kind"] == "replica_add":
                self._commit_replica_add(op)
            elif op["kind"] == "replica_remove":
                self._commit_replica_remove(op)
            else:
                self._commit_split(op)
            return True
        msg_type = _STEP_MSG.get((op["kind"], state))
        if msg_type is None:
            logger.error("balancer op %s in unknown state %r; failing",
                         op["id"], state)
            self._finish(op, "failed", f"unknown state {state!r}")
            return True
        ack = self._take_ack(op["id"], msg_type)
        if ack is None:
            # pre-commit steps time out into a rollback; post-commit
            # steps retry forever (the route already moved — the only
            # way out is forward)
            age_s = (now * 1000 - op["updated_ms"]) / 1e3
            if state in _PRE_COMMIT and age_s > self.step_timeout_s:
                self._abort(op, f"step {state} timed out after "
                                f"{age_s:.0f}s")
                return True
            self._send_step(op, msg_type, now)
            return False
        if not ack["ok"]:
            if state in _PRE_COMMIT:
                self._abort(op, f"step {state} failed on dn"
                                f"{ack['node']}: {ack['error']}")
            else:
                # post-commit failure: log, clear the send memo so the
                # step re-mails, and keep rolling forward
                logger.error(
                    "balancer op %s post-commit step %s failed on dn%d "
                    "(%s); retrying", op["id"], state, ack["node"],
                    ack["error"])
                self._sent.pop((op["id"], msg_type), None)
            return True
        payload = ack["payload"]
        if op["kind"] == "migrate":
            self._migrate_on_ack(op, state, payload)
        elif op["kind"] in ("replica_add", "replica_remove"):
            self._replica_on_ack(op, state, payload)
        else:
            self._split_on_ack(op, state, payload)
        return True

    def _send_step(self, op: dict, msg_type: str, now: float) -> None:
        key = (op["id"], msg_type)
        last = self._sent.get(key)
        if last is not None and now - last < self.resend_interval_s:
            return
        if last is not None:
            from ..common.telemetry import increment_counter
            increment_counter("balancer_step_resends")
        self._sent[key] = now
        node, msg = self._build_step(op, msg_type)
        self.srv.send_mailbox(node, msg)

    def _build_step(self, op: dict, msg_type: str) -> Tuple[int, dict]:
        base = {"type": msg_type, "op_id": op["id"],
                "catalog": op["catalog"], "schema": op["schema"],
                "table": op["table_short"], "region": op["region"]}
        if op["kind"] == "migrate":
            if msg_type == "balancer_open":
                info = self.srv.table_info(op["table"])
                if info is None:
                    raise GreptimeError(
                        f"no table info for {op['table']} — cannot "
                        f"materialize the region on dn{op['to_node']}")
                return op["to_node"], {
                    **base, "table_info": info,
                    "wal_tail": op.get("wal_tail") or []}
            return op["from_node"], base
        if op["kind"] in ("replica_add", "replica_remove"):
            if msg_type == "repl_attach":
                info = self.srv.table_info(op["table"])
                if info is None:
                    raise GreptimeError(
                        f"no table info for {op['table']} — cannot "
                        f"materialize the standby on dn{op['to_node']}")
                return op["to_node"], {
                    **base, "table_info": info,
                    "wal_tail": op.get("wal_tail") or []}
            if msg_type == "repl_drop":
                return op["to_node"], base
            if msg_type == "repl_set_followers":
                # the re-wire targets the route's CURRENT leader with the
                # route's CURRENT follower set (a failover may have moved
                # either since the op was enqueued)
                route = self.srv.table_route(op["table"])
                rr = next((r for r in (route.region_routes
                                       if route else [])
                           if r.region_number == op["region"]), None)
                if rr is None:
                    raise GreptimeError(
                        f"route for region {op['region']} of "
                        f"{op['table']} vanished mid-op")
                return rr.leader.id, {
                    **base,
                    "followers": [f.to_dict() for f in rr.followers]}
            # balancer_snapshot / repl_bootstrap run on the leader
            return op["from_node"], base
        # split: every step runs on the owning node
        extra: dict = {"children": op["children"]}
        if msg_type == "balancer_split_prepare":
            extra["at_value"] = op.get("at_value")
        elif msg_type == "balancer_split_catchup":
            extra["at_value"] = op["at_value"]
            extra["snapshot_seq"] = op["snapshot_seq"]
        elif msg_type == "balancer_split_apply":
            extra["rule"] = op["rule_doc"]
        return op["node"], {**base, **extra}

    # ---- migrate transitions ----
    def _migrate_on_ack(self, op: dict, state: str, payload: dict
                        ) -> None:
        if state == "snapshot":
            op["state"] = "fence"
        elif state == "fence":
            # the tail persists IN THE OP DOC: a meta crash after this
            # point still holds everything the target needs to replay
            op["wal_tail"] = payload.get("wal_tail") or []
            op["state"] = "open"
        elif state == "open":
            op["state"] = "commit"
        elif state == "release":
            self._finish(op, "done")
            return
        self._save(op)

    def _commit_migrate(self, op: dict) -> None:
        """The migrate commit point: route leader flips to the target in
        ONE atomic KV batch with the op transition — a crash either left
        the route untouched (op re-commits) or moved it together with
        the op's advance to release (op resumes forward)."""
        from ..common.telemetry import increment_counter
        _fp.fail_point("balancer_route_commit")
        route = self.srv.table_route(op["table"])
        if route is None:
            self._finish(op, "failed", "route vanished before commit")
            return
        rr = next((r for r in route.region_routes
                   if r.region_number == op["region"]), None)
        if rr is None:
            self._finish(op, "failed", "region vanished before commit")
            return
        if rr.leader.id != op["from_node"]:
            # the region moved under the op (failover raced it before the
            # busy-table guard, or an operator intervened): committing
            # would orphan whatever the CURRENT leader acked — abort and
            # leave the live placement alone
            self._abort(op, f"region leader changed to dn{rr.leader.id} "
                            f"mid-migration; aborting commit")
            return
        peer = self.srv.peer(op["to_node"]) or Peer(op["to_node"])
        rr.leader = peer
        route.version += 1
        op["state"] = "release"
        op["updated_ms"] = int(time.time() * 1000)
        op.setdefault("times", {}).setdefault("release",
                                              op["updated_ms"])
        self.srv.kv.batch([
            ("put", f"{ROUTE_PREFIX}{op['table']}",
             json.dumps(route.to_dict()).encode()),
            ("put", f"{OP_PREFIX}{op['id']}", json.dumps(op).encode())])
        increment_counter("balancer_migrations_committed")
        logger.info("balancer op %s: route committed — region %s of %s "
                    "now on dn%d (route v%d)", op["id"], op["region"],
                    op["table"], op["to_node"], route.version)

    # ---- split transitions ----
    def _split_on_ack(self, op: dict, state: str, payload: dict) -> None:
        if state == "prepare":
            if payload.get("probed"):
                # probe round: PIN the value in the durable op doc, then
                # re-send prepare (now with the value) — copies only ever
                # happen across a boundary the op doc already recorded
                op["at_value"] = payload["split_value"]
                self._save(op)
                self._sent.pop((op["id"], "balancer_split_prepare"), None)
                return
            op["snapshot_seq"] = payload.get("snapshot_seq", 0)
            op["state"] = "catchup"
        elif state == "catchup":
            op["state"] = "commit"
        elif state == "apply":
            self._finish(op, "done")
            return
        self._save(op)

    def _commit_split(self, op: dict) -> None:
        """The split commit point: the refined rule + the child region
        routes land in ONE atomic KV batch with the op transition."""
        from ..common.telemetry import increment_counter
        from ..mito.engine import _deserialize_rule, _serialize_rule
        from ..partition.rule import refine_range_rule
        _fp.fail_point("balancer_route_commit")
        route = self.srv.table_route(op["table"])
        info = self.srv.table_info(op["table"])
        if route is None or info is None:
            self._finish(op, "failed", "route/table info vanished "
                                       "before commit")
            return
        rule_doc = info.get("meta", {}).get("partition_rule")
        rule = _deserialize_rule(rule_doc)
        try:
            refined = refine_range_rule(rule, op["region"],
                                        op["at_value"], op["children"])
        except ValueError as e:
            self._abort(op, f"rule refinement failed at commit: {e}")
            return
        new_doc = _serialize_rule(refined)
        peer = self.srv.peer(op["node"]) or Peer(op["node"])
        routes = [r for r in route.region_routes
                  if r.region_number != op["region"]]
        routes += [RegionRoute(rn, peer) for rn in op["children"]]
        route.region_routes = sorted(routes,
                                     key=lambda r: r.region_number)
        route.version += 1
        info["meta"]["partition_rule"] = new_doc
        info["meta"]["region_numbers"] = sorted(
            r.region_number for r in route.region_routes)
        op["rule_doc"] = new_doc
        op["state"] = "apply"
        op["updated_ms"] = int(time.time() * 1000)
        op.setdefault("times", {}).setdefault("apply", op["updated_ms"])
        self.srv.kv.batch([
            ("put", f"{ROUTE_PREFIX}{op['table']}",
             json.dumps(route.to_dict()).encode()),
            ("put", f"{TINFO_PREFIX}{op['table']}",
             json.dumps(info).encode()),
            ("put", f"{OP_PREFIX}{op['id']}", json.dumps(op).encode())])
        increment_counter("balancer_splits_committed")
        logger.info("balancer op %s: rule committed — region %s of %s "
                    "split into %s at %r (route v%d)", op["id"],
                    op["region"], op["table"], op["children"],
                    op["at_value"], route.version)

    # ---- replica add/remove transitions ----
    def _replica_on_ack(self, op: dict, state: str, payload: dict
                        ) -> None:
        if state == "snapshot":
            # leader flushed: the shared-store SSTs now cover everything
            # below its flushed sequence, so the bootstrap tail is small
            op["state"] = "bootstrap"
        elif state == "bootstrap":
            # the tail persists IN THE OP DOC (the migrate discipline):
            # a meta crash after this point still holds everything the
            # follower needs to come up at the leader's acked frontier
            op["wal_tail"] = payload.get("wal_tail") or []
            op["flushed_seq"] = payload.get("flushed_seq", 0)
            op["state"] = "attach"
        elif state == "attach":
            op["state"] = "commit"
        elif state == "drop":
            op["state"] = "wire"
        elif state == "wire":
            self._finish(op, "done")
            return
        self._save(op)

    def _commit_replica_add(self, op: dict) -> None:
        """The replica-add commit point: the follower joins the route in
        ONE atomic KV batch with the op transition; the wire step then
        turns on continuous shipping from the leader."""
        from ..common.telemetry import increment_counter
        _fp.fail_point("balancer_route_commit")
        route = self.srv.table_route(op["table"])
        if route is None:
            self._finish(op, "failed", "route vanished before commit")
            return
        rr = next((r for r in route.region_routes
                   if r.region_number == op["region"]), None)
        if rr is None:
            self._finish(op, "failed", "region vanished before commit")
            return
        if rr.leader.id != op["from_node"]:
            # the leader moved under the op (failover/migration raced the
            # busy-table guard): the bootstrapped standby tracked the OLD
            # leader's WAL — abort and drop it rather than publish a
            # follower of unknown lineage
            self._abort(op, f"region leader changed to dn{rr.leader.id} "
                            f"mid-replica-add; aborting commit")
            return
        if all(f.id != op["to_node"] for f in rr.followers):
            peer = self.srv.peer(op["to_node"]) or Peer(op["to_node"])
            rr.followers.append(peer)
        route.version += 1
        op["state"] = "wire"
        op["wal_tail"] = None      # bootstrapped; shrink the op doc
        op["updated_ms"] = int(time.time() * 1000)
        op.setdefault("times", {}).setdefault("wire", op["updated_ms"])
        self.srv.kv.batch([
            ("put", f"{ROUTE_PREFIX}{op['table']}",
             json.dumps(route.to_dict()).encode()),
            ("put", f"{OP_PREFIX}{op['id']}", json.dumps(op).encode())])
        increment_counter("balancer_replicas_added")
        logger.info("balancer op %s: route committed — region %s of %s "
                    "now replicated on dn%d (route v%d)", op["id"],
                    op["region"], op["table"], op["to_node"],
                    route.version)

    def _commit_replica_remove(self, op: dict) -> None:
        """The replica-remove commit point (the op STARTS here): the
        follower leaves the route first so no frontend routes reads to
        it, then the drop step releases its standby region."""
        from ..common.telemetry import increment_counter
        _fp.fail_point("balancer_route_commit")
        route = self.srv.table_route(op["table"])
        if route is None:
            self._finish(op, "failed", "route vanished before commit")
            return
        rr = next((r for r in route.region_routes
                   if r.region_number == op["region"]), None)
        if rr is None:
            self._finish(op, "failed", "region vanished before commit")
            return
        rr.followers = [f for f in rr.followers
                        if f.id != op["to_node"]]
        route.version += 1
        op["state"] = "drop"
        op["updated_ms"] = int(time.time() * 1000)
        op.setdefault("times", {}).setdefault("drop", op["updated_ms"])
        self.srv.kv.batch([
            ("put", f"{ROUTE_PREFIX}{op['table']}",
             json.dumps(route.to_dict()).encode()),
            ("put", f"{OP_PREFIX}{op['id']}", json.dumps(op).encode())])
        increment_counter("balancer_replicas_removed")
        logger.info("balancer op %s: route committed — region %s of %s "
                    "no longer replicated on dn%d (route v%d)", op["id"],
                    op["region"], op["table"], op["to_node"],
                    route.version)

    # ---- rollback ----
    def _abort(self, op: dict, reason: str) -> None:
        """Pre-commit rollback: the route never changed, so undoing means
        unfencing the source (migrate), dropping the pending children
        (split) or the half-built standby (replica_add). The undo message
        is fire-and-forget — it is idempotent and re-sendable, and the op
        itself lands in done/ as failed."""
        logger.warning("balancer op %s rolling back: %s", op["id"], reason)
        base = {"op_id": op["id"], "catalog": op["catalog"],
                "schema": op["schema"], "table": op["table_short"],
                "region": op["region"]}
        if op["kind"] == "migrate":
            self.srv.send_mailbox(op["from_node"],
                                  {**base, "type": "balancer_unfence"})
        elif op["kind"] == "replica_add":
            self.srv.send_mailbox(op["to_node"],
                                  {**base, "type": "repl_drop"})
        elif op["kind"] == "replica_remove":
            pass    # commit-first: nothing pre-commit to undo
        else:
            self.srv.send_mailbox(op["node"],
                                  {**base, "type": "balancer_split_abort",
                                   "children": op["children"]})
        self._finish(op, "failed", reason)

    # ------------------------------------------------------------------
    # auto policies
    # ------------------------------------------------------------------
    def _auto_split(self, now: float) -> List[dict]:
        """Enqueue splits for regions past the configured heat threshold
        (size and/or sustained ingest rate; 0 disables a dimension)."""
        if self.split_size_bytes <= 0 and self.split_rate_rps <= 0:
            return []
        by_tid = {r.table_id: r for r in self.srv.all_table_routes()}
        inflight = self._inflight_tables()
        out: List[dict] = []
        for row in self.srv.region_heat(now):
            if len(self.ops()) >= self.max_inflight:
                break
            hot_size = self.split_size_bytes > 0 and \
                int(row["size_bytes"]) > self.split_size_bytes
            hot_rate = self.split_rate_rps > 0 and \
                float(row["ingest_rate_rps"]) > self.split_rate_rps
            if not (hot_size or hot_rate):
                continue
            try:
                tid_s, rn_s = row["region"].rsplit("_", 1)
                tid, rn = int(tid_s), int(rn_s)
            except ValueError:
                continue
            route = by_tid.get(tid)
            if route is None or route.table_name in inflight:
                continue
            if rn not in {r.region_number for r in route.region_routes}:
                continue
            try:
                op = self.split(route.table_name, rn, auto=True)
            except (GreptimeError, ValueError) as e:
                logger.debug("auto-split of %s region %d skipped: %s",
                             route.table_name, rn, e)
                continue
            from ..common.telemetry import increment_counter
            increment_counter("balancer_auto_splits")
            inflight[route.table_name] = op["id"]
            out.append(op)
            logger.warning(
                "balancer: auto-split of region %d of %s (size=%s "
                "rate=%s) -> op %s", rn, route.table_name,
                row["size_bytes"], row["ingest_rate_rps"], op["id"])
        return out
