"""Distributed lock + leader election over the meta KV.

Reference behavior: src/meta-srv/src/lock/ — an etcd-backed distributed
lock keyed by name, and src/meta-srv/src/election/etcd.rs:34-70 — leader
election via a leased key so exactly one metasrv drives failover/routing
at a time. Both reduce to the same KV primitive available here:
compare-and-put of (holder, expiry) with lease renewal.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from typing import Optional

from .kv import MemKv

LOCK_PREFIX = "__meta/lock/"
ELECTION_KEY = "__meta/election/leader"


class DistributedLock:
    """Lease-based mutual exclusion over the shared KV."""

    def __init__(self, kv: MemKv, name: str, *, lease_secs: float = 10.0,
                 holder: Optional[str] = None) -> None:
        self.kv = kv
        self.key = f"{LOCK_PREFIX}{name}"
        self.lease_secs = lease_secs
        self.holder = holder or uuid.uuid4().hex

    def _doc(self, now: float) -> bytes:
        return json.dumps({"holder": self.holder,
                           "expires": now + self.lease_secs}).encode()

    def try_acquire(self, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        current = self.kv.get(self.key)
        if current is None:
            return self.kv.compare_and_put(self.key, None, self._doc(now))
        doc = json.loads(current)
        if doc["holder"] == self.holder or doc["expires"] < now:
            # re-entrant renewal or expired lease takeover
            return self.kv.compare_and_put(self.key, current,
                                           self._doc(now))
        return False

    def acquire(self, timeout: float = 30.0,
                poll_interval: float = 0.05) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.try_acquire():
                return True
            time.sleep(poll_interval)
        return False

    def renew(self, now: Optional[float] = None) -> bool:
        return self.try_acquire(now)

    def release(self) -> bool:
        current = self.kv.get(self.key)
        if current is None:
            return False
        if json.loads(current)["holder"] != self.holder:
            return False
        # atomic: a plain get/delete could remove a lock another node
        # acquired after our lease expired between the get and the delete
        return self.kv.compare_and_delete(self.key, current)

    def holder_of(self, now: Optional[float] = None) -> Optional[str]:
        now = time.time() if now is None else now
        current = self.kv.get(self.key)
        if current is None:
            return None
        doc = json.loads(current)
        return doc["holder"] if doc["expires"] >= now else None

    def __enter__(self) -> "DistributedLock":
        if not self.acquire():
            raise TimeoutError(f"could not acquire lock {self.key}")
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()


class Election:
    """Leader election: a named lease the winner keeps renewing
    (reference: etcd election, election/etcd.rs). Only the leader runs
    failover checks / route mutations when several metasrv replicas
    share one KV."""

    def __init__(self, kv: MemKv, candidate_id: str,
                 *, lease_secs: float = 10.0,
                 renew_interval: float = 3.0) -> None:
        self._lock = DistributedLock(kv, "__leader__",
                                     lease_secs=lease_secs,
                                     holder=candidate_id)
        self.candidate_id = candidate_id
        self.renew_interval = renew_interval
        self._task = None

    def campaign_once(self, now: Optional[float] = None) -> bool:
        from ..common.telemetry import root_span
        with root_span("election_campaign", candidate=self.candidate_id):
            return self._lock.try_acquire(now)

    @property
    def is_leader(self) -> bool:
        return self._lock.holder_of() == self.candidate_id

    def leader(self) -> Optional[str]:
        return self._lock.holder_of()

    def start(self) -> None:
        """Background campaign + renewal loop."""
        from ..storage.scheduler import RepeatedTask
        if self._task is None:
            self.campaign_once()
            self._task = RepeatedTask(self.renew_interval,
                                      self.campaign_once,
                                      name=f"election-{self.candidate_id}")
            self._task.start()

    def stop(self) -> None:
        if self._task is not None:
            self._task.stop()
            self._task = None
        if self.is_leader:
            self._lock.release()
