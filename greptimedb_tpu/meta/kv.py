"""In-memory metadata KV store.

Reference behavior: src/meta-srv/src/service/store/memory.rs — `MemStore`,
the etcd stand-in used by every in-process distributed test (and the same
API shape the etcd-backed store implements: range scans by prefix, CAS).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..common import failpoint as _fp

_fp.register("meta_kv_put")


class MemKv:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._data: Dict[str, bytes] = {}

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def put(self, key: str, value: bytes) -> None:
        with self._lock:
            self._data[key] = value

    def delete(self, key: str) -> bool:
        with self._lock:
            return self._data.pop(key, None) is not None

    def range(self, prefix: str) -> List[Tuple[str, bytes]]:
        with self._lock:
            return sorted((k, v) for k, v in self._data.items()
                          if k.startswith(prefix))

    def compare_and_put(self, key: str, expect: Optional[bytes],
                        value: bytes) -> bool:
        """Atomic put iff the current value equals `expect` (None = absent)."""
        with self._lock:
            cur = self._data.get(key)
            if cur != expect:
                return False
            self._data[key] = value
            return True

    def compare_and_delete(self, key: str, expect: bytes) -> bool:
        """Atomic delete iff the current value equals `expect` (lease
        release must not clobber a lock a later holder re-acquired)."""
        with self._lock:
            if self._data.get(key) != expect:
                return False
            del self._data[key]
            return True

    def incr(self, key: str, start: int = 0) -> int:
        """Atomic counter (sequence allocation, reference sequence.rs)."""
        with self._lock:
            cur = int(self._data.get(key, str(start).encode()))
            nxt = cur + 1
            self._data[key] = str(nxt).encode()
            return nxt

    def batch(self, ops: List[Tuple[str, str, Optional[bytes]]],
              guard: Optional[Tuple[str, Optional[bytes]]] = None) -> bool:
        """Apply [(op, key, value)] atomically; op is "put" or "delete".
        `guard` = (key, expect) aborts the whole batch unless the key's
        current value equals expect (None = absent) — the etcd-txn shape
        multi-key moves (table rename) need so a crash can't leave a
        half-renamed route."""
        with self._lock:
            if guard is not None and self._data.get(guard[0]) != guard[1]:
                return False
            self._apply_batch_locked(ops)
            return True

    def _apply_batch_locked(
            self, ops: List[Tuple[str, str, Optional[bytes]]]) -> None:
        # validate before mutating: a bad op mid-list must not leave the
        # batch half-applied (all-or-nothing contract)
        for op, key, value in ops:
            if op not in ("put", "delete"):
                raise ValueError(f"unknown batch op {op!r}")
            if op == "put" and not isinstance(value, bytes):
                raise ValueError(f"batch put needs bytes for {key!r}")
        for op, key, value in ops:
            if op == "put":
                self._data[key] = value
            else:
                self._data.pop(key, None)


class FileKv(MemKv):
    """MemKv with a JSON snapshot on every mutation — the etcd stand-in
    for single-meta deployments (reference deploys etcd; route/peer state
    must survive a metasrv restart either way)."""

    def __init__(self, path: str) -> None:
        super().__init__()
        import base64
        import json
        import os
        self._path = path
        self._b64 = base64
        self._json = json
        if os.path.exists(path):
            with open(path) as f:
                doc = json.load(f)
            self._data = {k: base64.b64decode(v) for k, v in doc.items()}

    def _persist_locked(self) -> None:
        from ..utils import atomic_write
        _fp.fail_point("meta_kv_put")
        doc = {k: self._b64.b64encode(v).decode()
               for k, v in self._data.items()}
        # fsync before the rename: the rename alone orders directory
        # metadata, not the data blocks — a power cut could otherwise
        # promote an empty/short snapshot
        atomic_write(self._path, self._json.dumps(doc), tmp_prefix=".kv-")

    def put(self, key: str, value: bytes) -> None:
        with self._lock:
            self._data[key] = value
            self._persist_locked()

    def delete(self, key: str) -> bool:
        with self._lock:
            existed = self._data.pop(key, None) is not None
            if existed:
                self._persist_locked()
            return existed

    def compare_and_put(self, key: str, expect: Optional[bytes],
                        value: bytes) -> bool:
        with self._lock:
            cur = self._data.get(key)
            if cur != expect:
                return False
            self._data[key] = value
            self._persist_locked()
            return True

    def compare_and_delete(self, key: str,
                           expect: Optional[bytes]) -> bool:
        with self._lock:
            if self._data.get(key) != expect:
                return False
            del self._data[key]
            self._persist_locked()
            return True

    def incr(self, key: str, start: int = 0) -> int:
        with self._lock:
            cur = int(self._data.get(key, str(start).encode()))
            nxt = cur + 1
            self._data[key] = str(nxt).encode()
            self._persist_locked()
            return nxt

    def batch(self, ops: List[Tuple[str, str, Optional[bytes]]],
              guard: Optional[Tuple[str, Optional[bytes]]] = None
              ) -> bool:
        with self._lock:
            if guard is not None and self._data.get(guard[0]) != guard[1]:
                return False
            self._apply_batch_locked(ops)
            self._persist_locked()
            return True
