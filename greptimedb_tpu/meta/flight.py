"""Wire facade for the meta service: Flight/gRPC server + client.

Reference behavior: src/meta-srv/src/service/ exposes the metadata
server's heartbeat/router/store RPCs over tonic gRPC, and
src/meta-client wraps them in a client SDK (client.rs). Here the same
surface rides Arrow Flight actions (Flight is gRPC) with JSON bodies:
`FlightMetaServer` wraps an in-process `MetaSrv`; `FlightMetaClient`
implements the exact `MetaClient` interface, so datanodes heartbeat and
frontends resolve routes across real sockets with no call-site changes.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Iterator, List, Optional, Tuple

import pyarrow.flight as flight

from ..errors import GreptimeError
from .service import (
    DatanodeStat, HeartbeatResponse, MetaSrv, Peer, TableRoute)


class FlightMetaServer(flight.FlightServerBase):
    def __init__(self, srv: MetaSrv, location: str = "grpc://127.0.0.1:0",
                 raft_node: object = None) -> None:
        super().__init__(location)
        self.srv = srv
        self.raft_node = raft_node    # replication RPCs when clustered
        self._location = location

    @property
    def address(self) -> str:
        from ..servers.flight import _advertised_address
        return _advertised_address(self._location, self.port)

    def serve_in_background(self) -> threading.Thread:
        from ..common.runtime import new_thread
        t = new_thread(self.serve, daemon=True, name="flight-metasrv",
                       propagate_context=False)
        t.start()
        return t

    def do_action(self, context: object, action: "flight.Action"
                  ) -> Iterator["flight.Result"]:
        body = json.loads(action.body.to_pybytes() or b"{}")
        kind = action.type
        # popped (not just read): raft_* handlers splat **body, and the
        # trace keys must not reach them as unexpected arguments. The
        # verdict piggyback matters little here (metasrv-rooted balancer
        # traces verdict locally), but a frontend's _traced() attaches
        # it to every meta RPC all the same
        from ..common.telemetry import remote_context
        from ..servers.flight import _apply_wire_verdicts
        _apply_wire_verdicts(body)
        with remote_context(body.pop("traceparent", None)):
            yield from self._do_action_inner(kind, body)

    def _do_action_inner(self, kind: str, body: dict
                         ) -> Iterator["flight.Result"]:
        try:
            if kind == "register":
                self.srv.register_datanode(Peer.from_dict(body["peer"]))
                resp = {"ok": True}
            elif kind == "heartbeat":
                stat = DatanodeStat(**body["stat"]) \
                    if body.get("stat") else None
                hb = self.srv.handle_heartbeat(body["node_id"], stat)
                resp = {"ok": True, "mailbox": hb.mailbox}
            elif kind == "create_route":
                route = self.srv.create_table_route(
                    body["name"], body["region_numbers"])
                resp = {"ok": True, "route": route.to_dict()}
            elif kind == "route":
                route = self.srv.table_route(body["name"])
                resp = {"ok": True,
                        "route": route.to_dict() if route else None}
            elif kind == "delete_route":
                resp = {"ok": True,
                        "deleted": self.srv.delete_table_route(
                            body["name"])}
            elif kind == "rename_route":
                route = self.srv.rename_table_route(body["name"],
                                                    body["new_name"])
                resp = {"ok": True,
                        "route": route.to_dict() if route else None}
            elif kind == "allocate_table_id":
                resp = {"ok": True, "id": self.srv.allocate_table_id()}
            elif kind == "put_table_info":
                self.srv.put_table_info(body["name"], body["info"])
                resp = {"ok": True}
            elif kind == "table_info":
                resp = {"ok": True,
                        "info": self.srv.table_info(body["name"])}
            elif kind == "delete_table_info":
                resp = {"ok": True,
                        "deleted": self.srv.delete_table_info(
                            body["name"])}
            elif kind == "cluster_info":
                # heartbeat state (_last_seen/_stats/detectors) is
                # leader-local memory: a follower would report a healthy
                # cluster as all-unknown. Redirect the caller — the
                # failover client retries the next replica on this.
                if self.raft_node is not None \
                        and not self.raft_node.is_leader:
                    from .replication import NotLeaderError
                    raise NotLeaderError(self.raft_node.leader_id)
                resp = {"ok": True, "nodes": self.srv.cluster_info(
                    metasrv_addr=self.address,
                    metasrv_state=self.raft_node.role
                    if self.raft_node is not None else None)}
            elif kind == "region_heat":
                # same leader-only rule as cluster_info: heartbeat stats
                # are leader-local memory
                if self.raft_node is not None \
                        and not self.raft_node.is_leader:
                    from .replication import NotLeaderError
                    raise NotLeaderError(self.raft_node.leader_id)
                resp = {"ok": True, "rows": self.srv.region_heat()}
            elif kind == "region_peers":
                # leader-only like cluster_info: lease state + balancer
                # op state are leader-local memory
                if self.raft_node is not None \
                        and not self.raft_node.is_leader:
                    from .replication import NotLeaderError
                    raise NotLeaderError(self.raft_node.leader_id)
                resp = {"ok": True, "rows": self.srv.region_peers()}
            elif kind in ("admin_migrate_region", "admin_split_region",
                          "admin_rebalance", "admin_add_replica",
                          "admin_remove_replica", "balancer_ack",
                          "balancer_configure"):
                # balancer surface: ops mutate routes / consume leader-
                # local acks, so only the leader may run them
                if self.raft_node is not None \
                        and not self.raft_node.is_leader:
                    from .replication import NotLeaderError
                    raise NotLeaderError(self.raft_node.leader_id)
                if kind == "admin_migrate_region":
                    resp = {"ok": True,
                            "op": self.srv.admin_migrate_region(
                                body["name"], body["region"],
                                body["to_node"])}
                elif kind == "admin_split_region":
                    resp = {"ok": True,
                            "op": self.srv.admin_split_region(
                                body["name"], body["region"],
                                body.get("at_value"))}
                elif kind == "admin_rebalance":
                    resp = {"ok": True,
                            "ops": self.srv.admin_rebalance(
                                body.get("name"))}
                elif kind == "admin_add_replica":
                    resp = {"ok": True,
                            "op": self.srv.admin_add_replica(
                                body["name"], body["region"],
                                body["to_node"])}
                elif kind == "admin_remove_replica":
                    resp = {"ok": True,
                            "op": self.srv.admin_remove_replica(
                                body["name"], body["region"],
                                body["node"])}
                elif kind == "balancer_configure":
                    self.srv.balancer.configure(body["knob"],
                                                body["value"])
                    resp = {"ok": True}
                else:
                    self.srv.balancer_ack(
                        body["node_id"], body["op_id"], body["step"],
                        body["ok"], body.get("error"),
                        body.get("payload") or {})
                    resp = {"ok": True}
            elif kind == "background_jobs":
                # THIS replica's live + recent background work (the
                # balancer runs on the leader, so its rows live there;
                # any replica may answer about itself — the registry is
                # process-local memory, not raft state)
                from ..common import background_jobs
                resp = {"ok": True, "jobs": background_jobs.rows()}
            elif kind == "list_datanodes":
                peers = self.srv.alive_datanodes() \
                    if body.get("alive_only", True) else self.srv.peers()
                resp = {"ok": True,
                        "peers": [p.to_dict() for p in peers]}
            elif kind == "kv_put":
                # generic kv passthroughs (values base64 — they are
                # bytes, e.g. flow-spec JSON docs under __flow/); a
                # wire frontend recovers its flows from these
                import base64
                self.srv.kv.put(body["key"],
                                base64.b64decode(body["value"]))
                resp = {"ok": True}
            elif kind == "kv_get":
                import base64
                v = self.srv.kv.get(body["key"])
                resp = {"ok": True,
                        "value": base64.b64encode(v).decode()
                        if v is not None else None}
            elif kind == "kv_range":
                import base64
                resp = {"ok": True, "items": [
                    [k, base64.b64encode(v).decode()]
                    for k, v in self.srv.kv.range(body["prefix"])]}
            elif kind == "kv_delete":
                resp = {"ok": True,
                        "deleted": bool(self.srv.kv.delete(body["key"]))}
            elif kind == "raft_request_vote" and self.raft_node is not None:
                resp = {"ok": True,
                        **self.raft_node.handle_request_vote(**body)}
            elif kind == "raft_append_entries" \
                    and self.raft_node is not None:
                resp = {"ok": True,
                        **self.raft_node.handle_append_entries(**body)}
            elif kind == "raft_install_snapshot" \
                    and self.raft_node is not None:
                resp = {"ok": True,
                        **self.raft_node.handle_install_snapshot(**body)}
            else:
                raise GreptimeError(f"unknown meta action {kind!r}")
        except GreptimeError as e:
            resp = {"ok": False, "error": str(e),
                    "error_type": type(e).__name__}
        if not kind.startswith("raft_"):
            # metasrv-rooted retained traces (balancer op steps) ride
            # home on whatever meta RPC comes next — the same export
            # channel the datanode servers use (raft bodies stay
            # protocol-pure)
            from ..servers.flight import _export_spans
            exported = _export_spans()
            if exported:
                resp["trace_spans"] = exported
        yield flight.Result(json.dumps(resp).encode())


class FlightMetaClient:
    """MetaClient surface over a FlightMetaServer."""

    def __init__(self, address: str) -> None:
        self.address = address
        self._conn: Optional[flight.FlightClient] = None

    @property
    def conn(self) -> flight.FlightClient:
        if self._conn is None:
            self._conn = flight.FlightClient(self.address)
        return self._conn

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def _action(self, kind: str, body: dict) -> dict:
        from ..client.flight import (_absorb_wire_spans,
                                     _to_greptime_error, _traced)
        try:
            results = list(self.conn.do_action(
                flight.Action(kind, json.dumps(_traced(body)).encode())))
            resp = json.loads(results[0].body.to_pybytes())
        except flight.FlightError as e:
            raise _to_greptime_error(e) from None
        _absorb_wire_spans(resp.pop("trace_spans", None))
        if not resp.get("ok", False):
            if resp.get("error_type") == "NotLeaderError":
                from .replication import NotLeaderError
                raise NotLeaderError(None)
            raise GreptimeError(resp.get("error", "meta error"))
        return resp

    # ---- MetaClient surface ----
    def register(self, peer: Peer) -> None:
        self._action("register", {"peer": peer.to_dict()})

    def heartbeat(self, node_id: int,
                  stat: Optional[DatanodeStat] = None) -> HeartbeatResponse:
        resp = self._action("heartbeat", {
            "node_id": node_id,
            "stat": dataclasses.asdict(stat) if stat else None})
        return HeartbeatResponse(mailbox=resp.get("mailbox", []))

    def create_route(self, full_name: str,
                     region_numbers: List[int]) -> TableRoute:
        resp = self._action("create_route", {
            "name": full_name, "region_numbers": list(region_numbers)})
        return TableRoute.from_dict(resp["route"])

    def route(self, full_name: str) -> Optional[TableRoute]:
        resp = self._action("route", {"name": full_name})
        return TableRoute.from_dict(resp["route"]) \
            if resp.get("route") else None

    def delete_route(self, full_name: str) -> bool:
        return bool(self._action("delete_route",
                                 {"name": full_name})["deleted"])

    def rename_route(self, full_name: str,
                     new_full_name: str) -> Optional[TableRoute]:
        resp = self._action("rename_route", {"name": full_name,
                                             "new_name": new_full_name})
        return TableRoute.from_dict(resp["route"]) \
            if resp.get("route") else None

    def allocate_table_id(self) -> int:
        return int(self._action("allocate_table_id", {})["id"])

    def cluster_info(self) -> List[dict]:
        return self._action("cluster_info", {})["nodes"]

    def background_jobs(self) -> List[dict]:
        """The metasrv replica's live + recent background jobs (the
        balancer's op steps run on the leader) — merged into
        information_schema.background_jobs by the frontend."""
        return list(self._action("background_jobs", {}).get("jobs", []))

    def region_heat(self) -> List[dict]:
        return self._action("region_heat", {})["rows"]

    def put_table_info(self, full_name: str, info: dict) -> None:
        self._action("put_table_info", {"name": full_name, "info": info})

    def table_info(self, full_name: str) -> Optional[dict]:
        return self._action("table_info", {"name": full_name}).get("info")

    def delete_table_info(self, full_name: str) -> bool:
        return bool(self._action("delete_table_info",
                                 {"name": full_name})["deleted"])

    def list_datanodes(self, alive_only: bool = True) -> List[Peer]:
        resp = self._action("list_datanodes", {"alive_only": alive_only})
        return [Peer.from_dict(p) for p in resp["peers"]]

    # ---- elastic region balancer surface ----
    def region_peers(self) -> List[dict]:
        return self._action("region_peers", {})["rows"]

    def admin_migrate_region(self, full_name: str, region: int,
                             to_node: int) -> dict:
        return self._action("admin_migrate_region", {
            "name": full_name, "region": region, "to_node": to_node})["op"]

    def admin_split_region(self, full_name: str, region: int,
                           at_value: object = None) -> dict:
        return self._action("admin_split_region", {
            "name": full_name, "region": region,
            "at_value": at_value})["op"]

    def admin_rebalance(self, full_name: Optional[str] = None
                        ) -> List[dict]:
        return self._action("admin_rebalance", {"name": full_name})["ops"]

    def admin_add_replica(self, full_name: str, region: int,
                          to_node: int) -> dict:
        return self._action("admin_add_replica", {
            "name": full_name, "region": region, "to_node": to_node})["op"]

    def admin_remove_replica(self, full_name: str, region: int,
                             node: int) -> dict:
        return self._action("admin_remove_replica", {
            "name": full_name, "region": region, "node": node})["op"]

    def balancer_configure(self, knob: str, value: object) -> None:
        self._action("balancer_configure", {"knob": knob, "value": value})

    def balancer_ack(self, node_id: int, op_id: str, step: str, ok: bool,
                     error: Optional[str], payload: dict) -> None:
        self._action("balancer_ack", {
            "node_id": node_id, "op_id": op_id, "step": step, "ok": ok,
            "error": error, "payload": payload or {}})

    # generic kv passthroughs (KvFlowStore persists flow specs under
    # __flow/ — without these a WIRE frontend crashed at start trying
    # to recover flows through the proxy's synthesized attribute)
    def kv_put(self, key: str, value: bytes) -> None:
        import base64
        self._action("kv_put", {"key": key,
                                "value": base64.b64encode(value).decode()})

    def kv_get(self, key: str) -> Optional[bytes]:
        import base64
        v = self._action("kv_get", {"key": key}).get("value")
        return base64.b64decode(v) if v is not None else None

    def kv_range(self, prefix: str) -> List[Tuple[str, bytes]]:
        # eager, not a generator: the RPC must fire inside this call so
        # FailoverFlightMetaClient's replica-walking wrapper (and any
        # caller try block) sees a connection failure, not the iterator
        import base64
        return [(k, base64.b64decode(v)) for k, v in
                self._action("kv_range", {"prefix": prefix})["items"]]

    def kv_delete(self, key: str) -> bool:
        return bool(self._action("kv_delete", {"key": key})["deleted"])


class PeerClientRegistry(dict):
    """node_id → DatanodeClient map that resolves unknown peers through
    the meta service and dials their Flight address on demand (the
    frontend's view of an elastic cluster)."""

    def __init__(self, meta: FlightMetaClient) -> None:
        super().__init__()
        self.meta = meta
        self._lock = threading.Lock()

    def _resolve(self, node_id: int) -> Optional[object]:
        from ..client.flight import FlightDatanodeClient
        for peer in self.meta.list_datanodes(alive_only=False):
            if peer.id == node_id and peer.addr:
                client = FlightDatanodeClient(peer.addr, node_id=node_id)
                with self._lock:
                    return self.setdefault(node_id, client)
        return None

    def __missing__(self, node_id: int) -> object:
        client = self._resolve(node_id)
        if client is None:
            raise KeyError(node_id)
        return client

    def get(self, node_id: int, default: object = None) -> object:
        try:
            return self[node_id]
        except KeyError:
            return default


class FailoverFlightMetaClient:
    """MetaClient surface over a metasrv replica set: every call walks
    the address list until one answers as the leader (reference clients
    iterate etcd endpoints the same way). Accepts one address too, so
    callers can always construct it from --metasrv-addr."""

    def __init__(self, addresses: List[str], *, retry_delay: float = 0.2,
                 max_rounds: int = 25) -> None:
        self.clients = [FlightMetaClient(a) for a in addresses]
        # the leader pin lives in a shared cell so advisory() copies
        # write the leader they discover back to the parent client
        self._pin = [0]
        self._delay = retry_delay
        self._rounds = max_rounds

    @property
    def _cur(self) -> int:
        return self._pin[0]

    @_cur.setter
    def _cur(self, value: int) -> None:
        self._pin[0] = value

    @property
    def address(self) -> str:
        return self.clients[self._cur % len(self.clients)].address

    def advisory(self) -> "FailoverFlightMetaClient":
        """A view of this client that tries each replica once with no
        inter-round sleep — for advisory reads (the cluster_info health
        view) that must degrade immediately when meta is down instead of
        stalling behind the write-path's full retry budget. Connections
        AND the leader pin are shared (`_pin` is a mutable cell), so a
        leader the quick pass discovers sticks for every later call."""
        import copy
        quick = copy.copy(self)
        quick._rounds = 1
        quick._delay = 0.0
        return quick

    def close(self) -> None:
        for c in self.clients:
            c.close()

    def __getattr__(self, name: str) -> object:
        if name.startswith("_"):
            raise AttributeError(name)

        def call(*args: object, **kwargs: object) -> object:
            from .replication import NotLeaderError
            import time as _time
            last: Optional[Exception] = None
            for attempt in range(self._rounds * len(self.clients)):
                client = self.clients[self._cur % len(self.clients)]
                try:
                    return getattr(client, name)(*args, **kwargs)
                except (NotLeaderError, ConnectionError) as e:
                    last = e
                except GreptimeError as e:
                    # unreachable replica (connection refused rides in as
                    # a generic flight error) — try the next one; real
                    # application errors don't mention leadership
                    if "refused" not in str(e).lower() \
                            and "unavailable" not in str(e).lower():
                        raise
                    last = e
                self._cur += 1
                if (attempt + 1) % len(self.clients) == 0:
                    _time.sleep(self._delay)
            raise last if last is not None else GreptimeError(
                "no metasrv replica reachable")
        return call
