"""Meta plane: cluster metadata service, client, failure detection.

Reference behavior: src/meta-srv + src/meta-client (see service.py).
"""

from .balancer import RegionBalancer
from .failure_detector import PhiAccrualFailureDetector
from .kv import MemKv
from .service import (
    DatanodeStat, HeartbeatResponse, MetaClient, MetaSrv,
    NoAliveDatanodeError, Peer, RegionRoute, TableRoute,
)

__all__ = [
    "DatanodeStat", "HeartbeatResponse", "MemKv", "MetaClient", "MetaSrv",
    "NoAliveDatanodeError", "Peer", "PhiAccrualFailureDetector",
    "RegionBalancer", "RegionRoute", "TableRoute",
]
