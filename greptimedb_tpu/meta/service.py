"""Meta service: the cluster brain.

Reference behavior: src/meta-srv — datanode registration + lease-tracked
heartbeats (handler.rs:115-176), table-route creation with region placement
via selectors (service/router.rs:86-238, selector/load_based.rs:27-80),
table-id sequences (sequence.rs:28), phi-accrual failure detection driven
off heartbeats (failure_detector.rs, handler/failure_handler/runner.rs),
and route/table metadata persisted to the KV store
(keys.rs:398, catalog/src/helper.rs:95-132).

This runs in-process over MemKv (the reference's MemStore test topology,
meta-srv/src/mocks.rs); a gRPC facade can wrap it 1:1 for multi-host.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..errors import GreptimeError
from .failure_detector import PhiAccrualFailureDetector
from .kv import MemKv


@dataclass(frozen=True)
class Peer:
    id: int
    addr: str = ""

    def to_dict(self) -> dict:
        return {"id": self.id, "addr": self.addr}

    @staticmethod
    def from_dict(d: dict) -> "Peer":
        return Peer(d["id"], d.get("addr", ""))


@dataclass
class RegionRoute:
    region_number: int
    leader: Peer
    #: read replicas (ISSUE 19): standby peers continuously fed the
    #: leader's WAL tail; reads may scatter here, writes never do, and
    #: failover promotes the most-caught-up one
    followers: List[Peer] = field(default_factory=list)

    def to_dict(self) -> dict:
        d = {"region_number": self.region_number,
             "leader": self.leader.to_dict()}
        if self.followers:
            d["followers"] = [p.to_dict() for p in self.followers]
        return d

    @staticmethod
    def from_dict(d: dict) -> "RegionRoute":
        return RegionRoute(d["region_number"], Peer.from_dict(d["leader"]),
                           [Peer.from_dict(p)
                            for p in d.get("followers", [])])


@dataclass
class TableRoute:
    table_id: int
    table_name: str                    # catalog.schema.table
    region_routes: List[RegionRoute] = field(default_factory=list)
    #: bumped on EVERY placement mutation (failover, migrate commit,
    #: split commit) — frontends compare it after a StaleRouteError
    #: refresh to tell "the route moved" from "still mid-handoff"
    version: int = 0

    def regions_on(self, peer_id: int) -> List[int]:
        return [r.region_number for r in self.region_routes
                if r.leader.id == peer_id]

    def peers(self) -> List[Peer]:
        seen: Dict[int, Peer] = {}
        for r in self.region_routes:
            seen[r.leader.id] = r.leader
        return [seen[i] for i in sorted(seen)]

    def to_dict(self) -> dict:
        return {"table_id": self.table_id, "table_name": self.table_name,
                "region_routes": [r.to_dict() for r in self.region_routes],
                "version": self.version}

    @staticmethod
    def from_dict(d: dict) -> "TableRoute":
        return TableRoute(d["table_id"], d["table_name"],
                          [RegionRoute.from_dict(r)
                           for r in d["region_routes"]],
                          version=int(d.get("version", 0)))


@dataclass
class DatanodeStat:
    """Per-heartbeat datanode report (reference: the Stat/RegionStat pair
    in meta-srv's heartbeat handler). `region_stats` carries one
    {"region", "rows", "size_bytes"} dict per hosted region — the
    region-heat input the elastic-region control loop (ROADMAP item 1)
    will read, surfaced via information_schema.cluster_info."""
    region_count: int = 0
    approximate_rows: int = 0
    approximate_bytes: int = 0
    region_stats: List[dict] = field(default_factory=list)
    #: False for a light liveness beat that refreshes region_count only
    #: (the load_based selector needs it fresh every beat) while the
    #: expensive per-region walk rides every stats_every-th beat; meta
    #: must not derive an ingest rate from a light beat's zero rows
    full: bool = True


@dataclass
class HeartbeatResponse:
    mailbox: List[dict] = field(default_factory=list)


TABLE_ID_SEQ = "__meta/seq/table_id"
ROUTE_PREFIX = "__meta/route/"
PEER_PREFIX = "__meta/peer/"
TINFO_PREFIX = "__meta/tinfo/"
#: pending failover promotions (ISSUE 19): the repl_promote mail is
#: fire-and-forget, so the doc persists here until a full heartbeat from
#: the promoted node shows the region out of standby — a new leader that
#: dies mid-promote gets the mail again after it restarts
PROMOTE_PREFIX = "__balancer/promote/"


class NoAliveDatanodeError(GreptimeError):
    status_code = "RuntimeResourcesExhausted"


class MetaSrv:
    """Single-leader metadata service over a KV store."""

    def __init__(self, kv: Optional[MemKv] = None, *,
                 datanode_lease_secs: float = 15.0,
                 selector: str = "load_based",
                 phi_threshold: float = 8.0) -> None:
        from ..common.locks import TrackedRLock
        from ..common.tracking import tracked_state
        self.kv = kv if kv is not None else MemKv()
        self.datanode_lease_secs = datanode_lease_secs
        self.selector = selector
        #: guards ALL the in-memory heartbeat state below. Heartbeats
        #: arrive on one server thread per datanode while cluster_info /
        #: region_heat / the balancer tick / failover_check read
        #: concurrently — greptsan flagged the unguarded dicts the round
        #: they were wrapped (a half-updated rate map could feed the
        #: selector). kv reads/writes stay OUTSIDE the lock.
        self._state_lock = TrackedRLock("meta.srv_state")
        self._stats: Dict[int, DatanodeStat] = tracked_state(
            {}, "meta.srv.stats")
        #: (approximate_rows, t) of the previous stat-bearing heartbeat,
        #: so consecutive reports yield a per-node ingest rate
        self._prev_ingest: Dict[int, tuple] = tracked_state(
            {}, "meta.srv.prev_ingest")
        self._ingest_rate: Dict[int, float] = tracked_state(
            {}, "meta.srv.ingest_rate")
        #: per-REGION twins of the above: {node: {region: rows}} at the
        #: previous full beat and the derived {node: {region: rps}} —
        #: the cluster-wide region-heat feed the self-monitoring
        #: scraper persists into greptime_private.region_heat
        self._prev_region_rows: Dict[int, tuple] = tracked_state(
            {}, "meta.srv.prev_region_rows")
        self._region_rates: Dict[int, Dict[str, float]] = tracked_state(
            {}, "meta.srv.region_rates")
        #: replica catch-up feed off full heartbeats (ISSUE 19): per
        #: FOLLOWER node {region_name: (replicated_seq, beat_time)}, and
        #: per region name the LEADER-reported (committed_seq,
        #: beat_time) — region_peers derives lag_ms from the pair, and
        #: failover_check promotes the max-replicated_seq follower
        self._replica_seq: Dict[int, Dict[str, tuple]] = tracked_state(
            {}, "meta.srv.replica_seq")
        self._leader_seq: Dict[str, tuple] = tracked_state(
            {}, "meta.srv.leader_seq")
        #: last FULL (stat-bearing) beat per node — pending-promotion
        #: confirmation compares against it (a beat after the promote
        #: mail whose stats no longer flag the region standby)
        self._stat_time: Dict[int, float] = tracked_state(
            {}, "meta.srv.stat_time")
        self._last_seen: Dict[int, float] = tracked_state(
            {}, "meta.srv.last_seen")
        self._detectors: Dict[int, PhiAccrualFailureDetector] = \
            tracked_state({}, "meta.srv.detectors")
        self._phi_threshold = phi_threshold
        self._mailboxes: Dict[int, List[dict]] = tracked_state(
            {}, "meta.srv.mailboxes")
        # Startup grace: peers persist in the KV but _last_seen does not.
        # After a metasrv restart every persisted peer would read seen=None
        # and a single failover tick would reassign ALL healthy nodes'
        # regions (split-brain: the old leaders keep serving writes). Treat
        # process start as the last-seen time for unseen persisted peers.
        self._start_time = time.time()
        # elastic region control loop (split / migrate / rebalance): the
        # op state machines persist under __balancer/ in the same KV, so
        # a metasrv restart resumes them (meta/balancer.py)
        from .balancer import RegionBalancer
        self.balancer = RegionBalancer(self)

    # ---- membership ----
    def register_datanode(self, peer: Peer) -> None:
        self.kv.put(f"{PEER_PREFIX}{peer.id}",
                    json.dumps(peer.to_dict()).encode())
        with self._state_lock:
            self._last_seen[peer.id] = time.time()
            self._detectors.setdefault(
                peer.id,
                PhiAccrualFailureDetector(threshold=self._phi_threshold))

    def peers(self) -> List[Peer]:
        return [Peer.from_dict(json.loads(v))
                for _, v in self.kv.range(PEER_PREFIX)]

    def peer(self, node_id: int) -> Optional[Peer]:
        raw = self.kv.get(f"{PEER_PREFIX}{node_id}")
        return Peer.from_dict(json.loads(raw)) if raw is not None else None

    def alive_datanodes(self, now: Optional[float] = None) -> List[Peer]:
        now = time.time() if now is None else now
        out = []
        peers = self.peers()               # kv read outside the lock
        with self._state_lock:
            for p in peers:
                seen = self._last_seen.get(p.id)
                if seen is not None and \
                        now - seen <= self.datanode_lease_secs:
                    det = self._detectors.get(p.id)
                    if det is None or det.sample_count == 0 or \
                            det.is_available(now * 1000.0):
                        out.append(p)
        return out

    def failed_datanodes(self, now: Optional[float] = None) -> List[Peer]:
        """Peers whose phi crossed the threshold (failover candidates —
        the action itself is still TODO in the reference too)."""
        now = time.time() if now is None else now
        out = []
        peers = self.peers()
        with self._state_lock:
            for p in peers:
                det = self._detectors.get(p.id)
                if det is not None and det.sample_count > 0 and \
                        not det.is_available(now * 1000.0):
                    out.append(p)
        return out

    # ---- heartbeat ----
    def handle_heartbeat(self, node_id: int,
                         stat: Optional[DatanodeStat] = None,
                         now: Optional[float] = None) -> HeartbeatResponse:
        now = time.time() if now is None else now
        if self.kv.get(f"{PEER_PREFIX}{node_id}") is None:
            # first contact registers the peer (reference: heartbeats are
            # the registration channel, keep_lease_handler.rs)
            self.register_datanode(Peer(node_id))
        with self._state_lock:
            self._last_seen[node_id] = now
            det = self._detectors.setdefault(
                node_id,
                PhiAccrualFailureDetector(threshold=self._phi_threshold))
            det.heartbeat(now * 1000.0)
            if stat is not None and stat.full:
                prev = self._prev_ingest.get(node_id)
                if prev is not None and now > prev[1]:
                    self._ingest_rate[node_id] = max(
                        0.0, (stat.approximate_rows - prev[0]) /
                        (now - prev[1]))
                self._prev_ingest[node_id] = (stat.approximate_rows, now)
                # per-region rate across consecutive FULL beats (light
                # beats carry no region rows, so the divisor is the true
                # elapsed time between stat walks, same as the node rate)
                by_region = {rs["region"]: int(rs["rows"])
                             for rs in stat.region_stats}
                prev_r = self._prev_region_rows.get(node_id)
                if prev_r is not None and now > prev_r[1]:
                    dt = now - prev_r[1]
                    self._region_rates[node_id] = {
                        region: max(0.0,
                                    (rows - prev_r[0].get(region, 0)) / dt)
                        for region, rows in by_region.items()}
                self._prev_region_rows[node_id] = (by_region, now)
                self._stats[node_id] = stat
                # replica lag feed: standby regions report how far they
                # have applied, leader regions what they have committed
                repl: Dict[str, tuple] = {}
                for rs in stat.region_stats:
                    if rs.get("standby"):
                        repl[rs["region"]] = (
                            int(rs.get("replicated_seq", 0) or 0), now)
                    elif rs.get("committed_seq") is not None:
                        self._leader_seq[rs["region"]] = (
                            int(rs.get("committed_seq", 0) or 0), now)
                self._replica_seq[node_id] = repl
                self._stat_time[node_id] = now
            elif stat is not None:
                # light beat: region_count only (selector freshness);
                # keep the last full stat's rows/region heat intact
                kept = self._stats.get(node_id)
                if kept is not None:
                    kept.region_count = stat.region_count
                else:
                    self._stats[node_id] = stat
            msgs = self._mailboxes.pop(node_id, [])
        return HeartbeatResponse(mailbox=msgs)

    def send_mailbox(self, node_id: int, message: dict) -> None:
        """Reverse control: meta→datanode messages ride the next heartbeat
        response (reference handler.rs:244-302)."""
        with self._state_lock:
            self._mailboxes.setdefault(node_id, []).append(message)

    # ---- sequences ----
    def allocate_table_id(self) -> int:
        return self.kv.incr(TABLE_ID_SEQ, start=1023)

    # ---- routes ----
    def create_table_route(self, full_table_name: str,
                           region_numbers: List[int],
                           now: Optional[float] = None) -> TableRoute:
        alive = self.alive_datanodes(now)
        if not alive:
            raise NoAliveDatanodeError("no alive datanode to place regions")
        if self.selector == "load_based":
            # fewest-regions-first (reference load_based.rs:27-80)
            with self._state_lock:
                load = {p.id: self._stats.get(p.id,
                                              DatanodeStat()).region_count
                        for p in alive}
            order = sorted(alive, key=lambda p: (load[p.id], p.id))
        else:
            order = sorted(alive, key=lambda p: p.id)
        table_id = self.allocate_table_id()
        routes = [RegionRoute(rn, order[i % len(order)])
                  for i, rn in enumerate(sorted(region_numbers))]
        route = TableRoute(table_id, full_table_name, routes)
        key = f"{ROUTE_PREFIX}{full_table_name}"
        if not self.kv.compare_and_put(
                key, None, json.dumps(route.to_dict()).encode()):
            raise GreptimeError(f"table route exists: {full_table_name}")
        return route

    def table_route(self, full_table_name: str) -> Optional[TableRoute]:
        raw = self.kv.get(f"{ROUTE_PREFIX}{full_table_name}")
        if raw is None:
            return None
        return TableRoute.from_dict(json.loads(raw))

    def delete_table_route(self, full_table_name: str) -> bool:
        return self.kv.delete(f"{ROUTE_PREFIX}{full_table_name}")

    def rename_table_route(self, old_full_name: str,
                           new_full_name: str) -> Optional[TableRoute]:
        """Move a route (and its table info) to a new name, keeping the
        table id and region placement (distributed ALTER ... RENAME)."""
        route = self.table_route(old_full_name)
        if route is None:
            return None
        route.table_name = new_full_name
        new_key = f"{ROUTE_PREFIX}{new_full_name}"
        # one guarded multi-op (etcd-txn shape): route + info move together
        # or not at all, so a crash can't leave the table under both names
        ops = [("put", new_key, json.dumps(route.to_dict()).encode()),
               ("delete", f"{ROUTE_PREFIX}{old_full_name}", None)]
        info = self.table_info(old_full_name)
        if info is not None:
            ops += [("put", f"{TINFO_PREFIX}{new_full_name}",
                     json.dumps(info).encode()),
                    ("delete", f"{TINFO_PREFIX}{old_full_name}", None)]
        if not self.kv.batch(ops, guard=(new_key, None)):
            raise GreptimeError(f"table route exists: {new_full_name}")
        return route

    def all_table_routes(self) -> List[TableRoute]:
        return [TableRoute.from_dict(json.loads(v))
                for _, v in self.kv.range(ROUTE_PREFIX)]

    # ---- table info (reference: TableGlobalKey/Value in etcd,
    # catalog/src/helper.rs:95-132 — schema travels with the route so
    # failover can materialize a region on a fresh datanode) ----
    def put_table_info(self, full_table_name: str, info: dict) -> None:
        self.kv.put(f"{TINFO_PREFIX}{full_table_name}",
                    json.dumps(info).encode())

    def table_info(self, full_table_name: str) -> Optional[dict]:
        raw = self.kv.get(f"{TINFO_PREFIX}{full_table_name}")
        return json.loads(raw) if raw is not None else None

    def delete_table_info(self, full_table_name: str) -> bool:
        return self.kv.delete(f"{TINFO_PREFIX}{full_table_name}")

    # ---- cluster health view (backs information_schema.cluster_info;
    # reference: the CLUSTER_INFO memory table fed from meta's
    # heartbeat-collected NodeInfo) ----
    def cluster_info(self, now: Optional[float] = None,
                     metasrv_addr: str = "",
                     metasrv_state: Optional[str] = None) -> List[dict]:
        """One row per cluster member: the metasrv itself plus every
        registered datanode with its lease state (alive / suspect /
        expired / unknown), last-seen time, route-derived region count
        and heartbeat-reported size/ingest-rate stats. Region counts
        come from the routes — the authoritative placement — so the view
        is live even before a node's next stat-bearing heartbeat.
        `metasrv_state` is the serving metasrv's raft role when it is
        replicated (a follower answering a stale read must not claim
        leadership); a lone metasrv is trivially the leader."""
        now = time.time() if now is None else now
        # peer_id -1: datanode ids are >= 0 (DatanodeOptions defaults to
        # 0), so the metasrv row must not collide with one — and sorts
        # first under ORDER BY peer_id
        rows = [{
            "peer_id": -1, "peer_type": "metasrv",
            "peer_addr": metasrv_addr,
            "lease_state": metasrv_state or "leader",
            "last_seen_ms": int(now * 1000), "region_count": 0,
            "approximate_rows": 0, "ingest_rate_rps": 0.0,
            "region_stats": "[]",
        }]
        placed: Dict[int, int] = {}
        for route in self.all_table_routes():
            for rr in route.region_routes:
                placed[rr.leader.id] = placed.get(rr.leader.id, 0) + 1
        peers = self.peers()               # kv read outside the lock
        with self._state_lock:
            for p in peers:
                seen = self._last_seen.get(p.id)
                if seen is None:
                    state = "unknown"
                elif now - seen <= self.datanode_lease_secs:
                    state = "alive"
                    det = self._detectors.get(p.id)
                    if det is not None and det.sample_count > 0 and \
                            not det.is_available(now * 1000.0):
                        state = "suspect"
                else:
                    state = "expired"
                stat = self._stats.get(p.id, DatanodeStat())
                rows.append({
                    "peer_id": p.id, "peer_type": "datanode",
                    "peer_addr": p.addr, "lease_state": state,
                    "last_seen_ms": int(seen * 1000)
                    if seen is not None else None,
                    "region_count": placed.get(p.id, 0),
                    "approximate_rows": int(stat.approximate_rows),
                    # rate is a derivative: a node that stopped
                    # heartbeating isn't ingesting, so don't let its
                    # last-known rate read as the hottest ingester
                    # forever (approximate_rows is cumulative and stays
                    # as the last-known fact)
                    "ingest_rate_rps": round(
                        self._ingest_rate.get(p.id, 0.0), 3)
                    if state == "alive" else 0.0,
                    "region_stats": json.dumps(stat.region_stats,
                                               separators=(",", ":")),
                })
        return rows

    def region_heat(self, now: Optional[float] = None) -> List[dict]:
        """One row per (datanode, region): heartbeat-reported rows and
        size plus the per-region ingest rate derived across full stat
        beats — the cluster-wide feed behind
        greptime_private.region_heat. Rates zero for non-alive nodes
        (same derivative rule as cluster_info's node rate)."""
        now = time.time() if now is None else now
        alive = {p.id for p in self.alive_datanodes(now)}
        rows: List[dict] = []
        with self._state_lock:
            for node_id in sorted(self._stats):
                stat = self._stats[node_id]
                rates = self._region_rates.get(node_id, {})
                for rs in stat.region_stats:
                    rows.append({
                        "node": f"dn{node_id}",
                        "region": rs["region"],
                        "rows": int(rs["rows"]),
                        "size_bytes": int(rs["size_bytes"]),
                        # cost-planner inputs riding the heartbeat
                        # (absent from pre-upgrade beats: .get)
                        "series": int(rs.get("series", 0) or 0),
                        "time_span": int(rs.get("time_span", 0) or 0),
                        "ingest_rate_rps": round(
                            rates.get(rs["region"], 0.0), 3)
                        if node_id in alive else 0.0,
                    })
        return rows

    # ---- elastic region admin (ADMIN MIGRATE/SPLIT/REBALANCE route
    # here through the frontends; meta/balancer.py runs the state
    # machines) ----
    def admin_migrate_region(self, full_table_name: str, region: int,
                             to_node: int) -> dict:
        return self.balancer.migrate(full_table_name, region, to_node)

    def admin_split_region(self, full_table_name: str, region: int,
                           at_value: object = None) -> dict:
        return self.balancer.split(full_table_name, region,
                                   at_value=at_value)

    def admin_rebalance(self, full_table_name: Optional[str] = None
                        ) -> List[dict]:
        return self.balancer.rebalance(full_table_name)

    def admin_add_replica(self, full_table_name: str, region: int,
                          to_node: int) -> dict:
        return self.balancer.add_replica(full_table_name, region, to_node)

    def admin_remove_replica(self, full_table_name: str, region: int,
                             node: int) -> dict:
        return self.balancer.remove_replica(full_table_name, region, node)

    def balancer_ack(self, node_id: int, op_id: str, step: str, ok: bool,
                     error: Optional[str], payload: dict) -> None:
        self.balancer.handle_ack(node_id, op_id, step, ok, error, payload)

    def region_peers(self, now: Optional[float] = None) -> List[dict]:
        """One row per (table, region, hosting peer): the leader row
        plus one row per read-replica follower, each with its lease
        state, replication position (`replicated_seq` — the leader row
        carries its committed sequence) and staleness bound (`lag_ms`),
        plus any in-flight balancer operation touching the region — the
        information_schema.region_peers feed and the replica-aware read
        router's input."""
        now = time.time() if now is None else now
        states = {r["peer_id"]: r["lease_state"]
                  for r in self.cluster_info(now)}
        addrs = {p.id: p.addr for p in self.peers()}
        ops_by_region: Dict[tuple, dict] = {}
        for op in self.balancer.ops():
            ops_by_region[(op["table"], op["region"])] = op
            for child in op.get("children") or []:
                ops_by_region.setdefault((op["table"], child), op)
        with self._state_lock:
            replica_seq = {nid: dict(m)
                           for nid, m in self._replica_seq.items()}
            leader_seq = dict(self._leader_seq)
        rows: List[dict] = []
        for route in self.all_table_routes():
            for rr in sorted(route.region_routes,
                             key=lambda r: r.region_number):
                op = ops_by_region.get(
                    (route.table_name, rr.region_number))
                rname = f"{route.table_id}_{rr.region_number:010d}"
                committed = leader_seq.get(rname, (None,))[0]
                base = {
                    "table_name": route.table_name,
                    "region_number": rr.region_number,
                    "route_version": route.version,
                    "operation": f"{op['kind']}:{op['state']}"
                    if op is not None else None,
                    "op_id": op["id"] if op is not None else None,
                }
                rows.append({
                    **base,
                    "peer_id": rr.leader.id,
                    "peer_addr": addrs.get(rr.leader.id, rr.leader.addr),
                    "is_leader": "Yes",
                    "status": states.get(rr.leader.id, "unknown").upper(),
                    "replicated_seq": committed,
                    "lag_ms": 0,
                })
                for f in rr.followers:
                    rep = replica_seq.get(f.id, {}).get(rname)
                    if rep is None:
                        lag_ms = None       # no stat beat yet: unknown
                    elif committed is not None and rep[0] >= committed:
                        lag_ms = 0          # fully caught up
                    else:
                        # staleness bound: the replica held everything
                        # as of its last stat-bearing heartbeat
                        lag_ms = max(0, int((now - rep[1]) * 1000))
                    rows.append({
                        **base,
                        "peer_id": f.id,
                        "peer_addr": addrs.get(f.id, f.addr),
                        "is_leader": "No",
                        "status": states.get(f.id, "unknown").upper(),
                        "replicated_seq": rep[0] if rep else None,
                        "lag_ms": lag_ms,
                    })
        rows.sort(key=lambda r: (r["table_name"], r["region_number"],
                                 r["is_leader"] != "Yes", r["peer_id"]))
        return rows

    # ---- region failover (the action the reference leaves TODO,
    # meta-srv/src/handler/failure_handler/runner.rs:132; design per
    # docs/rfcs/2023-03-08-region-fault-tolerance.md: region data lives
    # on shared object storage, so a dead node's regions reopen
    # elsewhere at their last-flushed state) ----
    def failover_check(self, now: Optional[float] = None) -> List[dict]:
        """Re-place regions led by dead datanodes onto alive ones and
        mail open_regions to the new leaders. Regions with a caught-up
        read replica are PROMOTED instead: the most-replicated alive
        follower becomes leader (mail repl_promote so it fences the dead
        leader's WAL, refreshes off the shared manifest and salvages the
        acked tail — zero acked rows lost). Dead followers are pruned.
        Returns the moves."""
        from ..common import failpoint as _fp
        now_t = time.time() if now is None else now
        self._retry_pending_promotions(now_t)
        dead = {p.id for p in self.failed_datanodes(now_t)}
        peers = self.peers()
        with self._state_lock:
            for p in peers:
                seen = self._last_seen.get(p.id, self._start_time)
                if now_t - seen > 2 * self.datanode_lease_secs:
                    dead.add(p.id)
        if not dead:
            return []
        alive = [p for p in self.alive_datanodes(now_t)
                 if p.id not in dead]
        if not alive:
            return []
        alive_ids = {p.id for p in alive}
        with self._state_lock:
            load = {p.id: self._stats.get(p.id,
                                          DatanodeStat()).region_count
                    for p in alive}
            replica_seq = {nid: dict(m)
                           for nid, m in self._replica_seq.items()}
        # tables mid-balancer-op are off limits: re-placing a region the
        # balancer is migrating would dual-own it (both paths rewrite the
        # route); the op finishes or times out into a rollback first, and
        # a truly dead source is caught by the NEXT failover pass
        busy_tables = {o["table"] for o in self.balancer.ops()}
        moves: List[dict] = []
        for route in self.all_table_routes():
            if route.table_name in busy_tables:
                continue
            changed = False
            rewire: List = []      # region routes whose follower set or
            promote: List = []     # leader changed: re-wire the shipper
            assigned: Dict[int, List[int]] = {}
            catalog, schema_name, tname = route.table_name.split(".", 2)
            for rr in route.region_routes:
                live_followers = [f for f in rr.followers
                                  if f.id not in dead]
                if len(live_followers) != len(rr.followers):
                    rr.followers = live_followers
                    changed = True
                    rewire.append(rr)
                if rr.leader.id not in dead:
                    continue
                old = rr.leader
                rname = f"{route.table_id}_{rr.region_number:010d}"
                candidates = [f for f in rr.followers
                              if f.id in alive_ids]
                if candidates:
                    # most-caught-up follower takes over: its standby
                    # region already holds everything up to its
                    # replicated_seq, so promotion replays the least
                    best = max(candidates, key=lambda f: (
                        replica_seq.get(f.id, {}).get(rname, (0, 0))[0],
                        -f.id))
                    rr.leader = best
                    rr.followers = [f for f in rr.followers
                                    if f.id != best.id]
                    load[best.id] = load.get(best.id, 0) + 1
                    pmsg = {
                        "type": "repl_promote", "catalog": catalog,
                        "schema": schema_name, "table": tname,
                        "region": rr.region_number,
                        "old_leader": old.id}
                    # durable until a post-promote heartbeat confirms:
                    # the mail itself is fire-and-forget, and a new
                    # leader that dies mid-promote must get it again
                    self.kv.put(
                        f"{PROMOTE_PREFIX}{best.id}/{rname}",
                        json.dumps({"node": best.id,
                                    "region_name": rname,
                                    "msg": pmsg, "t": now_t}).encode())
                    promote.append((best.id, pmsg))
                    rewire.append(rr)
                    moves.append({"table": route.table_name,
                                  "region": rr.region_number,
                                  "from": old.id, "to": best.id,
                                  "promoted": True})
                else:
                    target = min(alive, key=lambda p: (load[p.id], p.id))
                    load[target.id] += 1
                    rr.leader = target
                    assigned.setdefault(target.id, []).append(
                        rr.region_number)
                    moves.append({"table": route.table_name,
                                  "region": rr.region_number,
                                  "from": old.id, "to": target.id})
                changed = True
            if not changed:
                continue
            route.version += 1     # placement changed: stale frontends
            _fp.fail_point("balancer_route_commit")       # must refresh
            self.kv.put(f"{ROUTE_PREFIX}{route.table_name}",
                        json.dumps(route.to_dict()).encode())
            info = self.table_info(route.table_name)
            for node_id, region_numbers in assigned.items():
                self.send_mailbox(node_id, {
                    "type": "open_regions", "catalog": catalog,
                    "schema": schema_name, "table": tname,
                    "table_id": route.table_id,
                    "region_numbers": region_numbers,
                    "table_info": info})
            # fire-and-forget (no op_id → no ack): promotions first so
            # the new leader unfences before shipping resumes, then
            # shipper re-wires reflecting the pruned/promoted sets
            for node_id, msg in promote:
                self.send_mailbox(node_id, msg)
            for rr in rewire:
                self.send_mailbox(rr.leader.id, {
                    "type": "repl_set_followers", "catalog": catalog,
                    "schema": schema_name, "table": tname,
                    "region": rr.region_number,
                    "followers": [f.to_dict() for f in rr.followers]})
        return moves

    def _retry_pending_promotions(self, now_t: float) -> None:
        """Re-mail repl_promote for promotions the new leader has not
        confirmed (a full heartbeat after the mail whose stats show the
        region out of standby). The step is idempotent on the datanode,
        so duplicate deliveries are harmless; a doc whose region has
        since been re-routed away from the node is dropped."""
        docs = self.kv.range(PROMOTE_PREFIX)
        if not docs:
            return
        with self._state_lock:
            stat_time = dict(self._stat_time)
            replica_seq = {nid: dict(m)
                           for nid, m in self._replica_seq.items()}
        leaders = {}
        for route in self.all_table_routes():
            for rr in route.region_routes:
                leaders[f"{route.table_id}_{rr.region_number:010d}"] = \
                    rr.leader.id
        for key, raw in docs:
            try:
                doc = json.loads(raw)
                nid, rname = int(doc["node"]), doc["region_name"]
            except (ValueError, KeyError, TypeError):
                self.kv.delete(key)
                continue
            if leaders.get(rname) != nid:
                self.kv.delete(key)    # superseded by a later failover
                continue
            if stat_time.get(nid, 0.0) > float(doc["t"]) and \
                    rname not in replica_seq.get(nid, {}):
                self.kv.delete(key)    # promotion confirmed
                continue
            self.send_mailbox(nid, doc["msg"])


class MetaClient:
    """Client SDK facade (reference: src/meta-client). In-process it calls
    the service directly; the wire version keeps the same surface."""

    def __init__(self, srv: MetaSrv) -> None:
        self._srv = srv

    def register(self, peer: Peer) -> None:
        self._srv.register_datanode(peer)

    def heartbeat(self, node_id: int, stat: Optional[DatanodeStat] = None
                  ) -> HeartbeatResponse:
        return self._srv.handle_heartbeat(node_id, stat)

    def create_route(self, full_name: str, region_numbers: List[int]
                     ) -> TableRoute:
        return self._srv.create_table_route(full_name, region_numbers)

    def route(self, full_name: str) -> Optional[TableRoute]:
        return self._srv.table_route(full_name)

    def delete_route(self, full_name: str) -> bool:
        return self._srv.delete_table_route(full_name)

    def rename_route(self, full_name: str,
                     new_full_name: str) -> Optional[TableRoute]:
        return self._srv.rename_table_route(full_name, new_full_name)

    def allocate_table_id(self) -> int:
        return self._srv.allocate_table_id()

    def cluster_info(self) -> List[dict]:
        return self._srv.cluster_info()

    def background_jobs(self) -> List[dict]:
        """In-process twin of the wire action: the shared process
        registry (the view's (node, job_id) dedup absorbs the
        duplication with the frontend's own rows)."""
        from ..common import background_jobs
        return background_jobs.rows()

    def region_heat(self) -> List[dict]:
        return self._srv.region_heat()

    def region_peers(self) -> List[dict]:
        return self._srv.region_peers()

    def admin_migrate_region(self, full_name: str, region: int,
                             to_node: int) -> dict:
        return self._srv.admin_migrate_region(full_name, region, to_node)

    def admin_split_region(self, full_name: str, region: int,
                           at_value: object = None) -> dict:
        return self._srv.admin_split_region(full_name, region, at_value)

    def admin_rebalance(self, full_name: Optional[str] = None
                        ) -> List[dict]:
        return self._srv.admin_rebalance(full_name)

    def admin_add_replica(self, full_name: str, region: int,
                          to_node: int) -> dict:
        return self._srv.admin_add_replica(full_name, region, to_node)

    def admin_remove_replica(self, full_name: str, region: int,
                             node: int) -> dict:
        return self._srv.admin_remove_replica(full_name, region, node)

    def balancer_configure(self, knob: str, value: object) -> None:
        self._srv.balancer.configure(knob, value)

    def balancer_ack(self, node_id: int, op_id: str, step: str, ok: bool,
                     error: Optional[str], payload: dict) -> None:
        self._srv.balancer_ack(node_id, op_id, step, ok, error, payload)

    def put_table_info(self, full_name: str, info: dict) -> None:
        self._srv.put_table_info(full_name, info)

    def table_info(self, full_name: str) -> Optional[dict]:
        return self._srv.table_info(full_name)

    def delete_table_info(self, full_name: str) -> bool:
        return self._srv.delete_table_info(full_name)

    # generic kv passthroughs (flow specs persist under __flow/ so a
    # restarted frontend recovers its continuous rollups from meta)
    def kv_put(self, key: str, value: bytes) -> None:
        self._srv.kv.put(key, value)

    def kv_get(self, key: str) -> Optional[bytes]:
        return self._srv.kv.get(key)

    def kv_range(self, prefix: str) -> List[Tuple[str, bytes]]:
        return self._srv.kv.range(prefix)

    def kv_delete(self, key: str) -> bool:
        return self._srv.kv.delete(key)
