"""Phi-accrual failure detector.

Reference behavior: src/meta-srv/src/failure_detector.rs:17-75 (an Akka
port): heartbeat intervals feed a bounded sample window; `phi(now)` is the
-log10 of the probability that a heartbeat is merely late given the
observed interval distribution (normal approximation with a minimum
standard deviation). phi crosses the threshold ⇒ the node is suspected.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Deque, Optional


class PhiAccrualFailureDetector:
    def __init__(self, *, threshold: float = 8.0,
                 min_std_deviation_ms: float = 100.0,
                 acceptable_heartbeat_pause_ms: float = 3000.0,
                 first_heartbeat_estimate_ms: float = 1000.0,
                 max_sample_size: int = 1000) -> None:
        self.threshold = threshold
        self.min_std_deviation_ms = min_std_deviation_ms
        self.acceptable_heartbeat_pause_ms = acceptable_heartbeat_pause_ms
        self.first_heartbeat_estimate_ms = first_heartbeat_estimate_ms
        self.max_sample_size = max_sample_size
        self._intervals: Deque[float] = deque(maxlen=max_sample_size)
        self._sum = 0.0
        self._sum_sq = 0.0
        self._last_heartbeat_ms: Optional[float] = None

    # ---- sample window ----
    def _push(self, interval: float) -> None:
        if len(self._intervals) == self.max_sample_size:
            old = self._intervals[0]
            self._sum -= old
            self._sum_sq -= old * old
        self._intervals.append(interval)
        self._sum += interval
        self._sum_sq += interval * interval

    @property
    def sample_count(self) -> int:
        return len(self._intervals)

    def _mean(self) -> float:
        n = len(self._intervals)
        return self._sum / n if n else 0.0

    def _std_dev(self) -> float:
        n = len(self._intervals)
        if n == 0:
            return 0.0
        mean = self._mean()
        var = max(self._sum_sq / n - mean * mean, 0.0)
        return max(math.sqrt(var), self.min_std_deviation_ms)

    # ---- protocol ----
    def heartbeat(self, now_ms: float) -> None:
        last = self._last_heartbeat_ms
        if last is not None:
            if now_ms >= last:
                self._push(now_ms - last)
        else:
            # bootstrap with a conservative synthetic distribution
            # (reference: first_heartbeat_estimate seeding)
            est = self.first_heartbeat_estimate_ms
            self._push(est)
            self._push(est + est / 4)
            self._push(max(est - est / 4, 0.0))
        self._last_heartbeat_ms = now_ms

    def phi(self, now_ms: float) -> float:
        if self._last_heartbeat_ms is None or not self._intervals:
            return 0.0
        elapsed = now_ms - self._last_heartbeat_ms
        mean = self._mean() + self.acceptable_heartbeat_pause_ms
        std = self._std_dev()
        y = (elapsed - mean) / std
        # P(X > elapsed) for logistic approximation of the normal CDF
        # (exponent clamped: |y| beyond ~±40 saturates p at 1 / 0)
        e = math.exp(max(min(-y * (1.5976 + 0.070566 * y * y), 700.0),
                         -700.0))
        if elapsed > mean:
            p = e / (1.0 + e)
        else:
            p = 1.0 - 1.0 / (1.0 + e)
        if p <= 0.0:
            return float("inf")
        return -math.log10(p)

    def is_available(self, now_ms: float) -> bool:
        return self.phi(now_ms) < self.threshold

    @property
    def last_heartbeat_ms(self) -> Optional[float]:
        return self._last_heartbeat_ms
