"""Statement executor: DDL + DML statements.

Reference behavior: src/frontend/src/statement.rs + the datanode SQL
handlers (src/datanode/src/sql/*.rs): CREATE/DROP/ALTER TABLE, CREATE/DROP
DATABASE, INSERT, DELETE, USE, SET, TRUNCATE, COPY TO/FROM.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np
import pandas as pd

from ..catalog import CatalogManager
from ..common.datasource import (file_codec, open_compressed_in,
                                 open_compressed_out)
from ..datatypes.data_type import parse_type_name
from ..datatypes.schema import (
    ColumnDefaultConstraint, ColumnSchema, Schema, SemanticType)
from ..errors import (
    DatabaseAlreadyExistsError, DatabaseNotFoundError, InvalidArgumentsError,
    PlanError, TableNotFoundError, UnsupportedError)
from ..query.expr import Evaluator
from ..query.output import Output
from ..session import QueryContext
from ..sql import ast
from ..table.requests import (
    AddColumnRequest, AlterKind, AlterTableRequest, CreateTableRequest,
    DropTableRequest)
from ..table.table import TableEngine


def build_column_schema(col: ast.ColumnDef, *, is_tag: bool,
                        is_time_index: bool) -> ColumnSchema:
    dtype = parse_type_name(col.type_name)
    semantic = SemanticType.FIELD
    if is_time_index:
        semantic = SemanticType.TIMESTAMP
        if not dtype.is_timestamp:
            raise InvalidArgumentsError(
                f"TIME INDEX column {col.name!r} must be a timestamp type")
    elif is_tag:
        semantic = SemanticType.TAG
    default = None
    if col.default is not None:
        d = col.default
        if isinstance(d, ast.FunctionCall) and d.name in (
                "current_timestamp", "now"):
            default = ColumnDefaultConstraint(function="current_timestamp")
        elif isinstance(d, ast.Literal):
            default = ColumnDefaultConstraint(value=d.value)
        elif isinstance(d, ast.UnaryOp) and d.op == "-" and \
                isinstance(d.operand, ast.Literal):
            default = ColumnDefaultConstraint(value=-d.operand.value)
        else:
            raise InvalidArgumentsError(
                f"unsupported default expression for {col.name!r}")
    nullable = col.nullable and not is_time_index and not is_tag
    return ColumnSchema(col.name, dtype, nullable=nullable,
                        semantic_type=semantic, default=default,
                        comment=col.comment or "")


def build_schema_from_create(stmt: ast.CreateTable):
    """CREATE TABLE statement → (Schema, primary-key indices)."""
    pk = set(stmt.primary_keys)
    cols = []
    for c in stmt.columns:
        cols.append(build_column_schema(
            c, is_tag=c.name in pk,
            is_time_index=c.name == stmt.time_index))
    schema = Schema(cols)
    pk_indices = [i for i, c in enumerate(cols)
                  if c.semantic_type == SemanticType.TAG]
    return schema, pk_indices


def evaluate_insert_rows(stmt: ast.Insert, columns, query_engine, ctx
                         ) -> dict:
    """INSERT VALUES/SELECT → column dict (shared by the standalone and
    distributed executors)."""
    if stmt.select is not None:
        out = query_engine.execute_query(stmt.select, ctx)
        rows = [list(r) for b in out.batches for r in b.rows()]
    else:
        ev = None
        rows = []
        for row in stmt.rows:
            if len(row) != len(columns):
                raise InvalidArgumentsError(
                    f"insert row has {len(row)} values, expected "
                    f"{len(columns)}")
            vals = []
            for e in row:
                # literal fast path: bulk VALUES lists are literals;
                # only expressions (now(), 1+2, ...) hit the evaluator
                if type(e) is ast.Literal:
                    vals.append(e.value)
                    continue
                if ev is None:
                    ev = Evaluator(pd.DataFrame(index=[0]))
                v = ev.eval(e)
                if isinstance(v, pd.Series):
                    v = v.iloc[0]
                vals.append(v)
            rows.append(vals)
    return {c: [r[i] for r in rows] for i, c in enumerate(columns)}


def show_flows_output(flow_manager, stmt: ast.ShowFlows,
                      ctx: QueryContext) -> Output:
    """SHOW FLOWS rendering (shared by the standalone and distributed
    executors). The `watermark` column carries wall-advancing fold state;
    the sqlness runner normalizes it in goldens."""
    import re

    from ..datatypes import data_type as dt
    from ..datatypes.record_batch import RecordBatch
    from ..datatypes.schema import ColumnSchema, Schema
    from ..query.expr import like_to_regex

    flows = flow_manager.flows(ctx.current_catalog, ctx.current_schema)
    if stmt.like:
        rx = re.compile(like_to_regex(stmt.like))
        flows = [f for f in flows if rx.match(f.name)]
    schema = Schema([
        ColumnSchema("flow_name", dt.STRING),
        ColumnSchema("source", dt.STRING),
        ColumnSchema("sink", dt.STRING),
        ColumnSchema("stride_ms", dt.INT64),
        ColumnSchema("aggs", dt.STRING),
        ColumnSchema("watermark", dt.INT64, nullable=True),
        ColumnSchema("rows_folded", dt.INT64),
    ])
    rb = RecordBatch.from_pydict(schema, {
        "flow_name": [f.name for f in flows],
        "source": [f.source for f in flows],
        "sink": [f.sink for f in flows],
        "stride_ms": [f.stride_ms for f in flows],
        "aggs": [", ".join(a.describe() for a in f.aggs) for f in flows],
        "watermark": [f.watermark_ts() for f in flows],
        "rows_folded": [f.stats.get("rows_folded", 0) for f in flows],
    })
    return Output.record_batches([rb], schema)


def delete_matching_rows(table, stmt: ast.Delete) -> Output:
    """DELETE ... WHERE: scan key columns, filter, delete by key (shared by
    the standalone and distributed executors)."""
    schema = table.schema
    tc = schema.timestamp_column
    key_cols = schema.tag_names() + ([tc.name] if tc else [])
    batches = table.scan_batches(projection=key_cols)
    frames = [pd.DataFrame(b.to_pydict()) for b in batches]
    df = pd.concat(frames, ignore_index=True) if frames else \
        pd.DataFrame(columns=key_cols)
    if stmt.where is not None and len(df):
        mask = Evaluator(df).eval(stmt.where)
        if isinstance(mask, pd.Series):
            df = df[mask.fillna(False).astype(bool)]
        elif not mask:
            df = df.iloc[0:0]
    if not len(df):
        return Output.rows(0)
    df = df.drop_duplicates()
    table.delete({c: df[c].tolist() for c in key_cols})
    return Output.rows(len(df))


def _int_setting(stmt: ast.SetVariable) -> int:
    try:
        return int(stmt.value)
    except (TypeError, ValueError):
        raise InvalidArgumentsError(
            f"SET {stmt.name}: expected an integer, got {stmt.value!r}")


def admin_ops_output(ops: List[dict]) -> Output:
    """Render ADMIN MIGRATE/SPLIT/REBALANCE results: one row per enqueued
    balancer operation (async — the op id is the tracking handle;
    information_schema.region_peers shows live state)."""
    from ..datatypes import data_type as dt
    from ..datatypes.record_batch import RecordBatch
    from ..datatypes.schema import Schema as _Schema

    def detail(op: dict) -> str:
        if op["kind"] == "migrate":
            return f"dn{op['from_node']} -> dn{op['to_node']}"
        if op["kind"] == "replica_add":
            return f"replica on dn{op['to_node']} (leader " \
                   f"dn{op['from_node']})"
        if op["kind"] == "replica_remove":
            return f"drop replica on dn{op['to_node']}"
        d = f"children={op['children']}"
        if op.get("at_value") is not None:
            d += f" at={op['at_value']!r}"
        return d

    schema = _Schema([
        ColumnSchema("op_id", dt.STRING),
        ColumnSchema("kind", dt.STRING),
        ColumnSchema("table_name", dt.STRING),
        ColumnSchema("region", dt.INT64),
        ColumnSchema("detail", dt.STRING),
        ColumnSchema("state", dt.STRING),
    ])
    rb = RecordBatch.from_pydict(schema, {
        "op_id": [op["id"] for op in ops],
        "kind": [op["kind"] for op in ops],
        "table_name": [op["table"] for op in ops],
        "region": [op["region"] for op in ops],
        "detail": [detail(op) for op in ops],
        "state": [op["state"] for op in ops],
    })
    return Output.record_batches([rb], schema)


def apply_show_trace(catalog: CatalogManager, stmt: ast.Admin,
                     sync_clients=None) -> Output:
    """Shared ADMIN SHOW TRACE handler: render one stored trace's
    reassembled per-node waterfall from greptime_private.trace_spans.
    One function for both frontends.

    `sync_clients` (distributed) lets buffered datanode spans catch up
    first: a cheap ping RPC per datanode carries the frontend's recent
    verdicts piggybacked on its body, and any released spans ride the
    response back — the same piggyback every RPC performs, just forced
    now so the waterfall is complete at render time."""
    from ..common import trace_store
    from ..datatypes import data_type as dt
    from ..datatypes.record_batch import RecordBatch
    from ..datatypes.schema import Schema as _Schema
    trace_id, rows = trace_store.sync_and_fetch(
        catalog, stmt.trace_id or "", clients=sync_clients)
    if trace_id is None:
        raise InvalidArgumentsError(
            "ADMIN SHOW TRACE 'last': no trace has been retained on "
            "this frontend yet")
    if not rows:
        raise InvalidArgumentsError(
            f"trace {trace_id!r} not found in greptime_private."
            f"trace_spans (sampled out, swept by retention, or never "
            f"existed)")
    wf = trace_store.waterfall_rows(rows)
    schema = _Schema([
        ColumnSchema("span", dt.STRING),
        ColumnSchema("node", dt.STRING),
        ColumnSchema("start_offset_ms", dt.INT64),
        ColumnSchema("duration_ms", dt.FLOAT64),
        ColumnSchema("self_ms", dt.FLOAT64),
        ColumnSchema("status", dt.STRING),
        ColumnSchema("detail", dt.STRING),
    ])
    rb = RecordBatch.from_pydict(schema, {
        k: [r[k] for r in wf] for k in schema.names()})
    return Output.record_batches([rb], schema)


def apply_show_profile(catalog: CatalogManager, stmt: ast.Admin,
                       sync_clients=None) -> Output:
    """Shared ADMIN SHOW PROFILE handler: render one query's (or
    trace's) stored folded stacks as a per-node top-down self/total
    tree from greptime_private.profile_samples. One function for both
    frontends.

    `sync_clients` (distributed) drains every datanode's writer-less
    sampler over the Flight `profile` action first, so remote samples
    are stored before the read — the profile twin of the trace
    handler's span-sync pings."""
    from ..common import profiler
    ident, rows = profiler.sync_and_fetch(
        catalog, stmt.trace_id or "", clients=sync_clients)
    if ident is None:
        raise InvalidArgumentsError(
            "ADMIN SHOW PROFILE 'last': no query has been profiled on "
            "this frontend yet (SET profiling = 1 and run one)")
    if not rows:
        raise InvalidArgumentsError(
            f"profile for {ident!r} not found in greptime_private."
            f"profile_samples (profiling was off while it ran, it was "
            f"too fast to sample, or retention swept it)")
    tree = profiler.profile_tree_rows(rows)
    from ..datatypes import data_type as dt
    from ..datatypes.record_batch import RecordBatch
    from ..datatypes.schema import Schema as _Schema
    schema = _Schema([
        ColumnSchema("frame", dt.STRING),
        ColumnSchema("node", dt.STRING),
        ColumnSchema("self_samples", dt.INT64),
        ColumnSchema("total_samples", dt.INT64),
    ])
    rb = RecordBatch.from_pydict(schema, {
        k: [r[k] for r in tree] for k in schema.names()})
    return Output.record_batches([rb], schema)


def apply_kill(stmt: ast.Kill) -> Output:
    """Shared KILL handler: trip the cancel event of a running statement
    in the process-wide registry. The killed statement raises
    QueryCancelledError at its next batch boundary; an unknown or
    already-finished id is a clean InvalidArgumentsError (the registry
    raises it), never a crash. One function for both frontends so the
    semantics cannot drift."""
    from ..common import process_list
    process_list.REGISTRY.kill(stmt.process_id)
    return Output.rows(1)


def apply_admin_maintenance(catalog: CatalogManager, stmt: ast.Admin,
                            ctx: QueryContext) -> Output:
    """Shared ADMIN FLUSH/COMPACT TABLE handler: force the table's
    regions through a flush (memtables → indexed L0 SSTs) or a manual
    compaction. One function for both frontends; the sqlness goldens
    and the index bench use it to pin the on-disk SST layout."""
    catalog_name, schema_name, name = ctx.resolve(stmt.table)
    table = catalog.table(catalog_name, schema_name, name)
    if table is None:
        raise TableNotFoundError(f"table {name!r} not found")
    if stmt.kind == "flush_table":
        table.flush()
        return Output.rows(0)
    regions = getattr(table, "regions", None)
    if not regions:
        # a DistTable over remote datanodes reports an EMPTY region
        # dict, not a missing attribute — silently compacting nothing
        # must not read as success
        raise UnsupportedError(
            "ADMIN COMPACT TABLE needs locally-hosted regions (on a "
            "cluster, run it against the datanodes)")
    for region in regions.values():
        region.compact()
    return Output.rows(0)


#: session variables wire clients set as connection boilerplate (mysql
#: connectors, psql, JDBC). Accepted as no-ops — erroring would break
#: every driver handshake — but ONLY these: any other unknown name is a
#: typo'd knob and errors identically on both frontends.
_CLIENT_COMPAT_VARS = frozenset({
    "names", "autocommit", "sql_mode", "wait_timeout",
    "net_write_timeout", "net_read_timeout", "interactive_timeout",
    "character_set_results", "character_set_client",
    "character_set_connection", "collation_connection", "sql_select_limit",
    "max_execution_time", "transaction_isolation", "tx_isolation",
    # postgres-dialect session boilerplate
    "client_encoding", "datestyle", "extra_float_digits", "search_path",
    "application_name", "statement_timeout",
})


def apply_set_variable(stmt: ast.SetVariable, ctx: QueryContext) -> Output:
    """Shared SET handler: every knob here is session- or process-level
    state, so the standalone executor and the distributed frontend
    (DistInstance.execute_stmt) both route through this one function."""
    name = stmt.name.lower()
    if name in ("time_zone", "timezone"):
        ctx.time_zone = str(stmt.value)
    elif name == "slow_query_threshold_ms":
        # 0 or negative disables; default comes from the
        # GREPTIME_SLOW_QUERY_MS env/config (off when unset)
        from ..common.telemetry import set_slow_query_threshold_ms
        set_slow_query_threshold_ms(_int_setting(stmt))
    elif name == "rollup_rewrite":
        # flow rollup-rewrite kill switch (differential tests and
        # operators compare against the raw path with it off)
        from ..flow import rewrite as flow_rewrite
        try:
            flow_rewrite.set_enabled(bool(int(stmt.value)))
        except (TypeError, ValueError):
            raise InvalidArgumentsError(
                f"SET {stmt.name}: expected 0 or 1, got {stmt.value!r}")
    elif name.startswith("failpoint_"):
        # fault-injection surface: SET failpoint_<point> = 'action'
        # ('off' or 0 disarms). Same registry as GREPTIME_FAILPOINTS
        # and /v1/admin/failpoints (common/failpoint.py).
        from ..common import failpoint
        point = name[len("failpoint_"):]
        spec = str(stmt.value)
        try:
            failpoint.configure(point, None if spec in ("0", "off")
                                else spec)
        except ValueError as e:
            raise InvalidArgumentsError(f"SET {stmt.name}: {e}")
    elif name in ("objstore_max_retries", "objstore_retry_base_ms"):
        from ..storage.retry import configure_retry
        value = _int_setting(stmt)
        if name == "objstore_max_retries":
            configure_retry(max_retries=value)
        else:
            configure_retry(base_ms=value)
    elif name == "dist_fanout":
        # per-statement bound on concurrently in-flight datanode RPCs
        # in the distributed scatter-gather (1 = serial, the pre-
        # parallel behavior — the bench differential uses it)
        from ..common.runtime import configure_dist_fanout
        configure_dist_fanout(_int_setting(stmt))
    elif name in ("dist_rpc_max_retries", "dist_rpc_retry_base_ms"):
        from .distributed import configure_dist_rpc_retry
        value = _int_setting(stmt)
        if name == "dist_rpc_max_retries":
            configure_dist_rpc_retry(max_retries=value)
        else:
            configure_dist_rpc_retry(base_ms=value)
    elif name in ("stream_threshold_rows", "tpu_dispatch_min_rows"):
        value = _int_setting(stmt)
        if name == "stream_threshold_rows":
            # expose the cold-scan streaming knob to SQL so operators
            # (and the sqlness explain goldens) can pin the dispatch
            # decision without a config reload
            from ..query.stream_exec import configure_streaming
            configure_streaming(threshold_rows=value)
        else:
            # static device-dispatch floor (the latency-adaptive
            # floor never goes below it). Pinning it also resets the
            # adaptive observation: an operator setting the floor
            # expects it to take effect now, not to stay shadowed by
            # the fixed-cost estimate of earlier queries — and the
            # sqlness EXPLAIN ANALYZE goldens rely on the reset for
            # deterministic dispatch lines.
            from ..query import tpu_exec
            tpu_exec.TPU_DISPATCH_MIN_ROWS = value
            tpu_exec._observed_min_dt[0] = None
    elif name in ("wal_group_commit", "wal_group_max_wait_us",
                  "wal_group_max_batch"):
        # WAL group-commit knobs: concurrent sync_on_write writers share
        # one fsync; the toggle is the bench differential's kill switch
        from ..storage.wal import configure_group_commit
        value = _int_setting(stmt)
        try:
            if name == "wal_group_commit":
                configure_group_commit(enabled=bool(value))
            elif name == "wal_group_max_wait_us":
                configure_group_commit(max_wait_us=value)
            else:
                configure_group_commit(max_batch=value)
        except ValueError as e:
            raise InvalidArgumentsError(f"SET {stmt.name}: {e}")
    elif name in ("ingest_coalesce", "ingest_coalesce_window_ms"):
        # protocol-ingest coalescer (servers/coalesce.py): merge
        # concurrent small same-table writes into shared bulk batches
        from ..servers.coalesce import configure_coalescer
        value = _int_setting(stmt)
        try:
            if name == "ingest_coalesce":
                configure_coalescer(enabled=bool(value))
            else:
                configure_coalescer(window_ms=value)
        except ValueError as e:
            raise InvalidArgumentsError(f"SET {stmt.name}: {e}")
    elif name == "exact_distinct":
        # 1 = refuse sketch partials for count(DISTINCT): the statement
        # takes the raw-row path, exact at any cardinality
        from ..query import sketches
        sketches.configure(exact_distinct=bool(_int_setting(stmt)))
    elif name == "approx_error_target":
        # target relative error for the approx aggregates: drives the
        # HLL precision and the t-digest compression together
        from ..query import sketches
        try:
            sketches.configure(error_target=float(stmt.value))
        except (TypeError, ValueError):
            raise InvalidArgumentsError(
                f"SET {stmt.name}: expected a number in [0.001, 0.25], "
                f"got {stmt.value!r}")
    elif name == "dist_partial_agg":
        # distributed partial-aggregate pushdown kill switch: 0 sends
        # GROUP BYs over DistTables through the raw-row scatter (the
        # bench differential compares wire bytes against it)
        from ..query import tpu_exec
        tpu_exec.configure_partial_pushdown(
            enabled=bool(_int_setting(stmt)))
    elif name == "scan_fusion":
        # single-flight fusion of concurrent identical small scans of
        # one region (query/tpu_exec.py); 0 = every scan solo
        from ..query import tpu_exec
        tpu_exec.configure_scan_fusion(enabled=bool(_int_setting(stmt)))
    elif name == "sst_index":
        # per-SST secondary indexes (storage/index.py): 0 disables both
        # sidecar writes and every index consult — point/IN queries then
        # take the pre-index stats-only read path (the bench
        # differential's kill switch; env twin GREPTIME_SST_INDEX)
        from ..storage.index import configure_sst_index
        configure_sst_index(enabled=bool(_int_setting(stmt)))
    elif name in ("admission_max_inflight", "admission_max_queued_bytes",
                  "admission_retry_after_s"):
        # admission gate (common/admission.py): 0 disables a dimension
        from ..common.admission import GATE
        value = _int_setting(stmt)
        try:
            if name == "admission_max_inflight":
                GATE.configure(max_inflight=value)
            elif name == "admission_max_queued_bytes":
                GATE.configure(max_queued_bytes=value)
            else:
                GATE.configure(retry_after_s=value)
        except ValueError as e:
            raise InvalidArgumentsError(f"SET {stmt.name}: {e}")
    elif name == "trace_sample_ratio":
        # head-sample rate of the tail-sampling trace store (slow/
        # error/KILLed/balancer traces retain regardless); 0 = only
        # tail-flagged traces persist, 1 = everything does
        from ..common import trace_store
        try:
            trace_store.configure(sample_ratio=float(stmt.value))
        except (TypeError, ValueError):
            raise InvalidArgumentsError(
                f"SET {stmt.name}: expected a number in [0, 1], got "
                f"{stmt.value!r}")
    elif name == "trace_retention_ms":
        # retention for greptime_private.trace_spans (swept batched on
        # the self-monitor tick; 0 disables). Separate from
        # self_monitor_retention_ms — traces are bulkier than metrics
        from ..common import trace_store
        trace_store.configure(retention_ms=_int_setting(stmt))
    elif name == "profiling":
        # continuous stack sampler master switch (common/profiler.py);
        # env twin GREPTIME_PROFILING. Sampling starts/stops live.
        from ..common import profiler
        profiler.configure(enabled=bool(_int_setting(stmt)))
    elif name == "profile_hz":
        # continuous sampling rate (default ~19 Hz — low enough for
        # always-on, high enough to catch a slow query's hot frames)
        from ..common import profiler
        try:
            profiler.configure(hz=float(stmt.value))
        except (TypeError, ValueError):
            raise InvalidArgumentsError(
                f"SET {stmt.name}: expected a rate in "
                f"[{profiler.MIN_HZ:g}, {profiler.MAX_HZ:g}] Hz, got "
                f"{stmt.value!r}")
    elif name == "profile_retention_ms":
        # retention for greptime_private.profile_samples (swept batched
        # on the self-monitor tick; 0 disables). Separate knob from the
        # trace/metrics windows — profiles age fastest
        from ..common import profiler
        profiler.configure(retention_ms=_int_setting(stmt))
    elif name == "self_monitor_retention_ms":
        # retention window for greptime_private.node_metrics /
        # region_heat (monitor/scraper.py sweeps on each tick;
        # 0 disables the sweep)
        from ..monitor import scraper
        scraper.configure_retention(_int_setting(stmt))
    elif name.startswith("balancer_"):
        # elastic-region balancer knobs live in meta-srv; the distributed
        # frontend intercepts and forwards them BEFORE this shared
        # handler, so reaching here means a standalone deployment
        raise InvalidArgumentsError(
            f"SET {stmt.name}: balancer knobs apply to a distributed "
            f"cluster (standalone has no region balancer)")
    elif name in ("read_replica", "replica_max_lag_ms"):
        # replica-aware read routing is a distributed-frontend feature
        # (DistInstance intercepts BEFORE this shared handler); a
        # standalone deployment has no region replicas to read from
        from ..errors import UnsupportedError
        raise UnsupportedError(
            f"SET {stmt.name}: read replicas require a distributed "
            f"deployment (metasrv + datanodes)")
    elif name in _CLIENT_COMPAT_VARS or name.startswith("@"):
        # connection boilerplate from wire clients: accepted, ignored
        pass
    else:
        # unknown knob: the SAME error on both frontends (this function
        # is the one SET path), instead of the silent success that let a
        # typo'd `SET slow_query_treshold_ms` do nothing
        raise InvalidArgumentsError(
            f"SET {stmt.name}: unknown session variable (see README "
            f"'Session variables' for the supported knobs)")
    return Output.rows(0)


class StatementExecutor:
    def __init__(self, catalog: CatalogManager,
                 engines: Dict[str, TableEngine], query_engine,
                 procedure_manager=None, flow_manager=None):
        self.catalog = catalog
        self.engines = engines
        self.query_engine = query_engine
        # when present, DDL runs as durable procedures (reference:
        # table-procedure + mito DDL procedures)
        self.procedure_manager = procedure_manager
        # continuous rollup flows (flow/manager.py)
        self.flow_manager = flow_manager

    def engine_for(self, name: str) -> TableEngine:
        engine = self.engines.get(name)
        if engine is None:
            raise UnsupportedError(f"unknown table engine {name!r}")
        return engine

    # ---- DDL ----
    def create_table(self, stmt: ast.CreateTable, ctx: QueryContext) -> Output:
        catalog, schema_name, table_name = ctx.resolve(stmt.name)
        if not self.catalog.schema_exists(catalog, schema_name):
            raise DatabaseNotFoundError(
                f"schema {catalog}.{schema_name} not found")
        if self.catalog.table(catalog, schema_name, table_name) is not None:
            if stmt.if_not_exists:
                return Output.rows(0)
            from ..errors import TableAlreadyExistsError
            raise TableAlreadyExistsError(
                f"table {table_name!r} already exists")
        schema, pk_indices = build_schema_from_create(stmt)
        # CREATE EXTERNAL TABLE routes to the file engine (reference:
        # file-table-engine; immutable, single-step — no procedure)
        engine_name = "file" if stmt.external else stmt.engine
        engine = self.engine_for(engine_name)
        if stmt.external:
            table = engine.create_table(CreateTableRequest(
                table_name, schema, catalog_name=catalog,
                schema_name=schema_name,
                primary_key_indices=pk_indices,
                create_if_not_exists=stmt.if_not_exists,
                table_options=dict(stmt.options)))
            self.catalog.register_table(catalog, schema_name, table_name,
                                        table)
            return Output.rows(0)
        request = CreateTableRequest(
            table_name, schema, catalog_name=catalog,
            schema_name=schema_name, primary_key_indices=pk_indices,
            create_if_not_exists=stmt.if_not_exists,
            table_options=dict(stmt.options), partitions=stmt.partitions)
        if self.procedure_manager is not None:
            from ..mito.procedure import CreateTableProcedure
            self.procedure_manager.submit(CreateTableProcedure(
                request, engine, self.catalog)).wait()
            return Output.rows(0)
        table = engine.create_table(request)
        self.catalog.register_table(catalog, schema_name, table_name, table)
        return Output.rows(0)

    def create_database(self, stmt: ast.CreateDatabase,
                        ctx: QueryContext) -> Output:
        try:
            self.catalog.register_schema(ctx.current_catalog, stmt.name)
        except DatabaseAlreadyExistsError:
            if not stmt.if_not_exists:
                raise
        return Output.rows(1)

    def drop_table(self, stmt: ast.DropTable, ctx: QueryContext) -> Output:
        catalog, schema_name, table_name = ctx.resolve(stmt.name)
        table = self.catalog.table(catalog, schema_name, table_name)
        if table is None:
            if stmt.if_exists:
                return Output.rows(0)
            raise TableNotFoundError(f"table {table_name!r} not found")
        engine = self.engine_for(table.info.meta.engine)
        request = DropTableRequest(table_name, catalog, schema_name)
        if self.procedure_manager is not None:
            from ..mito.procedure import DropTableProcedure
            self.procedure_manager.submit(DropTableProcedure(
                request, engine, self.catalog)).wait()
            return Output.rows(0)
        engine.drop_table(request)
        self.catalog.deregister_table(catalog, schema_name, table_name)
        return Output.rows(0)

    def drop_database(self, stmt: ast.DropDatabase,
                      ctx: QueryContext) -> Output:
        catalog = ctx.current_catalog
        if not self.catalog.schema_exists(catalog, stmt.name):
            if stmt.if_exists:
                return Output.rows(0)
            raise DatabaseNotFoundError(f"database {stmt.name!r} not found")
        for tname in list(self.catalog.table_names(catalog, stmt.name)):
            table = self.catalog.table(catalog, stmt.name, tname)
            engine = self.engines.get(table.info.meta.engine)
            if engine is not None:
                engine.drop_table(DropTableRequest(tname, catalog, stmt.name))
            self.catalog.deregister_table(catalog, stmt.name, tname)
        self.catalog.deregister_schema(catalog, stmt.name)
        return Output.rows(0)

    def alter_table(self, stmt: ast.AlterTable, ctx: QueryContext) -> Output:
        catalog, schema_name, table_name = ctx.resolve(stmt.table)
        table = self.catalog.table(catalog, schema_name, table_name)
        if table is None:
            raise TableNotFoundError(f"table {table_name!r} not found")
        engine = self.engine_for(table.info.meta.engine)
        op = stmt.operation
        if isinstance(op, ast.AddColumn):
            cs = build_column_schema(op.column, is_tag=False,
                                     is_time_index=False)
            req = AlterTableRequest(
                table_name, AlterKind.ADD_COLUMNS, catalog_name=catalog,
                schema_name=schema_name,
                add_columns=[AddColumnRequest(cs, location=op.location)])
        elif isinstance(op, ast.DropColumn):
            req = AlterTableRequest(
                table_name, AlterKind.DROP_COLUMNS, catalog_name=catalog,
                schema_name=schema_name, drop_columns=[op.name])
        elif isinstance(op, ast.RenameTable):
            req = AlterTableRequest(
                table_name, AlterKind.RENAME_TABLE, catalog_name=catalog,
                schema_name=schema_name, new_table_name=op.new_name)
        else:
            raise UnsupportedError(f"ALTER operation {type(op).__name__}")
        if self.procedure_manager is not None:
            from ..mito.procedure import AlterTableProcedure
            self.procedure_manager.submit(AlterTableProcedure(
                req, engine, self.catalog)).wait()
            return Output.rows(0)
        engine.alter_table(req)
        if isinstance(op, ast.RenameTable):
            self.catalog.rename_table(catalog, schema_name, table_name,
                                      op.new_name)
        return Output.rows(0)

    def truncate_table(self, stmt: ast.TruncateTable,
                       ctx: QueryContext) -> Output:
        catalog, schema_name, table_name = ctx.resolve(stmt.name)
        table = self.catalog.table(catalog, schema_name, table_name)
        if table is None:
            raise TableNotFoundError(f"table {table_name!r} not found")
        engine = self.engine_for(table.info.meta.engine)
        engine.truncate_table(catalog, schema_name, table_name)
        return Output.rows(0)

    # ---- flows (continuous rollups) ----
    def _require_flows(self):
        if self.flow_manager is None:
            raise UnsupportedError("flows are not enabled on this node")
        return self.flow_manager

    def create_flow(self, stmt: ast.CreateFlow, ctx: QueryContext) -> Output:
        self._require_flows().create_flow(stmt, ctx)
        return Output.rows(0)

    def drop_flow(self, stmt: ast.DropFlow, ctx: QueryContext) -> Output:
        self._require_flows().drop_flow(stmt.name, ctx,
                                        if_exists=stmt.if_exists)
        return Output.rows(0)

    def show_flows(self, stmt: ast.ShowFlows, ctx: QueryContext) -> Output:
        return show_flows_output(self._require_flows(), stmt, ctx)

    # ---- DML ----
    def insert(self, stmt: ast.Insert, ctx: QueryContext) -> Output:
        catalog, schema_name, table_name = ctx.resolve(stmt.table)
        table = self.catalog.table(catalog, schema_name, table_name)
        if table is None:
            raise TableNotFoundError(f"table {table_name!r} not found")
        schema = table.schema
        columns = stmt.columns or schema.names()
        for c in columns:
            if not schema.contains(c):
                from ..errors import ColumnNotFoundError
                raise ColumnNotFoundError(
                    f"column {c!r} not found in {table_name!r}")
        data = evaluate_insert_rows(stmt, columns, self.query_engine, ctx)
        n = table.insert(data)
        return Output.rows(n)

    def delete(self, stmt: ast.Delete, ctx: QueryContext) -> Output:
        catalog, schema_name, table_name = ctx.resolve(stmt.table)
        table = self.catalog.table(catalog, schema_name, table_name)
        if table is None:
            raise TableNotFoundError(f"table {table_name!r} not found")
        return delete_matching_rows(table, stmt)

    # ---- session ----
    def use_database(self, stmt: ast.Use, ctx: QueryContext) -> Output:
        if not self.catalog.schema_exists(ctx.current_catalog, stmt.database):
            raise DatabaseNotFoundError(
                f"database {stmt.database!r} not found")
        ctx.set_current_schema(stmt.database)
        return Output.rows(0)

    def set_variable(self, stmt: ast.SetVariable, ctx: QueryContext) -> Output:
        return apply_set_variable(stmt, ctx)

    # ---- COPY ----
    def copy(self, stmt: ast.Copy, ctx: QueryContext) -> Output:
        catalog, schema_name, table_name = ctx.resolve(stmt.table)
        table = self.catalog.table(catalog, schema_name, table_name)
        if table is None:
            raise TableNotFoundError(f"table {table_name!r} not found")
        fmt = str(stmt.options.get("format", "parquet")).lower()
        path = stmt.path
        codec = file_codec(path, stmt.options.get("compression"))
        if stmt.direction == "to":
            return self._copy_to(table, path, fmt, codec)
        return self._copy_from(table, path, fmt, codec)

    def _copy_to(self, table, path: str, fmt: str,
                 codec: Optional[str]) -> Output:
        import pyarrow as pa
        import pyarrow.parquet as pq

        batches = table.scan_batches()
        arrow_batches = [b.to_arrow() for b in batches if b.num_rows]
        tbl = pa.Table.from_batches(arrow_batches) if arrow_batches else \
            pa.Table.from_batches([], schema=table.schema.to_arrow())
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if fmt == "parquet":
            pq.write_table(tbl, path)      # parquet compresses internally
        elif fmt == "csv":
            import pyarrow.csv as pcsv
            with open_compressed_out(path, codec) as sink:
                pcsv.write_csv(tbl, sink)
        elif fmt == "json":
            data = tbl.to_pandas().to_json(None, orient="records",
                                           lines=True, date_format="iso")
            with open_compressed_out(path, codec) as sink:
                sink.write(data.encode())
        else:
            raise UnsupportedError(f"COPY format {fmt!r}")
        return Output.rows(tbl.num_rows)

    def _copy_from(self, table, path: str, fmt: str,
                   codec: Optional[str]) -> Output:
        import io as _io

        import pyarrow.parquet as pq

        if fmt == "parquet":
            tbl = pq.read_table(path)
        elif fmt == "csv":
            import pyarrow.csv as pcsv
            with open_compressed_in(path, codec) as src:
                tbl = pcsv.read_csv(src)
        elif fmt == "json":
            import pyarrow as pa
            with open_compressed_in(path, codec) as src:
                raw = src.read()
            raw = raw.to_pybytes() if hasattr(raw, "to_pybytes") else raw
            tbl = pd.read_json(_io.BytesIO(raw), orient="records",
                               lines=True)
            tbl = pa.Table.from_pandas(tbl)
        else:
            raise UnsupportedError(f"COPY format {fmt!r}")
        from ..datatypes.record_batch import arrow_to_ingest_columns
        cols = arrow_to_ingest_columns(tbl, table.schema)
        # WAL-less direct-to-SST load when the engine supports it — the
        # SSTs + one manifest edit are the durability story for COPY FROM
        bulk = getattr(table, "bulk_load", None)
        n = bulk(cols) if bulk is not None else table.insert(cols)
        return Output.rows(n)
