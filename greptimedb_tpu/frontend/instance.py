"""FrontendInstance: the handler all protocol servers call into.

Reference behavior: src/frontend/src/instance.rs — implements
`SqlQueryHandler` (do_query), auto create/alter-on-insert for protocol
ingest (instance.rs:281-342), and wires the statement executor + query
engine. In standalone mode it sits directly on an in-process datanode
(instance.rs:200-222).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..datanode import DatanodeInstance
from ..datatypes.data_type import (
    ConcreteDataType, FLOAT64, INT64, STRING, TIMESTAMP_MILLISECOND)
from ..datatypes.schema import ColumnSchema, Schema, SemanticType
from ..errors import GreptimeError, TableNotFoundError
from ..query.output import Output
from ..session import QueryContext
from ..sql import ast, parse_statements
from ..table.requests import (
    AddColumnRequest, AlterKind, AlterTableRequest, CreateTableRequest)
from .statement import StatementExecutor

GREPTIME_TIMESTAMP = "greptime_timestamp"
GREPTIME_VALUE = "greptime_value"

#: dedicated logger so operators can route/filter the slow-query log
#: independently (reference: the slow_query appender in common-telemetry)
import logging
_slow_logger = logging.getLogger("greptimedb_tpu.slow_query")


class FrontendInstance:
    def __init__(self, datanode: DatanodeInstance):
        self.datanode = datanode
        self.catalog = datanode.catalog
        self.query_engine = datanode.query_engine
        self.statement_executor = StatementExecutor(
            self.catalog, datanode.engines, self.query_engine,
            procedure_manager=datanode.procedure_manager,
            flow_manager=getattr(datanode, "flow_manager", None))
        self._tql_engine = None
        self.script_engine = None
        from ..common.plugins import Plugins
        self.plugins = Plugins()
        # self-monitoring: the scraper walks the telemetry registry +
        # per-region heat and writes both through handle_row_insert into
        # greptime_private system tables (monitor/scraper.py)
        from ..common import (background_jobs, process_list, profiler,
                              trace_store)
        from ..monitor import SelfMonitor
        self.self_monitor = SelfMonitor(self, node_label="standalone")
        self.catalog.self_monitor = self.self_monitor
        process_list.configure_node("standalone")
        background_jobs.configure_node("standalone")
        # durable trace store: completed spans buffer in the sink; the
        # tail verdict fires at trace completion (this process roots its
        # statements' traces) and retained spans flush through
        # handle_row_insert into greptime_private.trace_spans
        self.trace_sink = trace_store.TraceSink(
            node_label="standalone", service="standalone", role="root",
            writer=self)
        trace_store.install(self.trace_sink)
        self.catalog.trace_sink = self.trace_sink
        # continuous profiler: folded stacks aggregate in-process and
        # flush on the self-monitor tick into
        # greptime_private.profile_samples (SET profiling = 1 arms it)
        self.profiler = profiler.Profiler(node_label="standalone",
                                          writer=self)
        profiler.install(self.profiler)

    def start(self) -> None:
        if not self.datanode._started:
            self.datanode.start()
        # recompile + re-register persisted coprocessors (reference:
        # scripts system table, src/script/src/table.rs:51)
        from ..script import ScriptEngine
        self.script_engine = ScriptEngine(self)
        self.script_engine.load_scripts()
        # free-running scrape tick only outside pytest (tests drive
        # tick() cooperatively — the same tier-1 rule flows follow)
        import os as _os
        interval = getattr(self.datanode.opts,
                           "self_monitor_interval_s", 0)
        if interval > 0 and "PYTEST_CURRENT_TEST" not in _os.environ:
            self.self_monitor.start_background(interval)

    def shutdown(self) -> None:
        self.self_monitor.stop()
        self.profiler.stop(join=False)
        self.datanode.shutdown()

    # ---- SqlQueryHandler ----
    def do_query(self, sql: str, ctx: Optional[QueryContext] = None
                 ) -> List[Output]:
        ctx = ctx or QueryContext()
        interceptor = self._interceptor()
        if interceptor is not None:
            sql = interceptor.pre_parsing(sql, ctx)
        stmts = parse_statements(sql)
        if interceptor is not None:
            stmts = interceptor.post_parsing(stmts, ctx)
        import time as _time

        from ..common import process_list
        from ..common.telemetry import (
            increment_counter, observe_latency, slow_query_threshold_ms,
            span, timer)
        from ..common.admission import GATE as _admission
        outputs = []
        for s in stmts:
            # admission gate: reject-with-retry-after past the in-flight
            # limit (KILL/SET stay admitted — the operator's way out)
            _admission.admit_statement(type(s).__name__)
            if interceptor is not None:
                interceptor.pre_execute(s, ctx)
            t0 = _time.perf_counter()
            prev_stats = getattr(self.query_engine, "last_exec_stats",
                                 None)
            try:
                with span("execute_stmt", stmt=type(s).__name__,
                          channel=ctx.channel.value) as sp, \
                        timer("stmt_execute"), \
                        process_list.track(
                            sql, protocol=ctx.channel.value,
                            catalog=ctx.current_catalog,
                            schema=ctx.current_schema,
                            trace_id=sp["trace_id"]):
                    out = self.execute_stmt(s, ctx)
            finally:
                # log-bucketed latency distribution per statement kind ×
                # protocol: the p50/p95/p99 rows in runtime_metrics and
                # the _bucket series on /metrics. Recorded in a finally —
                # statements that stall then RAISE are the ones an
                # operator most needs in the distribution
                observe_latency(
                    "stmt_latency",
                    _time.perf_counter() - t0,
                    stmt=type(s).__name__, protocol=ctx.channel.value)
            increment_counter(f"stmt_{type(s).__name__.lower()}")
            elapsed_ms = (_time.perf_counter() - t0) * 1e3
            thr = slow_query_threshold_ms()
            if thr is not None and elapsed_ms >= thr:
                # only attach ExecStats THIS statement produced — a slow
                # DDL/DML or plain EXPLAIN (which never collects) must
                # not report the previous SELECT's stages
                stats = getattr(self.query_engine, "last_exec_stats",
                                None)
                if stats is prev_stats:
                    stats = None
                # trace_stored makes the WARN a working pointer: 'yes'
                # means ADMIN SHOW TRACE '<trace>' can replay it later
                from ..common import profiler, trace_store
                sink = trace_store.sink()
                _slow_logger.warning(
                    "slow query: %.1fms (threshold %dms) trace=%s "
                    "trace_stored=%s%s stmt=%r stats=[%s]", elapsed_ms,
                    thr, sp["trace_id"],
                    sink.stored_verdict(sp["trace_id"])
                    if sink is not None else "off",
                    profiler.slow_query_suffix(sp["trace_id"]), sql,
                    stats.summary() if stats is not None else "n/a")
            if interceptor is not None:
                out = interceptor.post_execute(out, ctx)
            outputs.append(out)
        return outputs

    def _interceptor(self):
        """Plugin chain hook (reference: SqlQueryInterceptor consulted by
        every protocol frontend, src/servers/src/interceptor.rs:26)."""
        from ..servers.interceptor import SqlQueryInterceptor
        return self.plugins.get(SqlQueryInterceptor)

    def execute_stmt(self, stmt: ast.Statement, ctx: QueryContext) -> Output:
        ex = self.statement_executor
        if isinstance(stmt, ast.CreateTable):
            return ex.create_table(stmt, ctx)
        if isinstance(stmt, ast.CreateDatabase):
            return ex.create_database(stmt, ctx)
        if isinstance(stmt, ast.DropTable):
            return ex.drop_table(stmt, ctx)
        if isinstance(stmt, ast.DropDatabase):
            return ex.drop_database(stmt, ctx)
        if isinstance(stmt, ast.AlterTable):
            return ex.alter_table(stmt, ctx)
        if isinstance(stmt, ast.TruncateTable):
            return ex.truncate_table(stmt, ctx)
        if isinstance(stmt, ast.Insert):
            return ex.insert(stmt, ctx)
        if isinstance(stmt, ast.Delete):
            return ex.delete(stmt, ctx)
        if isinstance(stmt, ast.CreateFlow):
            return ex.create_flow(stmt, ctx)
        if isinstance(stmt, ast.DropFlow):
            return ex.drop_flow(stmt, ctx)
        if isinstance(stmt, ast.ShowFlows):
            return ex.show_flows(stmt, ctx)
        if isinstance(stmt, ast.Use):
            return ex.use_database(stmt, ctx)
        if isinstance(stmt, ast.SetVariable):
            return ex.set_variable(stmt, ctx)
        if isinstance(stmt, ast.Kill):
            from .statement import apply_kill
            return apply_kill(stmt)
        if isinstance(stmt, ast.Admin):
            if stmt.kind in ("flush_table", "compact_table"):
                from .statement import apply_admin_maintenance
                return apply_admin_maintenance(self.catalog, stmt, ctx)
            if stmt.kind == "show_trace":
                from .statement import apply_show_trace
                return apply_show_trace(self.catalog, stmt)
            if stmt.kind == "show_profile":
                from .statement import apply_show_profile
                return apply_show_profile(self.catalog, stmt)
            # region placement is a cluster concept: standalone's single
            # implicit node has nothing to migrate/split between
            from ..errors import UnsupportedError
            raise UnsupportedError(
                "ADMIN region operations require a distributed "
                "deployment (metasrv + datanodes)")
        if isinstance(stmt, ast.Copy):
            return ex.copy(stmt, ctx)
        if isinstance(stmt, ast.Tql):
            return self.execute_tql(stmt, ctx)
        return self.query_engine.execute(stmt, ctx)

    def promql_engine(self):
        """Lazily-built, shared PromQL engine (TQL + /api/v1 + /v1/promql)."""
        if self._tql_engine is None:
            try:
                from ..promql.engine import PromqlEngine
            except ImportError as e:
                from ..errors import UnsupportedError
                raise UnsupportedError(
                    f"PromQL engine unavailable: {e}") from e
            self._tql_engine = PromqlEngine(self.catalog)
        return self._tql_engine

    def execute_tql(self, stmt: ast.Tql, ctx: QueryContext) -> Output:
        return self.promql_engine().execute_tql(stmt, ctx)

    # ---- protocol ingest: auto create / alter on demand ----
    def handle_row_insert(
        self, table_name: str, columns: Dict[str, Sequence],
        *, tag_columns: Sequence[str] = (),
        timestamp_column: str = GREPTIME_TIMESTAMP,
        types: Optional[Dict[str, ConcreteDataType]] = None,
        ctx: Optional[QueryContext] = None,
    ) -> int:
        """Insert with auto table create / auto column add (reference:
        create_or_alter_table_on_demand, src/frontend/src/instance.rs:292)."""
        ctx = ctx or QueryContext()
        catalog, schema_name = ctx.current_catalog, ctx.current_schema
        table = self.catalog.table(catalog, schema_name, table_name)
        types = types or {}
        if table is None:
            table = self._create_on_demand(
                catalog, schema_name, table_name, columns, tag_columns,
                timestamp_column, types)
            # a concurrent protocol auto-create may have won the race
            # with a NARROWER shape (coalesced ingest makes first-write
            # storms normal): fall through to alter-on-demand against
            # the adopted table so this request's field columns exist
            self._alter_on_demand(table, catalog, schema_name, table_name,
                                  columns, types, tag_columns)
        else:
            self._alter_on_demand(table, catalog, schema_name, table_name,
                                  columns, types, tag_columns)
        # re-fetch for the post-alter schema; a concurrent DROP may have
        # emptied the slot — keep the handle we hold (its closed region
        # raises a clean taxonomy error, not AttributeError on None)
        table = self.catalog.table(catalog, schema_name, table_name) \
            or table
        return table.insert(columns)

    def handle_bulk_load(
        self, table_name: str, columns: Dict[str, Sequence],
        *, tag_columns: Sequence[str] = (),
        timestamp_column: str = GREPTIME_TIMESTAMP,
        types: Optional[Dict[str, ConcreteDataType]] = None,
        ctx: Optional[QueryContext] = None,
    ) -> int:
        """WAL-less bulk ingest (COPY FROM / Flight bulk do_put): same
        auto create/alter as row insert, but routed through the engine's
        direct-to-SST load (MitoTable.bulk_load) when available.
        Durability comes from the SSTs + one manifest edit (reference:
        direct part writes, src/storage/src/region/writer.rs:394-433)."""
        ctx = ctx or QueryContext()
        catalog, schema_name = ctx.current_catalog, ctx.current_schema
        table = self.catalog.table(catalog, schema_name, table_name)
        types = types or {}
        if table is None:
            table = self._create_on_demand(
                catalog, schema_name, table_name, columns, tag_columns,
                timestamp_column, types)
        else:
            self._alter_on_demand(table, catalog, schema_name, table_name,
                                  columns, types, tag_columns)
            table = self.catalog.table(catalog, schema_name, table_name)
        bulk = getattr(table, "bulk_load", None)
        return bulk(columns) if bulk is not None else table.insert(columns)

    def _infer_type(self, name: str, values: Sequence,
                    types: Dict[str, ConcreteDataType],
                    timestamp_column: str) -> ConcreteDataType:
        return infer_ingest_type(name, values, types, timestamp_column)

    def _create_on_demand(self, catalog, schema_name, table_name, columns,
                          tag_columns, timestamp_column, types):
        schema, pk = build_ingest_schema(columns, tag_columns,
                                         timestamp_column, types)
        engine = self.datanode.mito
        table = engine.create_table(CreateTableRequest(
            table_name, schema, catalog_name=catalog,
            schema_name=schema_name, primary_key_indices=pk,
            create_if_not_exists=True))
        from ..errors import TableAlreadyExistsError
        try:
            self.catalog.register_table(catalog, schema_name, table_name,
                                        table)
        except TableAlreadyExistsError:
            # concurrent auto-create race: a sibling protocol request
            # registered first — adopt its table (the engine-level create
            # was already if-not-exists, only the catalog insert raced)
            existing = self.catalog.table(catalog, schema_name, table_name)
            if existing is not None:
                return existing
            raise
        return table

    def _alter_on_demand(self, table, catalog, schema_name, table_name,
                         columns, types, tag_columns=()):
        missing = [name for name in columns
                   if not table.schema.contains(name)]
        if not missing:
            return
        new_tags = [n for n in missing if n in set(tag_columns)]
        if new_tags:
            # a new label cannot be added as a FIELD: distinct series that
            # differ only in it would collapse onto one (row key unchanged)
            # and MVCC dedup would silently drop samples. The series
            # dictionary is immutable post-create (reference v0.2 alter has
            # the same key restriction), so reject the write loudly.
            from ..errors import InvalidArgumentsError
            raise InvalidArgumentsError(
                f"table {table_name!r} has no tag column(s) {new_tags}; "
                f"tags cannot be added after create — write to a new table "
                f"or recreate with the full label set")
        adds = []
        for name in missing:
            dtype = self._infer_type(name, columns[name], types, "")
            adds.append(AddColumnRequest(ColumnSchema(name, dtype)))
        engine = self.datanode.engines[table.info.meta.engine]
        engine.alter_table(AlterTableRequest(
            table_name, AlterKind.ADD_COLUMNS, catalog_name=catalog,
            schema_name=schema_name, add_columns=adds))


def infer_ingest_type(name: str, values: Sequence,
                      types: Dict[str, ConcreteDataType],
                      timestamp_column: str) -> ConcreteDataType:
    """Column type inference for protocol ingest (shared by the
    standalone and distributed auto-create paths)."""
    if name in types:
        return types[name]
    if name == timestamp_column:
        return TIMESTAMP_MILLISECOND
    for v in values:
        if v is None:
            continue
        if isinstance(v, bool):
            from ..datatypes.data_type import BOOLEAN
            return BOOLEAN
        if isinstance(v, int):
            return INT64
        if isinstance(v, float):
            return FLOAT64
        if isinstance(v, str):
            return STRING
    return FLOAT64


def build_ingest_schema(columns, tag_columns, timestamp_column, types):
    """(Schema, pk_indices) for auto-created ingest tables: stable
    tags → timestamp → fields layout (reference column order)."""
    cols = []
    tag_set = set(tag_columns)
    for name, values in columns.items():
        dtype = infer_ingest_type(name, values, types or {},
                                  timestamp_column)
        if name == timestamp_column:
            cols.append(ColumnSchema(name, dtype, nullable=False,
                                     semantic_type=SemanticType.TIMESTAMP))
        elif name in tag_set:
            cols.append(ColumnSchema(name, dtype, nullable=False,
                                     semantic_type=SemanticType.TAG))
        else:
            cols.append(ColumnSchema(name, dtype))
    cols.sort(key=lambda c: {SemanticType.TAG: 0,
                             SemanticType.TIMESTAMP: 1,
                             SemanticType.FIELD: 2}[c.semantic_type])
    schema = Schema(cols)
    pk = [i for i, c in enumerate(cols)
          if c.semantic_type == SemanticType.TAG]
    return schema, pk


def build_standalone(opts=None) -> FrontendInstance:
    """Compose a standalone instance: frontend on an in-process datanode
    (reference: src/cmd/src/standalone.rs:317-350)."""
    from ..datanode import DatanodeOptions
    dn = DatanodeInstance(opts or DatanodeOptions())
    fe = FrontendInstance(dn)
    fe.start()
    return fe
