"""Distributed frontend: DistTable + DistInstance.

Reference behavior: src/frontend — `DistTable` splits inserts per region
and routes them to owning datanodes (table.rs:83-107, splitter.rs:46-80);
`DistInstance` orchestrates distributed DDL: allocate a table id, have the
meta service build the table route (region→peer placement), then fan the
create out to each datanode with its region subset
(instance/distributed.rs:95-204,206-320).

Upgrade over v0.2: the scan path pushes *aggregate moments* down to the
datanodes (client.region_moments — each worker reduces its regions with
the TPU kernel) and the frontend only folds per-run moment frames; the
reference ships only projection/filter/limit scans (table.rs:109-156).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np
import pandas as pd

from .. import DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME
from ..catalog import MemoryCatalogManager
from ..client import DatanodeClient
from ..datatypes.schema import Schema
from ..errors import (
    GreptimeError, InvalidArgumentsError, TableAlreadyExistsError,
    TableNotFoundError, UnsupportedError)
from ..meta import MetaClient, TableRoute
from ..partition import rule_from_partitions, split_rows
from ..query import QueryEngine
from ..session import QueryContext
from ..sql import ast
from ..table.metadata import (
    TableIdent, TableInfo, TableMeta)
from ..table.requests import CreateTableRequest
from ..table.table import Table

logger = logging.getLogger(__name__)


def _serialize_dist_rule(rule):
    from ..mito.engine import _serialize_rule
    return _serialize_rule(rule)


class DistTable(Table):
    """Frontend-side view of a distributed table: route + clients.

    Holds no storage; every data operation fans out to the datanodes that
    own the regions and merges on the way back."""

    def __init__(self, info: TableInfo, rule, route: TableRoute,
                 clients: Dict[int, DatanodeClient]):
        super().__init__(info)
        self.partition_rule = rule
        self.route = route
        self.clients = clients

    # ---- placement helpers ----
    def _owner(self, region_number: int) -> DatanodeClient:
        for rr in self.route.region_routes:
            if rr.region_number == region_number:
                client = self.clients.get(rr.leader.id)
                if client is None:
                    raise GreptimeError(
                        f"no client for datanode {rr.leader.id}")
                return client
        raise GreptimeError(f"region {region_number} not in route")

    def _involved_clients(self) -> List[DatanodeClient]:
        seen = {}
        for rr in self.route.region_routes:
            seen[rr.leader.id] = self.clients[rr.leader.id]
        return list(seen.values())

    @property
    def regions(self):
        """Union of the in-process regions across datanodes (promql +
        metadata endpoints walk these; remote clients would proxy)."""
        out = {}
        for client in self._involved_clients():
            dn_table = client.datanode.catalog.table(
                self.info.catalog_name, self.info.schema_name,
                self.info.name)
            if dn_table is not None:
                out.update(dn_table.regions)
        return out

    # ---- writes ----
    def insert(self, columns: Dict[str, Sequence]) -> int:
        return self._split_write(columns, op="put")

    def bulk_load(self, columns: Dict[str, Sequence]) -> int:
        """Route a WAL-less bulk load to each owning datanode's region
        (mito write_region op="bulk" → Region.bulk_ingest)."""
        return self._split_write(columns, op="bulk")

    def delete(self, key_columns: Dict[str, Sequence]) -> int:
        return self._split_write(key_columns, op="delete")

    def _split_write(self, columns: Dict[str, Sequence], op: str) -> int:
        if not columns:
            return 0
        num_rows = len(next(iter(columns.values())))
        for name, vals in columns.items():
            if len(vals) != num_rows:
                raise InvalidArgumentsError(f"ragged column {name!r}")
        splits = split_rows(self.partition_rule, columns, num_rows) \
            if self.partition_rule is not None else {self._first_region(): None}
        written = 0
        for rnum, idx in splits.items():
            part = columns if idx is None else \
                {k: v[idx] if isinstance(v, np.ndarray)
                 else [v[i] for i in idx] for k, v in columns.items()}
            written += self._owner(rnum).write_region(
                self.info.catalog_name, self.info.schema_name,
                self.info.name, rnum, part, op)
        return written

    def _first_region(self) -> int:
        return self.route.region_routes[0].region_number

    # ---- reads ----
    def scan_batches(self, projection: Optional[Sequence[str]] = None,
                     time_range=None) -> list:
        out = []
        for client in self._involved_clients():
            out.extend(client.scan_batches(
                self.info.catalog_name, self.info.schema_name,
                self.info.name, projection=projection,
                time_range=time_range))
        return out

    def execute_tpu_plan(self, plan) -> List[pd.DataFrame]:
        """Aggregate pushdown: each datanode reduces its regions on device
        and returns moment frames; the caller folds them."""
        frames: List[pd.DataFrame] = []
        for client in self._involved_clients():
            frames.extend(client.region_moments(
                self.info.catalog_name, self.info.schema_name,
                self.info.name, plan))
        return frames

    def flush(self) -> None:
        for client in self._involved_clients():
            client.flush_table(self.info.catalog_name,
                               self.info.schema_name, self.info.name)


class _RouteHydratingCatalog(MemoryCatalogManager):
    """Frontend catalog that falls back to the meta routes on a miss
    (reference: FrontendCatalogManager resolves through the meta KV on
    demand, src/frontend/src/catalog.rs). Hydration happens at table-
    resolution depth, so every statement shape — SELECT, INSERT..SELECT,
    TQL, DESCRIBE — sees remote tables on a fresh frontend."""

    def __init__(self, instance: "DistInstance"):
        super().__init__()
        self._instance = instance
        self._miss_guard = threading.local()

    def table(self, catalog: str, schema: str, name: str):
        t = super().table(catalog, schema, name)
        if t is not None or getattr(self._miss_guard, "busy", False):
            return t
        self._miss_guard.busy = True
        try:
            route = self._instance.meta.route(
                f"{catalog}.{schema}.{name}")
            if route is None:
                return None
            return self._instance._hydrate_table(route, catalog, schema,
                                                 name)
        finally:
            self._miss_guard.busy = False


class DistInstance:
    """Distributed frontend instance (reference DistInstance).

    Wires: meta client (routes/ids/heartbeats) + one DatanodeClient per
    worker + a frontend-local catalog of DistTables + the query engine."""

    def __init__(self, meta: MetaClient,
                 clients: Dict[int, DatanodeClient]):
        self.meta = meta
        self.clients = clients
        self.catalog = _RouteHydratingCatalog(self)
        self.query_engine = QueryEngine(self.catalog)
        # continuous rollup flows: specs live in the meta kv so every
        # frontend (and a restarted one) sees the same flows; folds run
        # through the generic scan-based path over DistTables
        from ..flow import FlowManager, KvFlowStore
        # wire meta clients without kv passthroughs still get in-memory
        # flows; the in-process MetaClient persists specs under __flow/
        store = KvFlowStore(meta) \
            if hasattr(meta, "kv_put") or hasattr(meta, "put") else None
        self.flow_manager = FlowManager(
            self.catalog, store, create_sink_fn=self._create_flow_sink)
        self.flow_manager.recover()
        self.query_engine.flow_manager = self.flow_manager
        self.catalog.flow_manager = self.flow_manager

    def _create_flow_sink(self, spec, schema, pk_indices):
        """Materialize a flow sink as an ordinary distributed table."""
        cols = []
        for cs in schema.column_schemas:
            cols.append(ast.ColumnDef(
                name=cs.name, type_name=cs.dtype.name,
                nullable=cs.nullable,
                is_time_index=cs.is_time_index,
                is_primary_key=cs.is_tag))
        stmt = ast.CreateTable(
            name=ast.ObjectName([spec.catalog, spec.schema, spec.sink]),
            columns=cols,
            time_index=spec.ts_column,
            primary_keys=[c.name for c in schema.column_schemas
                          if c.is_tag],
            if_not_exists=True)
        ctx = QueryContext(spec.catalog, spec.schema)
        return self.create_table(stmt, ctx)

    # ---- DDL ----
    def create_table(self, stmt: ast.CreateTable,
                     ctx: Optional[QueryContext] = None) -> DistTable:
        from .statement import build_schema_from_create
        ctx = ctx or QueryContext()
        catalog, schema_name, table_name = ctx.resolve(stmt.name)
        full = f"{catalog}.{schema_name}.{table_name}"
        if self.catalog.table(catalog, schema_name, table_name) \
                is not None:
            if stmt.if_not_exists:
                return self.catalog.table(catalog, schema_name, table_name)
            raise TableAlreadyExistsError(f"table {full} already exists")

        existing_route = self.meta.route(full)
        if existing_route is not None:
            # frontend restart / second frontend: reattach to the live
            # table instead of failing an idempotent statement
            table = self._hydrate_table(existing_route, catalog,
                                        schema_name, table_name)
            if stmt.if_not_exists and table is not None:
                return table
            raise TableAlreadyExistsError(f"table {full} already exists")

        schema, pk_indices = build_schema_from_create(stmt)
        rule = rule_from_partitions(stmt.partitions) \
            if stmt.partitions is not None else None
        region_numbers = rule.region_numbers() if rule is not None else [0]

        # 1. meta: allocate id + place regions on alive datanodes
        route = self.meta.create_route(full, region_numbers)
        try:
            # 2. fan out: each datanode creates its region subset
            for peer in route.peers():
                client = self.clients.get(peer.id)
                if client is None:
                    raise GreptimeError(f"no client for datanode {peer.id}")
                client.ddl_create_table(CreateTableRequest(
                    table_name, schema,
                    catalog_name=catalog, schema_name=schema_name,
                    primary_key_indices=pk_indices,
                    create_if_not_exists=True,
                    table_options=dict(stmt.options or {}),
                    partitions=stmt.partitions,
                    table_id=route.table_id,
                    assigned_region_numbers=route.regions_on(peer.id)))
        except Exception:
            # roll back: route + any datanode that already created its part
            self.meta.delete_route(full)
            for peer in route.peers():
                client = self.clients.get(peer.id)
                if client is None:
                    continue
                try:
                    client.ddl_drop_table(catalog, schema_name, table_name)
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "rollback drop on datanode %d failed", peer.id)
            raise

        info = TableInfo(
            ident=TableIdent(route.table_id),
            name=table_name,
            meta=TableMeta(schema=schema,
                           primary_key_indices=pk_indices,
                           engine="mito",
                           region_numbers=list(region_numbers),
                           next_column_id=len(schema),
                           options=dict(stmt.options or {}),
                           partition_rule=_serialize_dist_rule(rule)),
            catalog_name=catalog, schema_name=schema_name)
        # schema travels with the route (TableGlobalValue) so failover
        # can materialize regions on datanodes that never saw the DDL
        if hasattr(self.meta, "put_table_info"):
            self.meta.put_table_info(full, info.to_dict())
        table = DistTable(info, rule, route, self.clients)
        self.catalog.register_table(catalog, schema_name, table_name, table)
        return table

    def drop_table(self, stmt: ast.DropTable,
                   ctx: Optional[QueryContext] = None) -> bool:
        ctx = ctx or QueryContext()
        catalog, schema_name, name = ctx.resolve(stmt.name)
        table = self._resolve_table(catalog, schema_name, name)
        if table is None:
            if stmt.if_exists:
                return False
            raise TableNotFoundError(f"table {name} not found")
        for client in table._involved_clients():
            client.ddl_drop_table(catalog, schema_name, name)
        self.meta.delete_route(f"{catalog}.{schema_name}.{name}")
        if hasattr(self.meta, "delete_table_info"):
            self.meta.delete_table_info(f"{catalog}.{schema_name}.{name}")
        self.catalog.deregister_table(catalog, schema_name, name)
        return True

    def _resolve_table(self, catalog: str, schema_name: str, name: str):
        """Local catalog first, then rebuild a DistTable from the meta
        route (frontend restart path)."""
        table = self.catalog.table(catalog, schema_name, name)
        if table is not None:
            return table
        route = self.meta.route(f"{catalog}.{schema_name}.{name}")
        if route is None:
            return None
        return self._hydrate_table(route, catalog, schema_name, name)

    def _hydrate_table(self, route: TableRoute, catalog: str,
                       schema_name: str, name: str) -> Optional[DistTable]:
        """Rebuild the frontend-side DistTable from the route + a hosting
        datanode's local table metadata."""
        for peer in route.peers():
            client = self.clients.get(peer.id)
            if client is None:
                continue
            described = client.describe_table(catalog, schema_name, name)
            if described is None:
                continue
            info, rule = described
            region_numbers = sorted(
                rr.region_number for rr in route.region_routes)
            info = TableInfo(
                ident=TableIdent(route.table_id), name=name,
                meta=TableMeta(
                    schema=info.meta.schema,
                    primary_key_indices=list(
                        info.meta.primary_key_indices),
                    engine=info.meta.engine,
                    region_numbers=region_numbers,
                    next_column_id=info.meta.next_column_id,
                    options=dict(info.meta.options)),
                catalog_name=catalog, schema_name=schema_name)
            table = DistTable(info, rule, route, self.clients)
            self.catalog.register_table(catalog, schema_name, name, table)
            return table
        return None

    # ---- protocol ingest: auto create / alter on demand ----
    def handle_bulk_load(
        self, table_name: str, columns: Dict[str, Sequence],
        *, tag_columns: Sequence[str] = (),
        timestamp_column: str = "greptime_timestamp",
        types=None, ctx: Optional[QueryContext] = None,
    ) -> int:
        """Distributed bulk load: same auto create/alter as row insert,
        but each datanode ingests its partition WAL-less
        (DistTable.bulk_load → write_region op="bulk")."""
        return self.handle_row_insert(
            table_name, columns, tag_columns=tag_columns,
            timestamp_column=timestamp_column, types=types, ctx=ctx,
            _bulk=True)

    def handle_row_insert(
        self, table_name: str, columns: Dict[str, Sequence],
        *, tag_columns: Sequence[str] = (),
        timestamp_column: str = "greptime_timestamp",
        types=None, ctx: Optional[QueryContext] = None,
        _bulk: bool = False,
    ) -> int:
        """Distributed twin of the standalone auto-create/alter ingest
        (reference: DistInstance implements the same handler traits,
        src/frontend/src/instance.rs:83-97). Auto-created tables get one
        region placed by the meta selector; missing field columns fan
        an ALTER out to every owning datanode."""
        from .instance import build_ingest_schema, infer_ingest_type
        ctx = ctx or QueryContext()
        catalog, schema_name = ctx.current_catalog, ctx.current_schema
        table = self._resolve_table(catalog, schema_name, table_name)
        if table is None:
            schema, pk = build_ingest_schema(columns, tag_columns,
                                             timestamp_column, types)
            full = f"{catalog}.{schema_name}.{table_name}"
            route = self.meta.create_route(full, [0])
            for peer in route.peers():
                self.clients[peer.id].ddl_create_table(CreateTableRequest(
                    table_name, schema, catalog_name=catalog,
                    schema_name=schema_name, primary_key_indices=pk,
                    create_if_not_exists=True, table_id=route.table_id,
                    assigned_region_numbers=route.regions_on(peer.id)))
            info = TableInfo(
                ident=TableIdent(route.table_id), name=table_name,
                meta=TableMeta(schema=schema, primary_key_indices=pk,
                               engine="mito", region_numbers=[0],
                               next_column_id=len(schema)),
                catalog_name=catalog, schema_name=schema_name)
            table = DistTable(info, None, route, self.clients)
            self.catalog.register_table(catalog, schema_name, table_name,
                                        table)
        else:
            missing = [n for n in columns
                       if not table.schema.contains(n)]
            new_tags = [n for n in missing if n in set(tag_columns)]
            if new_tags:
                raise InvalidArgumentsError(
                    f"table {table_name!r} has no tag column(s) "
                    f"{new_tags}; tags cannot be added after create")
            if missing:
                from ..datatypes.schema import ColumnSchema
                from ..table.requests import (
                    AddColumnRequest, AlterKind, AlterTableRequest)
                adds = [AddColumnRequest(ColumnSchema(
                    n, infer_ingest_type(n, columns[n], types or {}, "")))
                    for n in missing]
                req = AlterTableRequest(
                    table_name, AlterKind.ADD_COLUMNS,
                    catalog_name=catalog, schema_name=schema_name,
                    add_columns=adds)
                for client in table._involved_clients():
                    client.ddl_alter_table(req)
                # refresh the frontend view from a datanode's new schema
                self.catalog.deregister_table(catalog, schema_name,
                                              table_name)
                table = self._resolve_table(catalog, schema_name,
                                            table_name)
        return table.bulk_load(columns) if _bulk else table.insert(columns)

    def alter_table(self, stmt: ast.AlterTable, ctx: QueryContext):
        """Distributed ALTER: fan the engine request out to every owning
        datanode, then refresh the frontend view (and, for RENAME, move
        the meta route so the table resolves under its new name).
        Reference: dist DDL via meta procedures,
        src/frontend/src/instance/distributed.rs + alter flow in
        src/table/src/metadata.rs:249-297."""
        from ..query.output import Output
        from ..table.requests import (
            AddColumnRequest, AlterKind, AlterTableRequest)
        from .statement import build_column_schema
        catalog, schema_name, table_name = ctx.resolve(stmt.table)
        table = self._resolve_table(catalog, schema_name, table_name)
        if table is None:
            raise TableNotFoundError(f"table {table_name!r} not found")
        op = stmt.operation
        if isinstance(op, ast.AddColumn):
            cs = build_column_schema(op.column, is_tag=False,
                                     is_time_index=False)
            req = AlterTableRequest(
                table_name, AlterKind.ADD_COLUMNS, catalog_name=catalog,
                schema_name=schema_name,
                add_columns=[AddColumnRequest(cs, location=op.location)])
        elif isinstance(op, ast.DropColumn):
            req = AlterTableRequest(
                table_name, AlterKind.DROP_COLUMNS, catalog_name=catalog,
                schema_name=schema_name, drop_columns=[op.name])
        elif isinstance(op, ast.RenameTable):
            req = AlterTableRequest(
                table_name, AlterKind.RENAME_TABLE, catalog_name=catalog,
                schema_name=schema_name, new_table_name=op.new_name)
        else:
            raise UnsupportedError(f"ALTER operation {type(op).__name__}")
        for client in table._involved_clients():
            client.ddl_alter_table(req)
        self.catalog.deregister_table(catalog, schema_name, table_name)
        if isinstance(op, ast.RenameTable):
            self.meta.rename_route(
                f"{catalog}.{schema_name}.{table_name}",
                f"{catalog}.{schema_name}.{op.new_name}")
            self._resolve_table(catalog, schema_name, op.new_name)
        else:
            self._resolve_table(catalog, schema_name, table_name)
        return Output.rows(0)

    # ---- SQL ----
    def do_query(self, sql: str, ctx: Optional[QueryContext] = None):
        import time as _time

        from ..common.telemetry import (
            increment_counter, slow_query_threshold_ms, span, timer)
        from ..sql import parse_statements
        ctx = ctx or QueryContext()
        outs = []
        for stmt in parse_statements(sql):
            t0 = _time.perf_counter()
            prev_stats = getattr(self.query_engine, "last_exec_stats",
                                 None)
            with span("execute_stmt", stmt=type(stmt).__name__,
                      distributed=True) as sp, timer("stmt_execute"):
                outs.append(self.execute_stmt(stmt, ctx))
            increment_counter(f"stmt_{type(stmt).__name__.lower()}")
            elapsed_ms = (_time.perf_counter() - t0) * 1e3
            thr = slow_query_threshold_ms()
            if thr is not None and elapsed_ms >= thr:
                stats = getattr(self.query_engine, "last_exec_stats",
                                None)
                if stats is prev_stats:     # not this statement's stats
                    stats = None
                import logging
                logging.getLogger("greptimedb_tpu.slow_query").warning(
                    "slow query: %.1fms (threshold %dms) trace=%s "
                    "stmt=%r stats=[%s]", elapsed_ms, thr,
                    sp["trace_id"], sql,
                    stats.summary() if stats is not None else "n/a")
        return outs

    def execute_stmt(self, stmt, ctx: QueryContext):
        from ..query.output import Output
        if isinstance(stmt, ast.CreateTable):
            self.create_table(stmt, ctx)
            return Output.rows(0)
        if isinstance(stmt, ast.DropTable):
            self.drop_table(stmt, ctx)
            return Output.rows(0)
        if isinstance(stmt, ast.AlterTable):
            return self.alter_table(stmt, ctx)
        if isinstance(stmt, ast.Insert):
            return self._insert(stmt, ctx)
        if isinstance(stmt, ast.Delete):
            return self._delete(stmt, ctx)
        if isinstance(stmt, ast.CreateFlow):
            self.flow_manager.create_flow(stmt, ctx)
            return Output.rows(0)
        if isinstance(stmt, ast.DropFlow):
            self.flow_manager.drop_flow(stmt.name, ctx,
                                        if_exists=stmt.if_exists)
            return Output.rows(0)
        if isinstance(stmt, ast.ShowFlows):
            from .statement import show_flows_output
            return show_flows_output(self.flow_manager, stmt, ctx)
        return self.query_engine.execute(stmt, ctx)

    def _insert(self, stmt: ast.Insert, ctx: QueryContext):
        from ..query.output import Output
        from .statement import evaluate_insert_rows
        catalog, schema_name, table_name = ctx.resolve(stmt.table)
        table = self._resolve_table(catalog, schema_name, table_name)
        if table is None:
            raise TableNotFoundError(f"table {table_name} not found")
        schema = table.schema
        columns = stmt.columns or schema.names()
        for c in columns:
            if not schema.contains(c):
                from ..errors import ColumnNotFoundError
                raise ColumnNotFoundError(
                    f"column {c!r} not found in {table_name!r}")
        cols = evaluate_insert_rows(stmt, columns, self.query_engine, ctx)
        return Output.rows(table.insert(cols))

    def _delete(self, stmt: ast.Delete, ctx: QueryContext):
        from .statement import delete_matching_rows
        catalog, schema_name, table_name = ctx.resolve(stmt.table)
        table = self.catalog.table(catalog, schema_name, table_name)
        if table is None:
            raise TableNotFoundError(f"table {table_name} not found")
        return delete_matching_rows(table, stmt)
