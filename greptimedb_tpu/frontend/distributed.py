"""Distributed frontend: DistTable + DistInstance.

Reference behavior: src/frontend — `DistTable` splits inserts per region
and routes them to owning datanodes (table.rs:83-107, splitter.rs:46-80);
`DistInstance` orchestrates distributed DDL: allocate a table id, have the
meta service build the table route (region→peer placement), then fan the
create out to each datanode with its region subset
(instance/distributed.rs:95-204,206-320).

Upgrade over v0.2: the scan path pushes *aggregate moments* down to the
datanodes (client.region_moments — each worker reduces its regions with
the TPU kernel) and the frontend only folds per-run moment frames; the
reference ships only projection/filter/limit scans (table.rs:109-156).

The data plane is a PARALLEL, PRUNED scatter-gather executor:

- prune before fan-out — the query's tag/time predicates select regions
  through `partition_rule.find_regions_by_filters` (reference:
  src/partition/src/manager.rs:192), and only owning datanodes are
  contacted, with the surviving region list shipped over the wire so a
  datanode does not scan its un-pruned sibling regions either;
- concurrent fan-out with pipelined gather — per-datanode RPCs scatter
  through the shared `common/runtime` dist pool (bounded per statement
  by ``SET dist_fanout``) and results fold as they arrive instead of
  barriering on the slowest node; `_split_write` overlaps per-region
  WAL+memtable work the same way;
- robust + observable — each RPC retries transient faults (PR 4's
  classification; the ``dist_rpc`` failpoint injects them,
  greptime_dist_rpc_retry_total counts them) and ExecStats reports
  ``regions pruned a/b, fan-out=k, slowest_node_ms`` per statement.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from .. import DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME
from ..catalog import MemoryCatalogManager
from ..client import DatanodeClient
from ..common import exec_stats
from ..common.failpoint import register as _fp_register
from ..common.runtime import env_int
from ..datatypes.schema import Schema
from ..errors import (
    GreptimeError, InvalidArgumentsError, RegionClosedError,
    StaleRouteError, TableAlreadyExistsError, TableNotFoundError,
    UnsupportedError)
from ..meta import MetaClient, TableRoute
from ..partition import rule_from_partitions, split_rows
from ..query import QueryEngine
from ..session import QueryContext
from ..sql import ast
from ..table.metadata import (
    TableIdent, TableInfo, TableMeta)
from ..table.requests import CreateTableRequest
from ..table.table import Table

logger = logging.getLogger(__name__)

_fp_register("dist_rpc")


def _serialize_dist_rule(rule):
    from ..mito.engine import _serialize_rule
    return _serialize_rule(rule)






#: stale-route retries: attempts AFTER the first try for a statement
#: whose route moved mid-flight (migrate/split) or whose target region
#: is fenced for an in-flight handoff. Backoff doubles from
#: _STALE_ROUTE_BASE_MS so the retries ride over the bounded fence
#: window instead of failing into the client.
_STALE_ROUTE_MAX_RETRIES = [env_int("GREPTIME_STALE_ROUTE_MAX_RETRIES", 6)]
_STALE_ROUTE_BASE_MS = [env_int("GREPTIME_STALE_ROUTE_BASE_MS", 50)]
_STALE_ROUTE_MAX_BACKOFF_MS = 2000

#: attempts AFTER the first try for one datanode RPC (0 disables retry)
_DIST_RPC_MAX_RETRIES = [env_int("GREPTIME_DIST_RPC_MAX_RETRIES", 2)]
#: first backoff; doubles per attempt, capped, ±50% jitter
_DIST_RPC_BASE_MS = [env_int("GREPTIME_DIST_RPC_RETRY_BASE_MS", 25)]
_DIST_RPC_MAX_BACKOFF_MS = 1000


def configure_dist_rpc_retry(*, max_retries: Optional[int] = None,
                             base_ms: Optional[int] = None) -> None:
    """SET dist_rpc_max_retries / dist_rpc_retry_base_ms."""
    if max_retries is not None:
        _DIST_RPC_MAX_RETRIES[0] = max(0, int(max_retries))
    if base_ms is not None:
        _DIST_RPC_BASE_MS[0] = max(1, int(base_ms))


#: replica-aware read routing (PR 19): "leader" scatters reads to region
#: leaders only; "follower" lets reads land on read replicas whose
#: replication lag is inside the bounded-staleness budget below,
#: balancing by per-node assignment count. SET read_replica /
#: SET replica_max_lag_ms flip these at runtime; GREPTIME_* twins seed.
_READ_REPLICA = [os.environ.get("GREPTIME_READ_REPLICA",
                                "leader").strip().lower() or "leader"]
_REPLICA_MAX_LAG_MS = [env_int("GREPTIME_REPLICA_MAX_LAG_MS", 5000)]


def configure_read_replica(mode: Optional[str] = None,
                           max_lag_ms: Optional[int] = None) -> None:
    """SET read_replica = leader|follower / SET replica_max_lag_ms."""
    if mode is not None:
        mode = str(mode).strip().lower()
        if mode not in ("leader", "follower"):
            raise InvalidArgumentsError(
                f"read_replica: expected 'leader' or 'follower', "
                f"got {mode!r}")
        _READ_REPLICA[0] = mode
    if max_lag_ms is not None:
        try:
            _REPLICA_MAX_LAG_MS[0] = max(0, int(float(max_lag_ms)))
        except (TypeError, ValueError):
            raise InvalidArgumentsError(
                f"replica_max_lag_ms: expected a number, got "
                f"{max_lag_ms!r}")


def _dist_rpc(what: str, call):
    """Run one datanode RPC with transient-fault retry (PR 4's
    classification — storage/retry.is_transient): exponential backoff +
    jitter, greptime_dist_rpc_retry{,_giveup}_total counters. The
    `dist_rpc` failpoint fires inside the loop, so an injected
    err(transient) exercises the real retry path."""
    from ..common.failpoint import fail_point
    from ..common.telemetry import increment_counter
    from ..storage.retry import is_transient
    attempt = 0
    while True:
        try:
            fail_point("dist_rpc")
            return call()
        except Exception as e:  # noqa: BLE001 — classified below
            if not is_transient(e) or attempt >= _DIST_RPC_MAX_RETRIES[0]:
                if attempt:
                    increment_counter("dist_rpc_retry_giveup")
                raise
            attempt += 1
            increment_counter("dist_rpc_retry")
            delay_ms = min(_DIST_RPC_BASE_MS[0] * (2 ** (attempt - 1)),
                           _DIST_RPC_MAX_BACKOFF_MS)
            delay_s = delay_ms / 1e3 * (0.5 + random.random())
            logger.warning(
                "dist rpc %s failed transiently (%s); retry %d/%d in "
                "%.0fms", what, e, attempt, _DIST_RPC_MAX_RETRIES[0],
                delay_s * 1e3)
            time.sleep(delay_s)


class DistTable(Table):
    """Frontend-side view of a distributed table: route + clients.

    Holds no storage; every data operation prunes the region set by the
    statement's predicates, scatters bounded-parallel RPCs to the owning
    datanodes, and folds results as they arrive."""

    #: query/engine.py threads WHERE conjuncts + LIMIT into scan_batches
    #: for tables that advertise this
    supports_filter_pushdown = True

    def __init__(self, info: TableInfo, rule, route: TableRoute,
                 clients: Dict[int, DatanodeClient], meta=None):
        super().__init__(info)
        self.partition_rule = rule
        self.route = route
        self.clients = clients
        #: meta client for the stale-route refresh (regions move under
        #: live tables: migrate/split/failover); None degrades to no
        #: refresh — the StaleRouteError surfaces after the retries
        self.meta = meta
        self._warned_remote_regions = False
        #: per-node wall latency of the most recent scatter on this
        #: frontend ({label: ms}; bench.py's scatter profile reads it)
        self.last_scatter_node_ms: Dict[str, float] = {}

    # ---- stale-route refresh (elastic regions) ----
    def refresh_route(self) -> bool:
        """Re-pull the route AND the partition rule from meta: a migrate
        changes placement, a split changes the rule + the region set.
        Returns whether anything was actually refreshed."""
        if self.meta is None:
            return False
        full = (f"{self.info.catalog_name}.{self.info.schema_name}."
                f"{self.info.name}")
        try:
            route = self.meta.route(full)
        except Exception:  # noqa: BLE001 — refresh is best-effort; the
            logger.exception(       # caller's retry loop handles failure
                "stale-route refresh of %s failed", full)
            return False
        if route is None:
            return False
        self.route = route
        info_doc = self.meta.table_info(full) \
            if hasattr(self.meta, "table_info") else None
        if info_doc:
            meta_doc = info_doc.get("meta", {})
            from ..mito.engine import _deserialize_rule
            self.partition_rule = _deserialize_rule(
                meta_doc.get("partition_rule"))
            self.info.meta.partition_rule = meta_doc.get("partition_rule")
            self.info.meta.region_numbers = sorted(
                rr.region_number for rr in route.region_routes)
        from ..common.telemetry import increment_counter
        increment_counter("stale_route_refresh")
        logger.info("refreshed route of %s to v%d (%d regions)", full,
                    route.version, len(route.region_routes))
        return True

    def _retry_stale(self, what: str, call):
        """Run a whole-table operation, refreshing the route and retrying
        on StaleRouteError — regions move under live statements
        (migrate/split commit) or sit briefly fenced mid-handoff; the
        backoff rides over the bounded fence window."""
        from ..storage.retry import is_transient
        attempt = 0
        while True:
            try:
                return call()
            except GreptimeError as e:
                # retryable shapes: an explicit stale route; a datanode
                # whose LAST region of the table left (TableNotFound over
                # the wire); a peer the per-RPC retry gave up on that may
                # simply be DEAD (failover re-places its regions, so a
                # refresh covers the detection window). Everything else
                # propagates untouched.
                retryable = isinstance(
                    e, (StaleRouteError, TableNotFoundError,
                        RegionClosedError)) or \
                    is_transient(e)
                if not retryable or \
                        attempt >= _STALE_ROUTE_MAX_RETRIES[0]:
                    raise
                attempt += 1
                delay_ms = min(
                    _STALE_ROUTE_BASE_MS[0] * (2 ** (attempt - 1)),
                    _STALE_ROUTE_MAX_BACKOFF_MS)
                logger.info(
                    "%s of %s hit a stale route (%s); refresh + retry "
                    "%d/%d in %dms", what, self.info.name, e, attempt,
                    _STALE_ROUTE_MAX_RETRIES[0], delay_ms)
                time.sleep(delay_ms / 1e3 * (0.5 + random.random()))
                if not self.refresh_route() and \
                        isinstance(e, TableNotFoundError):
                    raise                  # the table is genuinely gone

    # ---- placement helpers ----
    def _owner(self, region_number: int) -> DatanodeClient:
        for rr in self.route.region_routes:
            if rr.region_number == region_number:
                client = self.clients.get(rr.leader.id)
                if client is None:
                    raise GreptimeError(
                        f"no client for datanode {rr.leader.id}")
                return client
        raise GreptimeError(f"region {region_number} not in route")

    def _involved_clients(self) -> List[DatanodeClient]:
        seen = {}
        for rr in self.route.region_routes:
            seen[rr.leader.id] = self.clients[rr.leader.id]
        return list(seen.values())

    @property
    def regions(self):
        """Union of the in-process regions across datanodes (promql +
        the local frame/scan caches walk these). A remote flight client
        has no in-process datanode to reach into — and a PARTIAL union
        would be served as the whole table by cached_table_frame, so any
        remote client degrades the view to EMPTY with one WARN; callers
        then fall back to the wire scan path."""
        out = {}
        for client in self._involved_clients():
            datanode = getattr(client, "datanode", None)
            if datanode is None:
                if not self._warned_remote_regions:
                    self._warned_remote_regions = True
                    logger.warning(
                        "DistTable %s.regions: datanode %s is remote; "
                        "in-process region metadata is unavailable — "
                        "returning no regions (reads go over the wire)",
                        self.info.name,
                        getattr(client, "node_id", "?"))
                return {}
            dn_table = datanode.catalog.table(
                self.info.catalog_name, self.info.schema_name,
                self.info.name)
            if dn_table is not None:
                # skip standby replicas: the leader's copy of the same
                # region number is the authoritative one for the union
                out.update({rn: reg for rn, reg
                            in dn_table.regions.items()
                            if not getattr(reg, "standby", False)})
        return out

    # ---- pruning ----
    def _all_region_numbers(self) -> List[int]:
        return sorted(rr.region_number for rr in self.route.region_routes)

    def _prune_regions(self, filters=None, time_lo=None, time_hi=None,
                       time_range=None) -> Tuple[List[int], int]:
        """(surviving region numbers, total routed regions) for the
        statement's predicates. Pruning is advisory: any failure falls
        back to the full region set — it must never fail a query."""
        all_regions = self._all_region_numbers()
        rule = self.partition_rule
        if rule is None:
            return all_regions, len(all_regions)
        preds = list(filters or ())
        tc = self.schema.timestamp_column
        if tc is not None:
            los = [time_lo]
            his = [time_hi]
            if time_range is not None:
                if hasattr(time_range, "start"):
                    los.append(time_range.start)
                    his.append(time_range.end)
                else:
                    lo, hi = time_range
                    los.append(lo)
                    his.append(hi)
            los = [v for v in los if v is not None]
            his = [v for v in his if v is not None]
            # time-range overlap joins the rule's predicate pruning when
            # the table partitions on its time index ([lo, hi) half-open)
            if los:
                preds.append(ast.BinaryOp(">=", ast.Column(tc.name),
                                          ast.Literal(int(max(los)))))
            if his:
                preds.append(ast.BinaryOp("<", ast.Column(tc.name),
                                          ast.Literal(int(min(his)))))
        try:
            survivors = rule.find_regions_by_filters(preds)
        except Exception:  # noqa: BLE001 — pruning is an optimization
            logger.exception("partition pruning failed; contacting all "
                             "regions of %s", self.info.name)
            survivors = rule.region_numbers()
        routed = set(all_regions)
        return [r for r in survivors if r in routed], len(all_regions)

    def _owners_for(self, region_numbers: Sequence[int]
                    ) -> List[Tuple[DatanodeClient, List[int]]]:
        """Surviving regions grouped by owning datanode, in stable
        datanode-id order — one scatter target per datanode."""
        wanted = set(region_numbers)
        by_node: Dict[int, List[int]] = {}
        for rr in self.route.region_routes:
            if rr.region_number in wanted:
                by_node.setdefault(rr.leader.id, []).append(
                    rr.region_number)
        out = []
        for node_id in sorted(by_node):
            client = self.clients.get(node_id)
            if client is None:
                raise GreptimeError(f"no client for datanode {node_id}")
            out.append((client, sorted(by_node[node_id])))
        return out

    #: region_peers cache TTL for replica routing: one meta read serves
    #: a read burst; lag only moves at heartbeat cadence anyway
    _REPLICA_TTL_S = 5.0

    def _replica_candidates(self) -> Dict[int, List[int]]:
        """{region_number: [alive follower node ids inside the lag
        bound]} from meta's region_peers, TTL-cached per route version.
        Empty on any failure — replica routing is an optimization and
        must never fail a read (it degrades to the leader)."""
        if self.meta is None or not hasattr(self.meta, "region_peers"):
            return {}
        now = time.monotonic()
        cache = getattr(self, "_replica_cache", None)
        if cache is not None and cache[0] > now and \
                cache[1] == self.route.version:
            return cache[2]
        max_lag = _REPLICA_MAX_LAG_MS[0]
        full = (f"{self.info.catalog_name}.{self.info.schema_name}."
                f"{self.info.name}")
        out: Dict[int, List[int]] = {}
        try:
            for row in self.meta.region_peers():
                if row.get("table_name") != full or \
                        row.get("is_leader") == "Yes" or \
                        row.get("status") != "ALIVE":
                    continue
                lag = row.get("lag_ms")
                if lag is None or lag > max_lag:
                    continue
                out.setdefault(int(row["region_number"]), []).append(
                    int(row["peer_id"]))
        except Exception:  # noqa: BLE001 — degrade to leader reads
            logger.exception("replica candidate lookup for %s failed; "
                             "reads stay on leaders", full)
            out = {}
        self._replica_cache = (now + self._REPLICA_TTL_S,
                               self.route.version, out)
        return out

    def _read_owners_for(self, region_numbers: Sequence[int]
                         ) -> List[Tuple[DatanodeClient, List[int]]]:
        """Scatter targets for a READ. Leader-only unless SET
        read_replica = 'follower': then each region picks the least-
        assigned node among its leader and lag-bounded followers
        (cost-based: per-node load with the replicated_seq lag gate),
        spreading a hot table's read QPS across its replicas. Writes
        always use _owners_for — only the leader may ack."""
        if _READ_REPLICA[0] != "follower":
            return self._owners_for(region_numbers)
        candidates = self._replica_candidates()
        if not candidates:
            return self._owners_for(region_numbers)
        wanted = set(region_numbers)
        count: Dict[int, int] = {}
        assigned: Dict[int, List[int]] = {}
        # rotating start keeps successive queries spreading over the
        # pool (a single-region table would otherwise pin every read to
        # the tie-winning leader and replicas would never take traffic)
        rot = self._read_rr = getattr(self, "_read_rr", 0) + 1
        for rr in sorted(self.route.region_routes,
                         key=lambda r: r.region_number):
            if rr.region_number not in wanted:
                continue
            pool = [rr.leader.id] + [
                n for n in candidates.get(rr.region_number, ())
                if n in self.clients]
            pool = pool[rot % len(pool):] + pool[:rot % len(pool)]
            # least-assigned within this scatter; min() keeps the first
            # (rotated) entry on ties
            pick = min(pool, key=lambda n: count.get(n, 0))
            count[pick] = count.get(pick, 0) + 1
            assigned.setdefault(pick, []).append(rr.region_number)
        out = []
        for node_id in sorted(assigned):
            client = self.clients.get(node_id)
            if client is None:
                raise GreptimeError(f"no client for datanode {node_id}")
            out.append((client, sorted(assigned[node_id])))
        return out

    # ---- scatter-gather core ----
    def _scatter(self, targets, call, what: str, node_ms=None):
        """Yield (result, elapsed_ms) per datanode target, in submit
        order as results complete (pipelined gather on the shared dist
        pool, in-flight window = SET dist_fanout). Each RPC retries
        transient faults via _dist_rpc.

        Observability: each RPC runs under its OWN ExecStats
        sub-collector — datanode-side stages (recorded in-process by
        LocalDatanodeClient, or absorbed from the wire response by
        FlightDatanodeClient) land there instead of flat on the
        statement. The sub-collector attaches to the statement's
        collector as a per-node block for the EXPLAIN ANALYZE tree on
        the CONSUMER side of the gather, so a straggler RPC finishing
        after the caller abandoned the gather (limit break) records
        nothing — node blocks are exactly the results the statement
        consumed, deterministically. The per-hop wall time feeds the
        dist_rpc latency histogram, and `node_ms` (when a list is
        passed) collects the per-node latency vector the
        scatter_describe line used to discard."""
        from ..common import runtime
        from ..common.telemetry import observe_latency
        parent = exec_stats.current()

        def one(target):
            client, regs = target
            label = f"dn{getattr(client, 'node_id', '?')}"
            holder = {"stats": None, "t0": 0.0}

            def attempt():
                # fresh sub-collector per attempt: a transient failure
                # mid-scan must not leave its half-recorded stages to
                # double-count under the retry (the per-node rows would
                # stop summing to the standalone differential). The
                # clock restarts per attempt too — a retried RPC's
                # failed attempt + backoff sleep is NOT network time,
                # and the node-vs-network split exists to be trusted
                holder["t0"] = time.perf_counter()
                ns = exec_stats.ExecStats() if parent is not None \
                    else None
                holder["stats"] = ns
                # per-hop span: in the stored trace waterfall its
                # self-time (RPC wall minus the datanode-side span) IS
                # the network share — the node_ms/network_ms split,
                # reconstructible after the fact
                from ..common.telemetry import span as _span
                with exec_stats.collect_into(ns), \
                        _span("dist_rpc", peer=label, what=what):
                    return call(client, regs)

            res = _dist_rpc(f"{what}[{label}]", attempt)
            wall_ms = (time.perf_counter() - holder["t0"]) * 1e3
            observe_latency("dist_rpc_hop", wall_ms / 1e3, what=what)
            return res, wall_ms, label, holder["stats"]

        from ..common import process_list
        for res, wall_ms, label, stats in runtime.parallel_imap(
                one, targets, max_workers=runtime.dist_fanout(),
                pool=runtime.dist_runtime()):
            # cooperative KILL at the gather boundary: raising here
            # closes the bounded gather, whose finally cancels every
            # queued RPC — a killed fan-out frees its dist-pool slots
            # instead of orphaning futures
            process_list.check_cancelled()
            if parent is not None and stats is not None:
                parent.record_node(label, stats, wall_ms)
                parent.record("dist_scatter", rpcs=1)
            if node_ms is not None:
                node_ms.append((label, wall_ms))
            yield res, wall_ms

    def _record_scatter(self, survivors: int, total: int, fan_out: int
                        ) -> None:
        exec_stats.record(
            "dist_scatter",
            scatter=f"regions pruned {total - survivors}/{total}, "
                    f"fan-out={fan_out}")

    # ---- writes ----
    def insert(self, columns: Dict[str, Sequence]) -> int:
        return self._split_write(columns, op="put")

    def bulk_load(self, columns: Dict[str, Sequence]) -> int:
        """Route a WAL-less bulk load to each owning datanode's region
        (mito write_region op="bulk" → Region.bulk_ingest)."""
        return self._split_write(columns, op="bulk")

    def delete(self, key_columns: Dict[str, Sequence]) -> int:
        return self._split_write(key_columns, op="delete")

    def _split_write(self, columns: Dict[str, Sequence], op: str) -> int:
        if not columns:
            return 0
        num_rows = len(next(iter(columns.values())))
        for name, vals in columns.items():
            if len(vals) != num_rows:
                raise InvalidArgumentsError(f"ragged column {name!r}")
        splits = split_rows(self.partition_rule, columns, num_rows) \
            if self.partition_rule is not None else {self._first_region(): None}
        tasks = []
        for rnum, idx in splits.items():
            part = columns if idx is None else \
                {k: v[idx] if isinstance(v, np.ndarray)
                 else [v[i] for i in idx] for k, v in columns.items()}
            tasks.append((rnum, part))

        def write_one(task):
            rnum, part = task
            try:
                return _dist_rpc(
                    f"write_region[{rnum}]",
                    lambda: self._owner(rnum).write_region(
                        self.info.catalog_name, self.info.schema_name,
                        self.info.name, rnum, part, op))
            except GreptimeError as e:
                # also covers _owner()'s "region not in route" against a
                # refreshed-but-shrunk route; only stale-route shapes
                # re-route — everything else propagates. A CLOSED region
                # is one: the node died or released it (failover moves
                # the lease, so the refreshed route points elsewhere)
                if not isinstance(e, (StaleRouteError,
                                      RegionClosedError)) and \
                        "not in route" not in str(e):
                    raise
                # the region moved (migrate) or was refined away (split)
                # mid-statement: re-split ONLY this part under the fresh
                # rule — completed sibling parts must not double-count
                return self._rewrite_stale_part(part, op)

        # per-REGION scatter: a multi-region insert/bulk load overlaps
        # WAL+memtable (or SST encode) work across datanodes instead of
        # paying the sum of its splits
        from ..common import runtime
        written = sum(runtime.parallel_map(
            write_one, tasks, max_workers=runtime.dist_fanout(),
            pool=runtime.dist_runtime()))
        if len(tasks) > 1:
            exec_stats.record("dist_write", rows=written,
                              fan_out=len(tasks), rpcs=len(tasks))
        return written

    def _rewrite_stale_part(self, part: Dict[str, Sequence],
                            op: str) -> int:
        """Re-route one failed write part after a stale-route refresh:
        the refined rule may fan the SAME rows across different (child)
        regions. Retries with backoff ride over the fenced handoff
        window; re-writes are MVCC-idempotent upserts, so a row that DID
        land before the error cannot duplicate."""
        attempt = 0
        while True:
            attempt += 1
            if attempt > _STALE_ROUTE_MAX_RETRIES[0]:
                raise StaleRouteError(
                    f"write to {self.info.name} still stale after "
                    f"{attempt - 1} route refreshes")
            delay_ms = min(_STALE_ROUTE_BASE_MS[0] * (2 ** (attempt - 1)),
                           _STALE_ROUTE_MAX_BACKOFF_MS)
            time.sleep(delay_ms / 1e3 * (0.5 + random.random()))
            self.refresh_route()
            num_rows = len(next(iter(part.values())))
            splits = split_rows(self.partition_rule, part, num_rows) \
                if self.partition_rule is not None \
                else {self._first_region(): None}
            try:
                written = 0
                for rnum, idx in splits.items():
                    piece = part if idx is None else \
                        {k: v[idx] if isinstance(v, np.ndarray)
                         else [v[i] for i in idx] for k, v in part.items()}
                    written += _dist_rpc(
                        f"write_region[{rnum}]",
                        lambda r=rnum, p=piece: self._owner(r).write_region(
                            self.info.catalog_name, self.info.schema_name,
                            self.info.name, r, p, op))
                from ..common.telemetry import increment_counter
                increment_counter("stale_route_write_reroutes")
                return written
            except (StaleRouteError, RegionClosedError) as e:
                logger.info("re-routed write to %s still stale (%s); "
                            "retry %d/%d", self.info.name, e, attempt,
                            _STALE_ROUTE_MAX_RETRIES[0])

    def _first_region(self) -> int:
        return self.route.region_routes[0].region_number

    # ---- reads ----
    def scan_batches(self, projection: Optional[Sequence[str]] = None,
                     time_range=None, limit: Optional[int] = None,
                     filters: Optional[Sequence] = None) -> list:
        """Pruned parallel scan with stale-route refresh: a datanode that
        no longer hosts a requested region (migrate/split landed mid-
        statement) raises StaleRouteError instead of returning partial
        rows, and the whole scan re-plans under the fresh route."""
        return self._retry_stale(
            "scan", lambda: self._scan_batches_once(
                projection=projection, time_range=time_range,
                limit=limit, filters=filters))

    def _scan_batches_once(self, projection: Optional[Sequence[str]] = None,
                           time_range=None, limit: Optional[int] = None,
                           filters: Optional[Sequence] = None) -> list:
        """One pruned parallel scan pass. `filters` are the statement's
        WHERE conjuncts (query/engine.py): they prune regions here, and
        the pushable tag subset also ships over the wire so datanodes
        drop dead rows before they ever cross a socket. `limit` travels
        only when the shipped subset IS the whole predicate — otherwise a
        frontend-side re-filter could leave fewer than `limit` rows."""
        from ..mito.engine import pushable_tag_filter
        filters = list(filters or ())
        survivors, total = self._prune_regions(filters=filters,
                                               time_range=time_range)
        targets = self._read_owners_for(survivors)
        tag_names = self.schema.tag_names()
        ship = [f for f in filters if pushable_tag_filter(f, tag_names)]
        wire_limit = limit if limit is not None and \
            len(ship) == len(filters) else None
        self._record_scatter(len(survivors), total, len(targets))
        out: list = []
        rows = 0
        node_ms: list = []
        for batches, dt_ms in self._scatter(
                targets,
                lambda c, regs: c.scan_batches(
                    self.info.catalog_name, self.info.schema_name,
                    self.info.name, projection=projection,
                    time_range=time_range, limit=wire_limit,
                    filters=ship or None, regions=regs),
                what="scan", node_ms=node_ms):
            out.extend(batches)
            rows += sum(b.num_rows for b in batches)
            if wire_limit is not None and rows >= wire_limit:
                # enough rows: abandoning the gather cancels queued RPCs
                # (the shipped filters ARE the predicate when a limit
                # travels, so any `limit` matching rows answer exactly)
                break
        self._record_node_vector(rows, node_ms)
        return out

    def _record_node_vector(self, rows: int, node_ms: list) -> None:
        """The per-node latency vector (not just its max) — rendered in
        the dist_scatter detail, kept on the table for bench.py's
        scatter profile JSON line. String values: a statement that
        scatters twice must not SUM its latencies (numeric details
        accumulate in ExecStats)."""
        slowest = max((ms for _, ms in node_ms), default=0.0)
        vector = "/".join(
            f"{label}:{ms:.1f}" for label, ms in sorted(
                node_ms, key=lambda kv: exec_stats.node_sort_key(kv[0]))
        ) or "-"
        self.last_scatter_node_ms = {label: ms for label, ms in node_ms}
        exec_stats.record("dist_scatter", rows=rows,
                          slowest_node_ms=f"{slowest:.2f}",
                          node_ms=vector)

    def _plan_scatter(self, plan):
        """(survivors, total, targets, cost) for an aggregate plan,
        memoized on the plan object — try_execute asks for the dispatch
        string (scatter_describe) right before execute_tpu_plan runs the
        same plan, and the route + cost walk should happen once. Keyed
        on the route version too: a stale-route refresh mid-statement
        must re-plan instead of re-using a scatter over regions that
        just moved."""
        cached = getattr(plan, "_dist_scatter_cache", None)
        if cached is not None and cached[0] is self and \
                cached[1] == self.route.version:
            return cached[2]
        survivors, total = self._prune_regions(
            filters=plan.tag_predicates, time_lo=plan.time_lo,
            time_hi=plan.time_hi)
        targets = self._read_owners_for(survivors)
        cost = self._plan_cost(plan, survivors)
        result = (survivors, total, targets, cost)
        plan._dist_scatter_cache = (self, self.route.version, result)
        return result

    # ---- cost-based dispatch (ISSUE 14) ----
    #: heartbeat-estimate cache TTL: one meta read serves a burst of
    #: statements; heat only moves at heartbeat cadence anyway
    _HEAT_TTL_S = 5.0

    def _region_estimates(self, wanted: Sequence[int]
                          ) -> Dict[int, Tuple[int, int, int]]:
        """{region_number: (rows, series, time_span)} for the cost
        planner, restricted to `wanted` (the plan's surviving regions —
        pruned siblings must not pay the SST-meta walk). In-process
        datanodes are walked directly (SST/memtable stats + series-dict
        counts); regions behind a wire client fall back to the meta
        heartbeat's region_stats — the SAME numbers, one stat beat
        stale, that every datanode already ships (ISSUE 14: 'SST stats
        + series-dict counts already in the route/heartbeat'). Results
        are TTL-cached per route version, so a statement burst pays one
        walk. Regions neither walkable nor heartbeat-known stay absent
        and the planner defaults to partial pushdown. Estimation must
        never fail a query."""
        now = time.monotonic()
        cache = getattr(self, "_est_cache", None)
        if cache is None or cache[0] <= now or \
                cache[2] != self.route.version:
            cache = (now + self._HEAT_TTL_S,
                     {}, self.route.version)
            self._est_cache = cache
        est: Dict[int, Tuple[int, int, int]] = cache[1]
        todo = [rn for rn in wanted if rn not in est]
        if not todo:
            return est
        from ..query.stream_exec import (region_estimated_rows,
                                         region_time_span)
        by_number = {rr.region_number: rr
                     for rr in self.route.region_routes}
        missing: List[int] = []
        for rn in todo:
            rr = by_number.get(rn)
            client = self.clients.get(rr.leader.id) \
                if rr is not None else None
            datanode = getattr(client, "datanode", None)
            if datanode is None:
                missing.append(rn)
                continue
            try:
                t = datanode.catalog.table(
                    self.info.catalog_name, self.info.schema_name,
                    self.info.name)
                region = t.regions.get(rn) if t is not None else None
                if region is None:
                    missing.append(rn)
                    continue
                sd = getattr(region, "series_dict", None)
                est[rn] = (
                    region_estimated_rows(region),
                    int(getattr(sd, "num_series", 0) or 0),
                    region_time_span(region))
            except Exception:  # noqa: BLE001 — estimates are advisory:
                # an unwalkable region leaves the map partial and the
                # planner defaults to pushdown
                from ..common.telemetry import increment_counter
                increment_counter("cost_estimate_errors")
                missing.append(rn)
                continue
        if missing:
            from ..mito.engine import region_name
            heat = self._heartbeat_estimates()
            for rn in missing:
                found = heat.get(region_name(self.info.ident.table_id,
                                             rn))
                if found is not None:
                    est[rn] = found
        return est

    def _heartbeat_estimates(self) -> Dict[str, Tuple[int, int, int]]:
        """{region name: (rows, series, time_span)} from the meta
        service's heartbeat-fed region stats, TTL-cached per table so a
        statement burst costs one meta read. Empty (and still cached,
        bounding the retry rate) when meta is unreachable or not the
        leader — the planner then defaults to pushdown."""
        cached = getattr(self, "_heat_cache", None)
        now = time.monotonic()
        if cached is not None and cached[0] > now:
            return cached[1]
        heat: Dict[str, Tuple[int, int, int]] = {}
        if self.meta is not None and hasattr(self.meta, "region_heat"):
            try:
                for h in self.meta.region_heat():
                    heat[str(h["region"])] = (
                        int(h.get("rows", 0) or 0),
                        int(h.get("series", 0) or 0),
                        int(h.get("time_span", 0) or 0))
            except Exception:  # noqa: BLE001 — advisory: a follower
                # meta or a flaky hop degrades to pushdown-by-default
                from ..common.telemetry import increment_counter
                increment_counter("cost_estimate_errors")
                heat = {}
        self._heat_cache = (now + self._HEAT_TTL_S, heat)
        return heat

    def _plan_cost(self, plan, survivors) -> Optional[dict]:
        """Estimated result cardinality + wire bytes for this plan over
        the surviving regions, and the partial-pushdown vs raw-pull
        choice. None = no estimate (remote datanodes without local
        stats): pushdown by default.

        The model: each region's GROUP BY yields at most
        min(rows, series × buckets) partial groups; a partial group
        costs its moment widths (8B numeric, bounded sketch frames for
        distinct/t-digest); a raw row costs its projected columns.
        Raw-pull wins only when the partial frames would outweigh the
        raw rows ~2x — the GROUP BY keys are nearly unique and a
        per-group sketch carries more than the rows it summarizes."""
        from ..query import sketches
        from ..query.tpu_exec import plan_scan_columns
        est = self._region_estimates(survivors)
        if not survivors or any(r not in est for r in survivors):
            return None
        rows = 0
        groups = 0
        stride = plan.bucket.stride_ms if plan.bucket is not None else None
        for r in survivors:
            n, series, span = est[r]
            if n == 0:
                continue
            rows += n
            g = max(1, series) if plan.tag_groups else 1
            if stride:
                g *= max(1, min(n, -(-max(span, 1) // stride)))
            groups += min(n, g)
        if rows == 0:
            return {"mode": "pushdown", "est_rows": 0, "est_groups": 0}
        rows_per_g = max(1, rows // max(groups, 1))
        per_g = 8 * (len(plan.tag_groups) +
                     (1 if plan.bucket else 0) + 1)   # keys + __rowcount
        for m in plan.moments:
            if m.op == "distinct":
                per_g += min(
                    8 * min(rows_per_g, sketches.EXACT_SET_LIMIT) + 40,
                    (1 << sketches.hll_precision()) + 16)
            elif m.op == "tdigest":
                per_g += 16 * min(rows_per_g,
                                  int(sketches.tdigest_delta())) + 44
            else:
                per_g += 8
        partial_b = groups * per_g
        raw_b = rows * (20 + 8 * len(plan_scan_columns(plan,
                                                       self.schema)))
        mode = "raw" if partial_b > 2 * raw_b else "pushdown"
        return {"mode": mode, "est_rows": int(rows),
                "est_groups": int(groups), "partial_bytes": int(partial_b),
                "raw_bytes": int(raw_b)}

    def execute_tpu_plan(self, plan) -> List[pd.DataFrame]:
        """Aggregate pushdown: prune regions by the plan's tag/time
        predicates, then each surviving datanode reduces ONLY its
        surviving regions on device; moment frames fold as they arrive.
        Stale routes re-plan + retry like the scan path."""
        return self._retry_stale(
            "aggregate", lambda: self._execute_tpu_plan_once(plan))

    def _execute_tpu_plan_once(self, plan) -> List[pd.DataFrame]:
        survivors, total, targets, cost = self._plan_scatter(plan)
        if cost is not None and cost["mode"] == "raw":
            # cost-based choice: the partial frames would outweigh the
            # raw rows — UnsupportedError sends try_execute to the
            # raw-row scatter, under the SAME dispatch line
            # scatter_describe already printed
            raise UnsupportedError(
                f"cost-based dispatch chose raw-pull (est "
                f"{cost['est_rows']} rows -> {cost['est_groups']} "
                f"groups)")
        self._record_scatter(len(survivors), total, len(targets))
        frames: List[pd.DataFrame] = []
        node_ms: list = []
        for part, dt_ms in self._scatter(
                targets,
                lambda c, regs: c.region_moments(
                    self.info.catalog_name, self.info.schema_name,
                    self.info.name, plan, regions=regs),
                what="region_moments", node_ms=node_ms):
            frames.extend(part)        # fold-as-they-arrive gather
        self._record_node_vector(0, node_ms)
        return frames

    def scatter_describe(self, plan) -> str:
        """The pruned-scatter dispatch line shared by EXPLAIN and
        execution (query/tpu_exec.dispatch_decision_for_pushdown) —
        including the cost-based partial-pushdown vs raw-pull choice
        with its row estimates, so EXPLAIN, EXPLAIN ANALYZE and the
        executed path render ONE decision."""
        survivors, total, targets, cost = self._plan_scatter(plan)
        prefix = (f"regions pruned {total - len(survivors)}/{total}, "
                  f"fan-out={len(targets)}")
        if cost is None:
            return (f"aggregate-pushdown ({prefix}; "
                    f"datanodes reduce, frontend folds)")
        est = (f"est_rows={cost['est_rows']} -> "
               f"est_groups={cost['est_groups']}")
        if cost["mode"] == "raw":
            return (f"raw-pull ({prefix}; {est}, partial frames would "
                    f"outweigh raw rows; datanodes ship rows, frontend "
                    f"aggregates)")
        return (f"aggregate-pushdown ({prefix}; {est}; "
                f"datanodes reduce, frontend folds)")

    def flush(self) -> None:
        """Flush every datanode's regions concurrently (the serial loop
        used to pay the sum of N datanode flushes)."""
        def once():
            for _ in self._scatter(
                    self._owners_for(self._all_region_numbers()),
                    lambda c, regs: c.flush_table(
                        self.info.catalog_name, self.info.schema_name,
                        self.info.name),
                    what="flush_table"):
                pass
        self._retry_stale("flush", once)


class _RouteHydratingCatalog(MemoryCatalogManager):
    """Frontend catalog that falls back to the meta routes on a miss
    (reference: FrontendCatalogManager resolves through the meta KV on
    demand, src/frontend/src/catalog.rs). Hydration happens at table-
    resolution depth, so every statement shape — SELECT, INSERT..SELECT,
    TQL, DESCRIBE — sees remote tables on a fresh frontend."""

    def __init__(self, instance: "DistInstance"):
        super().__init__()
        self._instance = instance
        self._miss_guard = threading.local()

    def table(self, catalog: str, schema: str, name: str):
        t = super().table(catalog, schema, name)
        if t is not None or getattr(self._miss_guard, "busy", False):
            return t
        self._miss_guard.busy = True
        try:
            route = self._instance.meta.route(
                f"{catalog}.{schema}.{name}")
            if route is None:
                return None
            return self._instance._hydrate_table(route, catalog, schema,
                                                 name)
        finally:
            self._miss_guard.busy = False


class DistInstance:
    """Distributed frontend instance (reference DistInstance).

    Wires: meta client (routes/ids/heartbeats) + one DatanodeClient per
    worker + a frontend-local catalog of DistTables + the query engine."""

    def __init__(self, meta: MetaClient,
                 clients: Dict[int, DatanodeClient]):
        self.meta = meta
        self.clients = clients
        self.catalog = _RouteHydratingCatalog(self)
        # information_schema.cluster_info resolves through the meta
        # client hanging off the catalog (both frontends serve the view)
        self.catalog.meta_client = meta
        self.query_engine = QueryEngine(self.catalog)
        # continuous rollup flows: specs live in the meta kv so every
        # frontend (and a restarted one) sees the same flows; folds run
        # through the generic scan-based path over DistTables
        from ..flow import FlowManager, KvFlowStore
        # wire meta clients without kv passthroughs still get in-memory
        # flows; the in-process MetaClient persists specs under __flow/
        store = KvFlowStore(meta) \
            if hasattr(meta, "kv_put") or hasattr(meta, "put") else None
        self.flow_manager = FlowManager(
            self.catalog, store, create_sink_fn=self._create_flow_sink)
        self.flow_manager.recover()
        self.query_engine.flow_manager = self.flow_manager
        self.catalog.flow_manager = self.flow_manager
        # self-monitoring: the frontend scrapes its own registry plus the
        # meta service's cluster-wide region heat (heartbeat-derived)
        # into greptime_private tables, written through the normal
        # distributed ingest path. Background ticking is opt-in
        # (self_monitor.start_background) — cmd/main wires it; tests
        # drive tick() cooperatively.
        from ..common import (background_jobs, process_list, profiler,
                              trace_store)
        from ..monitor import SelfMonitor
        self.self_monitor = SelfMonitor(self, node_label="frontend",
                                        meta=meta)
        self.catalog.self_monitor = self.self_monitor
        process_list.configure_node("frontend")
        background_jobs.configure_node("frontend")
        # durable trace store, root role: this frontend decides the tail
        # verdict for its statements' traces; datanode spans buffer
        # remotely until the verdict piggybacks on a later RPC (or the
        # in-process datanodes of a test cluster share this very sink)
        self.trace_sink = trace_store.TraceSink(
            node_label="frontend", service="frontend", role="root",
            writer=self)
        trace_store.install(self.trace_sink)
        self.catalog.trace_sink = self.trace_sink
        # continuous profiler, same root role: samples taken on this
        # frontend flush through the self-monitor path; datanode-side
        # samples drain over the Flight `profile` action on demand
        self.profiler = profiler.Profiler(node_label="frontend",
                                          writer=self)
        profiler.install(self.profiler)
        # information_schema.background_jobs fans out to every
        # reachable datanode and merges (compactions run THERE)
        self.catalog.dist_clients = clients
        # TQL / PromQL rides the same engine as standalone: selectors
        # resolve DistTables from this catalog, and the lowering in
        # promql/lowering.py ships TpuPlans through execute_tpu_plan
        self._tql_engine = None

    def _create_flow_sink(self, spec, schema, pk_indices):
        """Materialize a flow sink as an ordinary distributed table."""
        cols = []
        for cs in schema.column_schemas:
            cols.append(ast.ColumnDef(
                name=cs.name, type_name=cs.dtype.name,
                nullable=cs.nullable,
                is_time_index=cs.is_time_index,
                is_primary_key=cs.is_tag))
        stmt = ast.CreateTable(
            name=ast.ObjectName([spec.catalog, spec.schema, spec.sink]),
            columns=cols,
            time_index=spec.ts_column,
            primary_keys=[c.name for c in schema.column_schemas
                          if c.is_tag],
            if_not_exists=True)
        ctx = QueryContext(spec.catalog, spec.schema)
        return self.create_table(stmt, ctx)

    # ---- DDL ----
    def create_table(self, stmt: ast.CreateTable,
                     ctx: Optional[QueryContext] = None) -> DistTable:
        from .statement import build_schema_from_create
        ctx = ctx or QueryContext()
        catalog, schema_name, table_name = ctx.resolve(stmt.name)
        full = f"{catalog}.{schema_name}.{table_name}"
        if self.catalog.table(catalog, schema_name, table_name) \
                is not None:
            if stmt.if_not_exists:
                return self.catalog.table(catalog, schema_name, table_name)
            raise TableAlreadyExistsError(f"table {full} already exists")

        existing_route = self.meta.route(full)
        if existing_route is not None:
            # frontend restart / second frontend: reattach to the live
            # table instead of failing an idempotent statement
            table = self._hydrate_table(existing_route, catalog,
                                        schema_name, table_name)
            if stmt.if_not_exists and table is not None:
                return table
            raise TableAlreadyExistsError(f"table {full} already exists")

        schema, pk_indices = build_schema_from_create(stmt)
        rule = rule_from_partitions(stmt.partitions) \
            if stmt.partitions is not None else None
        region_numbers = rule.region_numbers() if rule is not None else [0]

        # 1. meta: allocate id + place regions on alive datanodes
        route = self.meta.create_route(full, region_numbers)
        try:
            # 2. fan out: each datanode creates its region subset
            for peer in route.peers():
                client = self.clients.get(peer.id)
                if client is None:
                    raise GreptimeError(f"no client for datanode {peer.id}")
                client.ddl_create_table(CreateTableRequest(
                    table_name, schema,
                    catalog_name=catalog, schema_name=schema_name,
                    primary_key_indices=pk_indices,
                    create_if_not_exists=True,
                    table_options=dict(stmt.options or {}),
                    partitions=stmt.partitions,
                    table_id=route.table_id,
                    assigned_region_numbers=route.regions_on(peer.id)))
        except Exception:
            # roll back: route + any datanode that already created its part
            self.meta.delete_route(full)
            for peer in route.peers():
                client = self.clients.get(peer.id)
                if client is None:
                    continue
                try:
                    client.ddl_drop_table(catalog, schema_name, table_name)
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "rollback drop on datanode %d failed", peer.id)
            raise

        info = TableInfo(
            ident=TableIdent(route.table_id),
            name=table_name,
            meta=TableMeta(schema=schema,
                           primary_key_indices=pk_indices,
                           engine="mito",
                           region_numbers=list(region_numbers),
                           next_column_id=len(schema),
                           options=dict(stmt.options or {}),
                           partition_rule=_serialize_dist_rule(rule)),
            catalog_name=catalog, schema_name=schema_name)
        # schema travels with the route (TableGlobalValue) so failover
        # can materialize regions on datanodes that never saw the DDL
        if hasattr(self.meta, "put_table_info"):
            self.meta.put_table_info(full, info.to_dict())
        table = DistTable(info, rule, route, self.clients,
                          meta=self.meta)
        self.catalog.register_table(catalog, schema_name, table_name, table)
        return table

    def drop_table(self, stmt: ast.DropTable,
                   ctx: Optional[QueryContext] = None) -> bool:
        ctx = ctx or QueryContext()
        catalog, schema_name, name = ctx.resolve(stmt.name)
        table = self._resolve_table(catalog, schema_name, name)
        if table is None:
            if stmt.if_exists:
                return False
            raise TableNotFoundError(f"table {name} not found")
        for client in table._involved_clients():
            client.ddl_drop_table(catalog, schema_name, name)
        self.meta.delete_route(f"{catalog}.{schema_name}.{name}")
        if hasattr(self.meta, "delete_table_info"):
            self.meta.delete_table_info(f"{catalog}.{schema_name}.{name}")
        self.catalog.deregister_table(catalog, schema_name, name)
        return True

    def _resolve_table(self, catalog: str, schema_name: str, name: str):
        """Local catalog first, then rebuild a DistTable from the meta
        route (frontend restart path)."""
        table = self.catalog.table(catalog, schema_name, name)
        if table is not None:
            return table
        route = self.meta.route(f"{catalog}.{schema_name}.{name}")
        if route is None:
            return None
        return self._hydrate_table(route, catalog, schema_name, name)

    def _hydrate_table(self, route: TableRoute, catalog: str,
                       schema_name: str, name: str) -> Optional[DistTable]:
        """Rebuild the frontend-side DistTable from the route + a hosting
        datanode's local table metadata."""
        for peer in route.peers():
            client = self.clients.get(peer.id)
            if client is None:
                continue
            described = client.describe_table(catalog, schema_name, name)
            if described is None:
                continue
            info, rule = described
            region_numbers = sorted(
                rr.region_number for rr in route.region_routes)
            info = TableInfo(
                ident=TableIdent(route.table_id), name=name,
                meta=TableMeta(
                    schema=info.meta.schema,
                    primary_key_indices=list(
                        info.meta.primary_key_indices),
                    engine=info.meta.engine,
                    region_numbers=region_numbers,
                    next_column_id=info.meta.next_column_id,
                    options=dict(info.meta.options)),
                catalog_name=catalog, schema_name=schema_name)
            table = DistTable(info, rule, route, self.clients,
                          meta=self.meta)
            self.catalog.register_table(catalog, schema_name, name, table)
            return table
        return None

    # ---- protocol ingest: auto create / alter on demand ----
    def handle_bulk_load(
        self, table_name: str, columns: Dict[str, Sequence],
        *, tag_columns: Sequence[str] = (),
        timestamp_column: str = "greptime_timestamp",
        types=None, ctx: Optional[QueryContext] = None,
    ) -> int:
        """Distributed bulk load: same auto create/alter as row insert,
        but each datanode ingests its partition WAL-less
        (DistTable.bulk_load → write_region op="bulk")."""
        return self.handle_row_insert(
            table_name, columns, tag_columns=tag_columns,
            timestamp_column=timestamp_column, types=types, ctx=ctx,
            _bulk=True)

    def handle_row_insert(
        self, table_name: str, columns: Dict[str, Sequence],
        *, tag_columns: Sequence[str] = (),
        timestamp_column: str = "greptime_timestamp",
        types=None, ctx: Optional[QueryContext] = None,
        _bulk: bool = False,
    ) -> int:
        """Distributed twin of the standalone auto-create/alter ingest
        (reference: DistInstance implements the same handler traits,
        src/frontend/src/instance.rs:83-97). Auto-created tables get one
        region placed by the meta selector; missing field columns fan
        an ALTER out to every owning datanode."""
        from .instance import build_ingest_schema, infer_ingest_type
        ctx = ctx or QueryContext()
        catalog, schema_name = ctx.current_catalog, ctx.current_schema
        table = self._resolve_table(catalog, schema_name, table_name)
        if table is None:
            schema, pk = build_ingest_schema(columns, tag_columns,
                                             timestamp_column, types)
            full = f"{catalog}.{schema_name}.{table_name}"
            route = self.meta.create_route(full, [0])
            for peer in route.peers():
                self.clients[peer.id].ddl_create_table(CreateTableRequest(
                    table_name, schema, catalog_name=catalog,
                    schema_name=schema_name, primary_key_indices=pk,
                    create_if_not_exists=True, table_id=route.table_id,
                    assigned_region_numbers=route.regions_on(peer.id)))
            info = TableInfo(
                ident=TableIdent(route.table_id), name=table_name,
                meta=TableMeta(schema=schema, primary_key_indices=pk,
                               engine="mito", region_numbers=[0],
                               next_column_id=len(schema)),
                catalog_name=catalog, schema_name=schema_name)
            table = DistTable(info, None, route, self.clients,
                              meta=self.meta)
            from ..errors import TableAlreadyExistsError
            try:
                self.catalog.register_table(catalog, schema_name,
                                            table_name, table)
            except TableAlreadyExistsError:
                # concurrent protocol auto-create race (coalesced ingest
                # makes first-write storms normal): adopt the winner's
                # registration — the datanode-side create was already
                # if-not-exists
                existing = self._resolve_table(catalog, schema_name,
                                               table_name)
                if existing is not None:
                    table = existing
        else:
            missing = [n for n in columns
                       if not table.schema.contains(n)]
            new_tags = [n for n in missing if n in set(tag_columns)]
            if new_tags:
                raise InvalidArgumentsError(
                    f"table {table_name!r} has no tag column(s) "
                    f"{new_tags}; tags cannot be added after create")
            if missing:
                from ..datatypes.schema import ColumnSchema
                from ..table.requests import (
                    AddColumnRequest, AlterKind, AlterTableRequest)
                adds = [AddColumnRequest(ColumnSchema(
                    n, infer_ingest_type(n, columns[n], types or {}, "")))
                    for n in missing]
                req = AlterTableRequest(
                    table_name, AlterKind.ADD_COLUMNS,
                    catalog_name=catalog, schema_name=schema_name,
                    add_columns=adds)
                for client in table._involved_clients():
                    client.ddl_alter_table(req)
                # refresh the frontend view from a datanode's new schema
                self.catalog.deregister_table(catalog, schema_name,
                                              table_name)
                table = self._resolve_table(catalog, schema_name,
                                            table_name)
        return table.bulk_load(columns) if _bulk else table.insert(columns)

    def alter_table(self, stmt: ast.AlterTable, ctx: QueryContext):
        """Distributed ALTER: fan the engine request out to every owning
        datanode, then refresh the frontend view (and, for RENAME, move
        the meta route so the table resolves under its new name).
        Reference: dist DDL via meta procedures,
        src/frontend/src/instance/distributed.rs + alter flow in
        src/table/src/metadata.rs:249-297."""
        from ..query.output import Output
        from ..table.requests import (
            AddColumnRequest, AlterKind, AlterTableRequest)
        from .statement import build_column_schema
        catalog, schema_name, table_name = ctx.resolve(stmt.table)
        table = self._resolve_table(catalog, schema_name, table_name)
        if table is None:
            raise TableNotFoundError(f"table {table_name!r} not found")
        op = stmt.operation
        if isinstance(op, ast.AddColumn):
            cs = build_column_schema(op.column, is_tag=False,
                                     is_time_index=False)
            req = AlterTableRequest(
                table_name, AlterKind.ADD_COLUMNS, catalog_name=catalog,
                schema_name=schema_name,
                add_columns=[AddColumnRequest(cs, location=op.location)])
        elif isinstance(op, ast.DropColumn):
            req = AlterTableRequest(
                table_name, AlterKind.DROP_COLUMNS, catalog_name=catalog,
                schema_name=schema_name, drop_columns=[op.name])
        elif isinstance(op, ast.RenameTable):
            req = AlterTableRequest(
                table_name, AlterKind.RENAME_TABLE, catalog_name=catalog,
                schema_name=schema_name, new_table_name=op.new_name)
        else:
            raise UnsupportedError(f"ALTER operation {type(op).__name__}")
        for client in table._involved_clients():
            client.ddl_alter_table(req)
        self.catalog.deregister_table(catalog, schema_name, table_name)
        if isinstance(op, ast.RenameTable):
            self.meta.rename_route(
                f"{catalog}.{schema_name}.{table_name}",
                f"{catalog}.{schema_name}.{op.new_name}")
            self._resolve_table(catalog, schema_name, op.new_name)
        else:
            self._resolve_table(catalog, schema_name, table_name)
        return Output.rows(0)

    # ---- SQL ----
    def do_query(self, sql: str, ctx: Optional[QueryContext] = None):
        import time as _time

        from ..common import process_list
        from ..common.telemetry import (
            increment_counter, observe_latency, slow_query_threshold_ms,
            span, timer)
        from ..sql import parse_statements
        from ..common.admission import GATE as _admission
        ctx = ctx or QueryContext()
        outs = []
        for stmt in parse_statements(sql):
            # same admission gate as the standalone frontend: reject
            # past the in-flight limit, KILL/SET always admitted
            _admission.admit_statement(type(stmt).__name__)
            t0 = _time.perf_counter()
            prev_stats = getattr(self.query_engine, "last_exec_stats",
                                 None)
            try:
                with span("execute_stmt", stmt=type(stmt).__name__,
                          distributed=True) as sp, timer("stmt_execute"), \
                        process_list.track(
                            sql, protocol=ctx.channel.value,
                            catalog=ctx.current_catalog,
                            schema=ctx.current_schema,
                            trace_id=sp["trace_id"]):
                    outs.append(self.execute_stmt(stmt, ctx))
            finally:
                # finally: failing statements must count in the
                # latency distribution too
                observe_latency(
                    "stmt_latency", _time.perf_counter() - t0,
                    stmt=type(stmt).__name__,
                    protocol=ctx.channel.value)
            increment_counter(f"stmt_{type(stmt).__name__.lower()}")
            elapsed_ms = (_time.perf_counter() - t0) * 1e3
            thr = slow_query_threshold_ms()
            if thr is not None and elapsed_ms >= thr:
                stats = getattr(self.query_engine, "last_exec_stats",
                                None)
                if stats is prev_stats:     # not this statement's stats
                    stats = None
                import logging

                from ..common import profiler, trace_store
                sink = trace_store.sink()
                logging.getLogger("greptimedb_tpu.slow_query").warning(
                    "slow query: %.1fms (threshold %dms) trace=%s "
                    "trace_stored=%s%s stmt=%r stats=[%s]", elapsed_ms,
                    thr, sp["trace_id"],
                    sink.stored_verdict(sp["trace_id"])
                    if sink is not None else "off",
                    profiler.slow_query_suffix(sp["trace_id"]), sql,
                    stats.summary() if stats is not None else "n/a")
        return outs

    def execute_stmt(self, stmt, ctx: QueryContext):
        from ..query.output import Output
        if isinstance(stmt, ast.CreateTable):
            self.create_table(stmt, ctx)
            return Output.rows(0)
        if isinstance(stmt, ast.DropTable):
            self.drop_table(stmt, ctx)
            return Output.rows(0)
        if isinstance(stmt, ast.AlterTable):
            return self.alter_table(stmt, ctx)
        if isinstance(stmt, ast.Insert):
            return self._insert(stmt, ctx)
        if isinstance(stmt, ast.Delete):
            return self._delete(stmt, ctx)
        if isinstance(stmt, ast.CreateFlow):
            self.flow_manager.create_flow(stmt, ctx)
            return Output.rows(0)
        if isinstance(stmt, ast.DropFlow):
            self.flow_manager.drop_flow(stmt.name, ctx,
                                        if_exists=stmt.if_exists)
            return Output.rows(0)
        if isinstance(stmt, ast.ShowFlows):
            from .statement import show_flows_output
            return show_flows_output(self.flow_manager, stmt, ctx)
        if isinstance(stmt, ast.SetVariable):
            # balancer knobs forward to meta-srv (the balancer lives on
            # the meta leader); everything else is the shared handler
            name = stmt.name.lower()
            if name.startswith("balancer_") and \
                    hasattr(self.meta, "balancer_configure"):
                from ..query.output import Output as _Output
                self.meta.balancer_configure(
                    name[len("balancer_"):], stmt.value)
                return _Output.rows(0)
            if name in ("read_replica", "replica_max_lag_ms"):
                # replica-aware read routing is frontend-local state
                # (each frontend scatters its own reads)
                from ..query.output import Output as _Output
                if name == "read_replica":
                    configure_read_replica(mode=stmt.value)
                else:
                    configure_read_replica(max_lag_ms=stmt.value)
                return _Output.rows(0)
            from .statement import apply_set_variable
            return apply_set_variable(stmt, ctx)
        if isinstance(stmt, ast.Kill):
            from .statement import apply_kill
            return apply_kill(stmt)
        if isinstance(stmt, ast.Admin):
            return self._admin(stmt, ctx)
        if isinstance(stmt, ast.Tql):
            return self.promql_engine().execute_tql(stmt, ctx)
        return self.query_engine.execute(stmt, ctx)

    def promql_engine(self):
        """Lazily-built, shared PromQL engine (TQL + /api/v1 + /v1/promql).

        Same engine as standalone: its selectors resolve DistTables from
        this frontend's catalog, so lowerable aggregates scatter TpuPlans
        to the datanodes and non-lowerable shapes ride the IR raw scan
        (region pruning + wire filter pushdown)."""
        if self._tql_engine is None:
            try:
                from ..promql.engine import PromqlEngine
            except ImportError as e:
                from ..errors import UnsupportedError
                raise UnsupportedError(
                    f"PromQL engine unavailable: {e}") from e
            self._tql_engine = PromqlEngine(self.catalog)
        return self._tql_engine

    def _admin(self, stmt: ast.Admin, ctx: QueryContext):
        """ADMIN MIGRATE/SPLIT/REBALANCE → meta balancer ops. Async by
        design (the reference's migrate_region returns a procedure id):
        the returned op id tracks progress in region_peers."""
        from .statement import admin_ops_output
        if stmt.kind in ("flush_table", "compact_table"):
            from .statement import apply_admin_maintenance
            return apply_admin_maintenance(self.catalog, stmt, ctx)
        if stmt.kind == "show_trace":
            # sync first: a ping per datanode piggybacks this frontend's
            # verdicts and collects any released buffered spans, so the
            # waterfall is complete even though the query long finished
            from .statement import apply_show_trace
            return apply_show_trace(self.catalog, stmt,
                                    sync_clients=list(
                                        self.clients.values()))
        if stmt.kind == "show_profile":
            # drain every datanode's pending sample aggregate over the
            # Flight `profile` action, flush locally, then read the
            # per-node tree back out of greptime_private
            from .statement import apply_show_profile
            return apply_show_profile(self.catalog, stmt,
                                      sync_clients=list(
                                          self.clients.values()))
        if stmt.kind == "rebalance":
            full = None
            if stmt.table is not None:
                catalog, schema_name, name = ctx.resolve(stmt.table)
                full = f"{catalog}.{schema_name}.{name}"
            return admin_ops_output(self.meta.admin_rebalance(full))
        catalog, schema_name, name = ctx.resolve(stmt.table)
        full = f"{catalog}.{schema_name}.{name}"
        if self._resolve_table(catalog, schema_name, name) is None:
            raise TableNotFoundError(f"table {name!r} not found")
        if stmt.kind == "migrate_region":
            op = self.meta.admin_migrate_region(full, stmt.region,
                                                stmt.target_node)
        elif stmt.kind == "split_region":
            op = self.meta.admin_split_region(full, stmt.region,
                                              stmt.at_value)
        elif stmt.kind == "add_replica":
            op = self.meta.admin_add_replica(full, stmt.region,
                                             stmt.target_node)
        elif stmt.kind == "remove_replica":
            op = self.meta.admin_remove_replica(full, stmt.region,
                                                stmt.target_node)
        else:
            raise UnsupportedError(f"ADMIN {stmt.kind}")
        return admin_ops_output([op])

    def _insert(self, stmt: ast.Insert, ctx: QueryContext):
        from ..query.output import Output
        from .statement import evaluate_insert_rows
        catalog, schema_name, table_name = ctx.resolve(stmt.table)
        table = self._resolve_table(catalog, schema_name, table_name)
        if table is None:
            raise TableNotFoundError(f"table {table_name} not found")
        schema = table.schema
        columns = stmt.columns or schema.names()
        for c in columns:
            if not schema.contains(c):
                from ..errors import ColumnNotFoundError
                raise ColumnNotFoundError(
                    f"column {c!r} not found in {table_name!r}")
        cols = evaluate_insert_rows(stmt, columns, self.query_engine, ctx)
        return Output.rows(table.insert(cols))

    def _delete(self, stmt: ast.Delete, ctx: QueryContext):
        from .statement import delete_matching_rows
        catalog, schema_name, table_name = ctx.resolve(stmt.table)
        table = self.catalog.table(catalog, schema_name, table_name)
        if table is None:
            raise TableNotFoundError(f"table {table_name} not found")
        return delete_matching_rows(table, stmt)
