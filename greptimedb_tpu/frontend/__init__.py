"""Frontend: the SQL/protocol-facing instance.

Reference behavior: src/frontend — implements the protocol handler traits
(src/frontend/src/instance.rs:83-97), auto table create/alter on insert
(instance.rs:292-342), and the statement executor
(src/frontend/src/statement.rs).
"""

from .instance import FrontendInstance

__all__ = ["FrontendInstance"]
