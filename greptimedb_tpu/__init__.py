"""greptimedb_tpu — a TPU-native time-series / analytics database framework.

A ground-up rebuild of the capabilities of GreptimeDB v0.2.0 (reference:
iamazy/greptimedb, surveyed in SURVEY.md), designed TPU-first:

- columnar LSM storage engine: WAL + SoA memtable buffers + Parquet SSTs
  (reference: src/storage)
- scan / filter / group-by-tag / time-bucket aggregation, window functions
  (rate, *_over_time), merge+dedup, and compaction downsampling execute as
  JAX/XLA kernels (pjit/vmap/shard_map over device meshes)
- SQL and PromQL front ends, HTTP/MySQL/gRPC protocol servers
- standalone-to-distributed frontend/datanode/meta architecture

The compute path is JAX (jit/pallas); the host path (WAL, catalog, routing,
object-store I/O) is Python/C++ and never touches the accelerator.
"""

__version__ = "0.1.0"

DEFAULT_CATALOG_NAME = "greptime"
DEFAULT_SCHEMA_NAME = "public"
MITO_ENGINE = "mito"
