"""Shared host-side utilities."""

from __future__ import annotations

import os
import tempfile


def env_int(name: str, default: int) -> int:
    """Integer env knob; malformed values fall back to the default."""
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    """Float env knob; malformed values fall back to the default."""
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def env_flag(name: str, default: bool) -> bool:
    """Boolean env knob: unset → default; '0'/'false'/'off'/'no'/''
    (any case) → False; anything else → True. THE parser for on/off
    env twins — per-module copies drift on the accepted false-strings.
    Lives in this leaf module so storage/ can import it without pulling
    the runtime→scheduler→storage import cycle."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "off", "no", "")


def atomic_write(path: str, data: "bytes | str", *, fsync: bool = True,
                 tmp_prefix: str = ".tmp-") -> None:
    """Write `data` (bytes or str) to `path` atomically: temp file in the
    same directory, optional fsync, rename. A crash at any point leaves
    either the old file or the complete new one — never a torn mix — and
    the temp file is unlinked on failure. One implementation shared by
    every state-doc writer (object store, meta kv, raft persistence) so
    a durability fix lands everywhere at once."""
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=tmp_prefix)
    try:
        mode = "wb" if isinstance(data, (bytes, bytearray)) else "w"
        with os.fdopen(fd, mode) as f:
            f.write(data)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_publish(tmp_path: str, path: str, *, fsync: bool = True) -> None:
    """Publish an ALREADY-WRITTEN temp file to its final name atomically:
    the streaming/subprocess twin of :func:`atomic_write`, for bytes
    produced by someone else (a compiler, a spooled upload stream).
    Optionally fsyncs the temp file, renames it into place, and unlinks
    the temp on failure — same guarantees, same single implementation
    (greptlint GL03 allows renames only here)."""
    try:
        if fsync:
            with open(tmp_path, "rb+") as f:
                os.fsync(f.fileno())
        os.replace(tmp_path, path)
    except BaseException:
        try:
            os.unlink(tmp_path)
        except OSError:
            pass
        raise
