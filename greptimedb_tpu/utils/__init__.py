"""Shared host-side utilities."""

from __future__ import annotations

import os
import tempfile


def atomic_write(path: str, data, *, fsync: bool = True,
                 tmp_prefix: str = ".tmp-") -> None:
    """Write `data` (bytes or str) to `path` atomically: temp file in the
    same directory, optional fsync, rename. A crash at any point leaves
    either the old file or the complete new one — never a torn mix — and
    the temp file is unlinked on failure. One implementation shared by
    every state-doc writer (object store, meta kv, raft persistence) so
    a durability fix lands everywhere at once."""
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=tmp_prefix)
    try:
        mode = "wb" if isinstance(data, (bytes, bytearray)) else "w"
        with os.fdopen(fd, mode) as f:
            f.write(data)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
