"""Shared host-side utilities."""
