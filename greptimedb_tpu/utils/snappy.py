"""Pure-Python snappy block-format codec.

Prometheus remote read/write bodies are snappy-compressed protobuf
(reference: src/servers/src/prometheus.rs:286). The image has no snappy
binding, so this implements the block format directly: decompression is
complete; compression emits literal-only blocks (valid snappy, ~0% ratio —
fine for tests and small responses).
"""

from __future__ import annotations


def _read_varint(data: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("snappy: truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 35:
            raise ValueError("snappy: varint too long")


def _write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decompress(data: bytes) -> bytes:
    if not data:
        return b""
    expected, pos = _read_varint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        elem_type = tag & 0x03
        if elem_type == 0x00:                       # literal
            length = (tag >> 2) + 1
            pos += 1
            if length > 60:
                extra = length - 60
                if pos + extra > n:
                    raise ValueError("snappy: truncated literal length")
                length = int.from_bytes(data[pos:pos + extra], "little") + 1
                pos += extra
            if pos + length > n:
                raise ValueError("snappy: truncated literal")
            out += data[pos:pos + length]
            pos += length
            continue
        if elem_type == 0x01:                       # copy, 1-byte offset
            length = ((tag >> 2) & 0x07) + 4
            if pos + 1 >= n:
                raise ValueError("snappy: truncated copy1")
            offset = ((tag >> 5) << 8) | data[pos + 1]
            pos += 2
        elif elem_type == 0x02:                     # copy, 2-byte offset
            length = (tag >> 2) + 1
            if pos + 2 >= n:
                raise ValueError("snappy: truncated copy2")
            offset = int.from_bytes(data[pos + 1:pos + 3], "little")
            pos += 3
        else:                                       # copy, 4-byte offset
            length = (tag >> 2) + 1
            if pos + 4 >= n:
                raise ValueError("snappy: truncated copy4")
            offset = int.from_bytes(data[pos + 1:pos + 5], "little")
            pos += 5
        if offset == 0 or offset > len(out):
            raise ValueError("snappy: invalid copy offset")
        start = len(out) - offset
        for i in range(length):                     # may self-overlap
            out.append(out[start + i])
    if len(out) != expected:
        raise ValueError(
            f"snappy: length mismatch ({len(out)} != {expected})")
    return bytes(out)


def compress(data: bytes) -> bytes:
    """Literal-only snappy encoding (valid, uncompressed)."""
    out = bytearray(_write_varint(len(data)))
    pos = 0
    n = len(data)
    while pos < n:
        chunk = min(n - pos, 65536)
        if chunk <= 60:
            out.append((chunk - 1) << 2)
        else:
            extra = (chunk - 1).bit_length() + 7 >> 3
            out.append((59 + extra) << 2)
            out += (chunk - 1).to_bytes(extra, "little")
        out += data[pos:pos + chunk]
        pos += chunk
    return bytes(out)
