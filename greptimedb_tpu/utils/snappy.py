"""Snappy block-format codec: native C++ with pure-Python fallback.

Prometheus remote read/write bodies are snappy-compressed protobuf
(reference: src/servers/src/prometheus.rs:286, via the snappy crate).
The image has no snappy binding, so native/snappy.cpp implements the
block format (greedy hash-match compression + full decompression),
built on first use via g++ and bound through ctypes; this module keeps
the pure-Python decoder and a literal-only encoder as the fallback.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

_logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "snappy.cpp")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libgdbsnappy.so")
_lib = None
_lib_failed = False
_build_lock = threading.Lock()


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if not (os.path.exists(_LIB_PATH) and
                    os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC)):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     "-o", _LIB_PATH + ".tmp", _SRC],
                    check=True, capture_output=True, timeout=120)
                from . import atomic_publish
                atomic_publish(_LIB_PATH + ".tmp", _LIB_PATH,
                               fsync=False)   # build artifact
            lib = ctypes.CDLL(_LIB_PATH)
            lib.snappy_max_compressed.restype = ctypes.c_uint64
            lib.snappy_max_compressed.argtypes = [ctypes.c_uint64]
            lib.snappy_compress.restype = ctypes.c_uint64
            lib.snappy_compress.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p]
            lib.snappy_uncompressed_length.restype = ctypes.c_uint64
            lib.snappy_uncompressed_length.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64]
            lib.snappy_uncompress.restype = ctypes.c_int64
            lib.snappy_uncompress.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p,
                ctypes.c_uint64]
            _lib = lib
        except (subprocess.SubprocessError, OSError) as e:
            _logger.warning("native snappy unavailable (%s); using the "
                            "pure-Python codec", e)
            _lib_failed = True
    return _lib


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("snappy: truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 35:
            raise ValueError("snappy: varint too long")


def _write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decompress(data: bytes) -> bytes:
    if not data:
        return b""
    lib = _load()
    if lib is not None:
        want = lib.snappy_uncompressed_length(data, len(data))
        buf = ctypes.create_string_buffer(max(int(want), 1))
        got = lib.snappy_uncompress(data, len(data), buf, want)
        if got >= 0 and got == want:
            return buf.raw[:got]
        raise ValueError("snappy: corrupt input (native decoder)")
    return _py_decompress(data)


def _py_decompress(data: bytes) -> bytes:
    expected, pos = _read_varint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        elem_type = tag & 0x03
        if elem_type == 0x00:                       # literal
            length = (tag >> 2) + 1
            pos += 1
            if length > 60:
                extra = length - 60
                if pos + extra > n:
                    raise ValueError("snappy: truncated literal length")
                length = int.from_bytes(data[pos:pos + extra], "little") + 1
                pos += extra
            if pos + length > n:
                raise ValueError("snappy: truncated literal")
            out += data[pos:pos + length]
            pos += length
            continue
        if elem_type == 0x01:                       # copy, 1-byte offset
            length = ((tag >> 2) & 0x07) + 4
            if pos + 1 >= n:
                raise ValueError("snappy: truncated copy1")
            offset = ((tag >> 5) << 8) | data[pos + 1]
            pos += 2
        elif elem_type == 0x02:                     # copy, 2-byte offset
            length = (tag >> 2) + 1
            if pos + 2 >= n:
                raise ValueError("snappy: truncated copy2")
            offset = int.from_bytes(data[pos + 1:pos + 3], "little")
            pos += 3
        else:                                       # copy, 4-byte offset
            length = (tag >> 2) + 1
            if pos + 4 >= n:
                raise ValueError("snappy: truncated copy4")
            offset = int.from_bytes(data[pos + 1:pos + 5], "little")
            pos += 5
        if offset == 0 or offset > len(out):
            raise ValueError("snappy: invalid copy offset")
        start = len(out) - offset
        for i in range(length):                     # may self-overlap
            out.append(out[start + i])
    if len(out) != expected:
        raise ValueError(
            f"snappy: length mismatch ({len(out)} != {expected})")
    return bytes(out)


def compress(data: bytes) -> bytes:
    """Snappy compression (native hash-match codec when available)."""
    lib = _load()
    if lib is not None:
        cap = int(lib.snappy_max_compressed(len(data)))
        buf = ctypes.create_string_buffer(cap)
        got = lib.snappy_compress(data, len(data), buf)
        if got > 0 or not data:
            return buf.raw[:got]
    return _py_compress(data)


def _py_compress(data: bytes) -> bytes:
    """Literal-only snappy encoding (valid, uncompressed)."""
    out = bytearray(_write_varint(len(data)))
    pos = 0
    n = len(data)
    while pos < n:
        chunk = min(n - pos, 65536)
        if chunk <= 60:
            out.append((chunk - 1) << 2)
        else:
            extra = (chunk - 1).bit_length() + 7 >> 3
            out.append((59 + extra) << 2)
            out += (chunk - 1).to_bytes(extra, "little")
        out += data[pos:pos + chunk]
        pos += chunk
    return bytes(out)
