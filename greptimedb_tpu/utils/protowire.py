"""Minimal protobuf wire-format reader/writer.

Used for the Prometheus remote read/write bodies (prompb.WriteRequest /
ReadRequest / ReadResponse) without a protoc dependency — the message
shapes are tiny and stable (reference: src/servers/src/prometheus.rs works
from the same prompb definitions).
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Tuple


def read_varint(data: memoryview, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def iter_fields(data: memoryview) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over a message body."""
    pos = 0
    n = len(data)
    while pos < n:
        key, pos = read_varint(data, pos)
        field, wt = key >> 3, key & 0x07
        if wt == 0:                          # varint
            v, pos = read_varint(data, pos)
            yield field, wt, v
        elif wt == 1:                        # 64-bit
            v = bytes(data[pos:pos + 8])
            pos += 8
            yield field, wt, v
        elif wt == 2:                        # length-delimited
            ln, pos = read_varint(data, pos)
            v = data[pos:pos + ln]
            pos += ln
            yield field, wt, v
        elif wt == 5:                        # 32-bit
            v = bytes(data[pos:pos + 4])
            pos += 4
            yield field, wt, v
        else:
            raise ValueError(f"unsupported wire type {wt}")


def zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def field_bytes(field: int, payload: bytes) -> bytes:
    return write_varint((field << 3) | 2) + write_varint(len(payload)) + payload


def field_varint(field: int, value: int) -> bytes:
    if value < 0:
        value &= (1 << 64) - 1
    return write_varint(field << 3) + write_varint(value)


def field_double(field: int, value: float) -> bytes:
    return write_varint((field << 3) | 1) + struct.pack("<d", value)


def decode_double(raw: bytes) -> float:
    return struct.unpack("<d", raw)[0]


def decode_sint64(v: int) -> int:
    """Interpret a varint as two's-complement int64 (proto int64)."""
    if v >= 1 << 63:
        v -= 1 << 64
    return v
