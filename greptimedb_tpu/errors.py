"""Error taxonomy with status codes.

Reference behavior: src/common/error/src/{ext.rs,status_code.rs} — every
error carries a StatusCode so protocol servers can map it onto MySQL/PG/HTTP
error spaces uniformly.
"""

from __future__ import annotations

import enum


class StatusCode(enum.IntEnum):
    # Success
    SUCCESS = 0
    # Unknown / unexpected
    UNKNOWN = 1000
    UNSUPPORTED = 1001
    UNEXPECTED = 1002
    INTERNAL = 1003
    INVALID_ARGUMENTS = 1004
    # SQL
    INVALID_SYNTAX = 2000
    # Query
    PLAN_QUERY = 3000
    ENGINE_EXECUTE_QUERY = 3001
    # Catalog
    TABLE_ALREADY_EXISTS = 4000
    TABLE_NOT_FOUND = 4001
    TABLE_COLUMN_NOT_FOUND = 4002
    TABLE_COLUMN_EXISTS = 4003
    DATABASE_NOT_FOUND = 4004
    DATABASE_ALREADY_EXISTS = 4005
    # Storage
    STORAGE_UNAVAILABLE = 5000
    REGION_NOT_FOUND = 5001
    REGION_ALREADY_EXISTS = 5002
    # Server
    RUNTIME_RESOURCES_EXHAUSTED = 6000
    RATE_LIMITED = 6001
    # Auth
    USER_NOT_FOUND = 7000
    UNSUPPORTED_PASSWORD_TYPE = 7001
    USER_PASSWORD_MISMATCH = 7002
    AUTH_HEADER_NOT_FOUND = 7003
    INVALID_AUTH_HEADER = 7004
    ACCESS_DENIED = 7005


class GreptimeError(Exception):
    """Base error. Subclasses set `status_code`."""

    status_code: StatusCode = StatusCode.UNKNOWN

    def __init__(self, msg: str = "", *, cause: BaseException | None = None):
        super().__init__(msg)
        self.msg = msg
        if cause is not None:
            self.__cause__ = cause

    def __str__(self) -> str:  # pragma: no cover - trivial
        return self.msg or self.__class__.__name__

    def to_http_status(self) -> int:
        c = self.status_code
        if c in (StatusCode.USER_NOT_FOUND, StatusCode.USER_PASSWORD_MISMATCH,
                 StatusCode.AUTH_HEADER_NOT_FOUND, StatusCode.INVALID_AUTH_HEADER,
                 StatusCode.UNSUPPORTED_PASSWORD_TYPE):
            return 401
        if c == StatusCode.ACCESS_DENIED:
            return 403
        if c in (StatusCode.TABLE_NOT_FOUND, StatusCode.DATABASE_NOT_FOUND,
                 StatusCode.REGION_NOT_FOUND, StatusCode.TABLE_COLUMN_NOT_FOUND):
            return 404
        if c in (StatusCode.INVALID_SYNTAX, StatusCode.INVALID_ARGUMENTS,
                 StatusCode.TABLE_ALREADY_EXISTS, StatusCode.DATABASE_ALREADY_EXISTS,
                 StatusCode.TABLE_COLUMN_EXISTS):
            return 400
        if c == StatusCode.RATE_LIMITED:
            return 429
        return 500


class UnsupportedError(GreptimeError):
    status_code = StatusCode.UNSUPPORTED


class InternalError(GreptimeError):
    status_code = StatusCode.INTERNAL


class InvalidArgumentsError(GreptimeError):
    status_code = StatusCode.INVALID_ARGUMENTS


class SyntaxError_(GreptimeError):
    status_code = StatusCode.INVALID_SYNTAX


class PlanError(GreptimeError):
    status_code = StatusCode.PLAN_QUERY


class ExecutionError(GreptimeError):
    status_code = StatusCode.ENGINE_EXECUTE_QUERY


class TableAlreadyExistsError(GreptimeError):
    status_code = StatusCode.TABLE_ALREADY_EXISTS


class TableNotFoundError(GreptimeError):
    status_code = StatusCode.TABLE_NOT_FOUND


class ColumnNotFoundError(GreptimeError):
    status_code = StatusCode.TABLE_COLUMN_NOT_FOUND


class ColumnExistsError(GreptimeError):
    status_code = StatusCode.TABLE_COLUMN_EXISTS


class DatabaseNotFoundError(GreptimeError):
    status_code = StatusCode.DATABASE_NOT_FOUND


class DatabaseAlreadyExistsError(GreptimeError):
    status_code = StatusCode.DATABASE_ALREADY_EXISTS


class StorageError(GreptimeError):
    status_code = StatusCode.STORAGE_UNAVAILABLE


class SchedulerStoppedError(StorageError, RuntimeError):
    """Background scheduler rejected a submit because it is shutting
    down. Inherits RuntimeError so pre-taxonomy `except RuntimeError`
    shutdown paths keep degrading gracefully (skip the job; WAL/retry
    machinery covers the data)."""


class RegionClosedError(StorageError):
    """The region is closed on this node (shutdown, or a crashed node's
    in-process twin). To a distributed frontend this is a stale-route
    signal: the region either moved or is being failed over — refresh
    the route and retry, exactly like a dead peer's connection error
    over the wire."""


class RegionNotFoundError(GreptimeError):
    status_code = StatusCode.REGION_NOT_FOUND


class AuthError(GreptimeError):
    status_code = StatusCode.USER_PASSWORD_MISMATCH


class TransientRpcError(GreptimeError):
    """RPC failure a later identical attempt can plausibly outlive —
    connection refused/reset, deadline exceeded, server restarting.
    storage/retry.is_transient recognizes it, so the distributed
    fan-out's per-RPC retry covers real network hops, not just
    failpoint-injected faults."""

    status_code = StatusCode.STORAGE_UNAVAILABLE


class OverloadedError(GreptimeError):
    """The frontend's admission gate rejected new work: in-flight
    statements or queued ingest bytes are past the configured limits.
    Reject-with-retry-after, never collapse: HTTP maps it to 429 with a
    ``Retry-After`` header (`to_http_status` → RATE_LIMITED → 429),
    MySQL to a clean server-busy error (1040), Postgres to SQLSTATE
    53300. Carries the ``overloaded`` wire marker so Flight's
    string-flattened errors rebuild the type client-side."""

    status_code = StatusCode.RATE_LIMITED
    WIRE_MARKER = "server overloaded"

    def __init__(self, msg: str, *, retry_after_s: int = 1):
        if self.WIRE_MARKER not in msg:
            msg = f"{self.WIRE_MARKER}: {msg}"
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class QueryCancelledError(GreptimeError):
    """The statement was killed (`KILL <id>`): cooperative cancellation
    fired at a batch boundary in the streamed scan / scatter-gather
    loops. NOT transient — a retry would re-run the work the operator
    just killed."""

    status_code = StatusCode.ENGINE_EXECUTE_QUERY


class SketchCodecError(GreptimeError):
    """A sketch partial (HLL / t-digest frame from a datanode) failed to
    decode: corrupt, truncated, or version-skewed. The frontend counts
    ``greptime_sketch_degrade_total`` and retries the statement through
    the raw-row path — a bad partial must never become a wrong answer.
    NOT transient: the same partial would re-corrupt on a plain retry of
    the same RPC."""

    status_code = StatusCode.ENGINE_EXECUTE_QUERY


class StaleRouteError(GreptimeError):
    """The caller's region route is out of date: the region moved
    (migrate), was refined away (split), or is fenced for an in-flight
    handoff. The DistTable catches this, refreshes its route + partition
    rule from meta, and retries — so elastic region movement is
    invisible to SQL clients. Every message carries the ``stale route``
    marker because Flight flattens error types to strings on the wire
    (client/flight.py rebuilds the type from it)."""

    status_code = StatusCode.REGION_NOT_FOUND
    WIRE_MARKER = "stale route"

    def __init__(self, msg: str):
        if self.WIRE_MARKER not in msg:
            msg = f"{self.WIRE_MARKER}: {msg}"
        super().__init__(msg)
