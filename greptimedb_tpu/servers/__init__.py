"""Protocol servers.

Reference behavior: src/servers — HTTP (axum → aiohttp here), MySQL,
Postgres, gRPC/Flight, InfluxDB line protocol, OpenTSDB, Prometheus remote
read/write, with pluggable auth (src/servers/src/auth/) and per-protocol
handler traits implemented by the frontend.
"""
