"""Protocol-ingest coalescing: merge concurrent small writes into
shared bulk batches.

Reference behavior: the reference's per-protocol servers funnel tiny
Prometheus remote-write / InfluxDB line requests through one gRPC
insert plane where the region server batches them; our port did one
``handle_row_insert`` per request — at thousands of concurrent
remote-write streams that is one WAL record + one fsync wait + one
auto-create probe per 5-row body.

Mechanics (cooperative, no background thread — the FlowManager /
self-monitor tier-1 rule): requests for the same **(frontend, catalog,
schema, table, column-name signature)** land in one pending batch. The
first arrival is the *leader*: it sleeps the coalesce window (default
2 ms), closes the batch, concatenates the column lists, and runs ONE
``handle_row_insert`` for everyone. Followers park on the batch event
with a bounded wait + ``check_cancelled`` (the GL11 contract).

Per-request acks still reflect per-request durability and errors: a
follower returns only after the shared insert — WAL append + (group-
commit) fsync included — has covered its rows, and a shared-insert
failure surfaces to EVERY cohort member (none of their rows are
durable). Keying on the column signature means a request that would
need a different auto-create/alter shape never rides a stranger's
batch, so one bad request cannot poison unrelated acks.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common.locks import TrackedLock
from ..common.process_list import check_cancelled
from ..common.telemetry import increment_counter
from ..errors import GreptimeError, InternalError

#: hard bound on how long a follower parks for the leader's shared
#: insert before surfacing an error (never deadlock on a dead leader)
_FOLLOW_TIMEOUT_S = 30.0

from ..utils import env_flag as _env_flag, env_float as _env_float

_CFG_LOCK = TrackedLock("servers.coalesce_config")

_ENABLED = [_env_flag("GREPTIME_INGEST_COALESCE", True)]
_WINDOW_MS = [_env_float("GREPTIME_INGEST_COALESCE_WINDOW_MS", 2.0)]


def configure_coalescer(*, enabled: Optional[bool] = None,
                        window_ms: Optional[float] = None) -> None:
    """Process-wide knobs (SET ingest_coalesce /
    ingest_coalesce_window_ms; 0 ms behaves like off)."""
    with _CFG_LOCK:
        if enabled is not None:
            _ENABLED[0] = bool(enabled)
        if window_ms is not None:
            if window_ms < 0:
                raise ValueError("ingest_coalesce_window_ms must be >= 0")
            _WINDOW_MS[0] = float(window_ms)


def coalescer_settings() -> Tuple[bool, float]:
    with _CFG_LOCK:
        return _ENABLED[0], _WINDOW_MS[0]


class _Batch:
    """One open cohort of same-shape requests for one table."""

    __slots__ = ("requests", "done", "error")

    def __init__(self) -> None:
        self.requests: List[Dict[str, list]] = []
        self.done = threading.Event()
        self.error: Optional[BaseException] = None


class IngestCoalescer:
    """See module docstring. One instance per process (module-level
    ``COALESCER``), shared by every protocol server like the process
    registry is."""

    def __init__(self) -> None:
        from ..common.tracking import tracked_state
        self._lock = TrackedLock("servers.ingest_coalesce")
        self._pending: Dict[tuple, _Batch] = tracked_state(
            {}, "servers.coalesce.pending")

    def ingest(self, frontend, table: str, columns: Dict[str, list], *,
               tag_columns=(), timestamp_column: str, ctx,
               types: Optional[dict] = None) -> int:
        """Drop-in for ``frontend.handle_row_insert`` on protocol ingest
        paths; returns THIS request's row count once its rows are as
        durable as a solo insert would have made them."""
        n_rows = len(columns.get(timestamp_column, ()))
        enabled, window_ms = coalescer_settings()
        if not enabled or window_ms <= 0:
            return frontend.handle_row_insert(
                table, columns, tag_columns=tag_columns,
                timestamp_column=timestamp_column, types=types, ctx=ctx)
        key = (id(frontend), ctx.current_catalog, ctx.current_schema,
               table, tuple(sorted(columns)), tuple(tag_columns),
               timestamp_column)
        with self._lock:
            batch = self._pending.get(key)
            leader = batch is None
            if leader:
                batch = _Batch()
                self._pending[key] = batch
            batch.requests.append(columns)
        if leader:
            return self._lead(frontend, key, batch, table,
                              tag_columns=tag_columns,
                              timestamp_column=timestamp_column,
                              types=types, ctx=ctx, n_rows=n_rows,
                              window_ms=window_ms)
        return self._follow(batch, n_rows)

    # ---- leader: window → close → merge → one shared insert ----
    def _lead(self, frontend, key, batch: _Batch, table: str, *,
              tag_columns, timestamp_column, types, ctx, n_rows: int,
              window_ms: float) -> int:
        time.sleep(window_ms / 1e3)        # the accumulation window
        with self._lock:
            self._pending.pop(key, None)   # close: later arrivals re-key
            requests = list(batch.requests)
        try:
            merged = requests[0] if len(requests) == 1 else \
                _merge_requests(requests)
            frontend.handle_row_insert(
                table, merged, tag_columns=tag_columns,
                timestamp_column=timestamp_column, types=types, ctx=ctx)
        except BaseException as e:
            # the whole cohort's rows are un-durable: every member errors
            batch.error = e
            raise
        finally:
            batch.done.set()
        increment_counter("ingest_coalesce_batches")
        if len(requests) > 1:
            increment_counter("ingest_coalesce_merged_requests",
                              len(requests) - 1)
        return n_rows

    # ---- follower: bounded park on the leader's shared insert ----
    def _follow(self, batch: _Batch, n_rows: int) -> int:
        deadline = time.monotonic() + _FOLLOW_TIMEOUT_S
        while not batch.done.wait(timeout=0.05):
            check_cancelled()              # killed mid-wait: bail out
            if time.monotonic() > deadline:
                raise InternalError(
                    "coalesced ingest wait timed out (leader died?)")
        if batch.error is not None:
            raise _recast(batch.error)
        increment_counter("ingest_coalesce_follower_acks")
        return n_rows

    def pending_batches(self) -> int:
        with self._lock:
            return len(self._pending)


def _merge_requests(requests: List[Dict[str, list]]) -> Dict[str, list]:
    """Concatenate same-signature column dicts (the key guarantees every
    request carries exactly the same column names)."""
    merged: Dict[str, list] = {}
    for name in requests[0]:
        out: list = []
        for req in requests:
            out.extend(req[name])
        merged[name] = out
    return merged


def _recast(e: BaseException) -> GreptimeError:
    """A follower's copy of the cohort error: same taxonomy type where
    possible so protocol mappings (429, server-busy, 400...) hold for
    every member, not just the leader's request."""
    if isinstance(e, GreptimeError):
        try:
            return type(e)(str(e))
        except TypeError:
            return GreptimeError(str(e))
    return InternalError(f"coalesced ingest failed: {e}")


#: the process-wide coalescer every protocol server shares
COALESCER = IngestCoalescer()
