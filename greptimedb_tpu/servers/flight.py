"""Arrow Flight data plane: router↔worker and client↔server transport.

Reference behavior: src/servers/src/grpc/flight.rs:40-120 — the gRPC
service exposes Arrow Flight `do_get` carrying an encoded request ticket
and streams record batches back; src/client/src/database.rs:209-260 is the
matching client. Here the same plane is built directly on
`pyarrow.flight` (Flight *is* gRPC + Arrow IPC):

- `FlightDatanodeServer` wraps a `DatanodeInstance` and exposes the
  `DatanodeClient` surface over the wire: DDL actions, `do_put` region
  writes, `do_get` scans / pushed-down aggregate moments. This is the
  multi-host version of the in-process router↔worker calls
  (client/__init__.py).
- `FlightFrontendServer` wraps a frontend (standalone or distributed) and
  serves user SQL over `do_get` + gRPC-style row inserts with
  auto-create/alter over `do_put` (reference:
  src/frontend/src/instance.rs:292-342).

Tickets, descriptors and action bodies are JSON; data rides Arrow IPC.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Dict, Optional

import pyarrow as pa
import pyarrow.flight as flight

from ..common import exec_stats
from ..common.telemetry import (
    remote_context, slow_query_threshold_ms, span)
from ..datatypes.record_batch import RecordBatch
from ..datatypes.schema import Schema
from ..errors import GreptimeError
from ..table.requests import (
    CreateTableRequest, create_request_from_dict, create_request_to_dict)

_EMPTY_SCHEMA = pa.schema([])

#: wire key for the datanode-side ExecStats riding a response (stream
#: schema metadata on do_get, the JSON ack on do_put)
EXEC_STATS_KEY = exec_stats.EXEC_STATS_WIRE_KEY

#: same logger as the frontends' slow-query log, so one `grep trace=`
#: finds a slow distributed statement on every process it touched
_slow_logger = logging.getLogger("greptimedb_tpu.slow_query")


def _apply_wire_verdicts(body: dict) -> None:
    """Tail-sampling verdicts piggybacked on an inbound RPC body: pop
    the key (handlers must not see it) and release/discard the matching
    buffered traces on this process's sink."""
    from ..common import trace_store
    verdicts = body.pop(trace_store.TRACE_VERDICTS_BODY_KEY, None)
    sink = trace_store.sink()
    if sink is None or not isinstance(verdicts, dict) or not verdicts:
        return
    try:
        sink.apply_verdicts({str(k): bool(v)
                             for k, v in verdicts.items()})
    except Exception:  # noqa: BLE001 — advisory; never fail the RPC
        logging.getLogger(__name__).exception(
            "trace verdict application failed")


def _export_spans() -> list:
    """Retained spans awaiting the trip home — they ride this RPC's
    response to the frontend, which writes them into
    greptime_private.trace_spans."""
    from ..common import trace_store
    sink = trace_store.sink()
    return sink.take_export() if sink is not None else []


def _advertised_address(location: str, port: int) -> str:
    """Dialable address for peers: the bound host with the real port
    (port 0 in the location means OS-assigned)."""
    host = location.split("://", 1)[-1].rsplit(":", 1)[0] or "127.0.0.1"
    if host == "0.0.0.0":
        import socket
        host = socket.gethostbyname(socket.gethostname())
    return f"grpc://{host}:{port}"


# ---------------------------------------------------------------------------
# request codecs (JSON-safe)
# ---------------------------------------------------------------------------

def _arrow_to_columns(table: pa.Table) -> Dict[str, list]:
    return {name: table.column(i).to_pylist()
            for i, name in enumerate(table.schema.names)}


def _with_metadata(schema: pa.Schema,
                   metadata: Optional[Dict[bytes, bytes]]) -> pa.Schema:
    if not metadata:
        return schema
    merged = dict(schema.metadata or {})
    merged.update(metadata)
    return schema.with_metadata(merged)


def _frames_stream(frames, metadata: Optional[Dict[bytes, bytes]] = None
                   ) -> flight.GeneratorStream:
    """One moment frame = one IPC batch, so per-region frame boundaries
    survive the wire and the frontend fold sees the same units as the
    in-process path. `metadata` rides the stream schema (the datanode's
    ExecStats travel there)."""
    if not frames:
        return flight.GeneratorStream(
            _with_metadata(_EMPTY_SCHEMA, metadata), iter(()))
    schema0 = _with_metadata(
        pa.Schema.from_pandas(frames[0], preserve_index=False), metadata)

    def gen():
        for f in frames:
            t = pa.Table.from_pandas(f, schema=schema0,
                                     preserve_index=False)
            yield t.combine_chunks().to_batches(
                max_chunksize=max(1, len(f)))[0]
    return flight.GeneratorStream(schema0, gen())


def _batches_stream(batches, fallback_schema: Optional[Schema] = None,
                    metadata: Optional[Dict[bytes, bytes]] = None
                    ) -> flight.GeneratorStream:
    if not batches:
        schema = fallback_schema.to_arrow() if fallback_schema is not None \
            else _EMPTY_SCHEMA
        return flight.GeneratorStream(_with_metadata(schema, metadata),
                                      iter(()))
    schema = _with_metadata(batches[0].schema.to_arrow(), metadata)
    return flight.GeneratorStream(
        schema, (b.to_arrow() for b in batches))


_AFFECTED_SCHEMA = pa.schema([("affected_rows", pa.int64())],
                             metadata={b"gdb.kind": b"affected_rows"})


def _affected_stream(n: int,
                     proto_metadata: bool = False) -> flight.GeneratorStream:
    batch = pa.RecordBatch.from_arrays([pa.array([n], pa.int64())],
                                       schema=_AFFECTED_SCHEMA)
    if proto_metadata:
        # greptime-proto clients read the row count from
        # FlightData.app_metadata (FlightMetadata{affected_rows},
        # reference common/grpc/src/flight.rs:84-120)
        from ..api.v1 import encode_affected_rows_metadata
        meta = pa.py_buffer(encode_affected_rows_metadata(n))
        return flight.GeneratorStream(_AFFECTED_SCHEMA,
                                      iter([(batch, meta)]))
    return flight.GeneratorStream(_AFFECTED_SCHEMA, iter([batch]))


# ---------------------------------------------------------------------------
# datanode server (worker side of the distributed data plane)
# ---------------------------------------------------------------------------

class FlightDatanodeServer(flight.FlightServerBase):
    """Serves one datanode's region data plane over Arrow Flight."""

    def __init__(self, datanode, location: str = "grpc://127.0.0.1:0"):
        super().__init__(location)
        from ..client import LocalDatanodeClient
        self.datanode = datanode
        self.local = LocalDatanodeClient(datanode)
        self._location = location

    @property
    def address(self) -> str:
        return _advertised_address(self._location, self.port)

    def serve_in_background(self) -> threading.Thread:
        from ..common.runtime import new_thread
        t = new_thread(self.serve, daemon=True,
                       name=f"flight-dn{self.datanode.opts.node_id}",
                       propagate_context=False)
        t.start()
        return t

    # ---- control plane: DDL / flush / describe ----
    def do_action(self, context, action):
        body = json.loads(action.body.to_pybytes() or b"{}")
        kind = action.type
        _apply_wire_verdicts(body)
        # join the caller's trace before any handler work so DDL/flush
        # spans and logs carry the frontend's trace id
        with remote_context(body.pop("traceparent", None)), \
                span(f"dn_{kind}", node=self.datanode.opts.node_id):
            yield from self._do_action_inner(kind, body)

    def _do_action_inner(self, kind, body):
        try:
            if kind == "ddl_create_table":
                self.local.ddl_create_table(
                    create_request_from_dict(body["request"]))
                resp = {"ok": True}
            elif kind == "ddl_alter_table":
                from ..table.requests import alter_request_from_dict
                self.local.ddl_alter_table(
                    alter_request_from_dict(body["request"]))
                resp = {"ok": True}
            elif kind == "ddl_drop_table":
                dropped = self.local.ddl_drop_table(
                    body["catalog"], body["schema"], body["table"])
                resp = {"ok": True, "dropped": bool(dropped)}
            elif kind == "flush_table":
                self.local.flush_table(body["catalog"], body["schema"],
                                       body["table"])
                resp = {"ok": True}
            elif kind == "describe_table":
                described = self.local.describe_table(
                    body["catalog"], body["schema"], body["table"])
                if described is None:
                    resp = {"ok": True, "info": None}
                else:
                    info, _rule = described
                    resp = {"ok": True, "info": info.to_dict()}
            elif kind == "ping":
                resp = {"ok": True, "node_id": self.datanode.opts.node_id}
            elif kind == "repl_apply":
                # continuous replication consumer: apply shipped WAL
                # records to this node's standby replica of the region
                applied = self.local.repl_apply(
                    body["catalog"], body["schema"], body["table"],
                    int(body["region_number"]),
                    list(body.get("entries") or []),
                    leader_flushed=int(body.get("leader_flushed") or 0))
                resp = {"ok": True, **applied}
            elif kind == "background_jobs":
                # live + recent background work on THIS node, for the
                # frontend's cluster-merged information_schema view
                from ..common import background_jobs
                resp = {"ok": True, "jobs": background_jobs.rows()}
            elif kind == "profile":
                # continuous profiler, datanode side: writer-less
                # sampler — {"drain": true} hands the pending aggregate
                # to the frontend (which owns the flush), {"seconds":
                # N[, "hz": h]} runs a high-rate burst for /debug/prof
                from ..common import profiler
                s = profiler.sampler()
                if s is None:
                    resp = {"ok": True, "rows": []}
                elif body.get("seconds") is not None:
                    resp = {"ok": True, "rows": s.collect_burst(
                        float(body["seconds"]),
                        burst_hz=body.get("hz"))}
                else:
                    resp = {"ok": True, "rows": s.drain_rows()}
            else:
                raise GreptimeError(f"unknown action {kind!r}")
        except GreptimeError as e:
            resp = {"ok": False, "error": str(e),
                    "error_type": type(e).__name__}
        exported = _export_spans()
        if exported:
            resp["trace_spans"] = exported
        yield flight.Result(json.dumps(resp).encode())

    # ---- write plane ----
    def do_put(self, context, descriptor, reader, writer):
        cmd = json.loads(descriptor.command)
        if cmd.get("type") != "write_region":
            raise GreptimeError(f"unsupported put {cmd.get('type')!r}")
        _apply_wire_verdicts(cmd)
        stats = exec_stats.ExecStats()
        t0 = time.perf_counter()
        with remote_context(cmd.get("traceparent")), \
                span("dn_write_region", node=self.datanode.opts.node_id,
                     table=cmd.get("table")) as sp, \
                exec_stats.collect(stats):
            tbl = reader.read_all()
            op = cmd.get("op", "put")
            target = self.datanode.catalog.table(
                cmd["catalog"], cmd["schema"], cmd["table"]) \
                if op == "bulk" else None
            if target is not None:
                # bulk path: typed ndarray columns feed bulk_ingest's raw
                # fast path instead of a per-value pylist round trip
                from ..datatypes.record_batch import arrow_to_ingest_columns
                columns = arrow_to_ingest_columns(tbl, target.schema)
            else:
                columns = _arrow_to_columns(tbl)
            n = self.local.write_region(
                cmd["catalog"], cmd["schema"], cmd["table"],
                cmd["region_number"], columns, op=op)
        self._log_slow(sp, "write_region", cmd,
                       (time.perf_counter() - t0) * 1e3, stats)
        ack = {"affected_rows": n, "exec_stats": stats.to_dict()}
        exported = _export_spans()
        if exported:
            ack["trace_spans"] = exported
        writer.write(pa.py_buffer(json.dumps(ack).encode()))

    def _log_slow(self, sp, what: str, cmd: dict, elapsed_ms: float,
                  stats: exec_stats.ExecStats) -> None:
        """Datanode-side slow-op log: after wire trace propagation this
        reports the SAME trace id as the frontend's slow-query entry for
        the statement that caused the RPC."""
        thr = slow_query_threshold_ms()
        if thr is None or elapsed_ms < thr:
            return
        _slow_logger.warning(
            "slow datanode op: %s %.1fms (threshold %dms) trace=%s "
            "node=%d table=%s stats=[%s]", what, elapsed_ms, thr,
            sp["trace_id"], self.datanode.opts.node_id,
            cmd.get("table"), stats.summary())

    # ---- read plane ----
    def do_get(self, context, ticket):
        cmd = json.loads(ticket.ticket)
        kind = cmd.get("type")
        if kind not in ("scan", "region_moments"):
            raise GreptimeError(f"unsupported ticket {kind!r}")
        _apply_wire_verdicts(cmd)
        # the scan executes eagerly under a local collector; its stats
        # ride the stream schema back so the frontend can render this
        # node's stage rows in its EXPLAIN ANALYZE tree
        stats = exec_stats.ExecStats()
        t0 = time.perf_counter()
        with remote_context(cmd.get("traceparent")), \
                span(f"dn_{kind}", node=self.datanode.opts.node_id,
                     table=cmd.get("table")) as sp, \
                exec_stats.collect(stats):
            if kind == "scan":
                batches, fallback = self._do_scan(cmd)
            else:
                frames = self._do_region_moments(cmd)
        self._log_slow(sp, kind, cmd, (time.perf_counter() - t0) * 1e3,
                       stats)
        metadata = {EXEC_STATS_KEY: json.dumps(stats.to_dict()).encode()}
        exported = _export_spans()
        if exported:
            from ..common.trace_store import TRACE_SPANS_WIRE_KEY
            metadata[TRACE_SPANS_WIRE_KEY] = \
                json.dumps(exported).encode()
        if kind == "scan":
            return _batches_stream(batches, fallback, metadata=metadata)
        return _frames_stream(frames, metadata=metadata)

    def _do_scan(self, cmd):
        from ..common.time import TimestampRange
        from ..query.plan_codec import expr_from_dict
        filters = [expr_from_dict(f) for f in cmd["filters"]] \
            if cmd.get("filters") else None
        # rebuild a real TimestampRange: Region.scan dereferences
        # .start/.end, so the wire's [lo, hi] pair must not stay a
        # tuple (ranges ship in ms, the region-native unit)
        time_range = None
        if cmd.get("time_range"):
            lo, hi = cmd["time_range"]
            time_range = TimestampRange(lo, hi)
        # self.local (a LocalDatanodeClient) records the "scan" stage
        batches = self.local.scan_batches(
            cmd["catalog"], cmd["schema"], cmd["table"],
            projection=cmd.get("projection"),
            time_range=time_range,
            limit=cmd.get("limit"), filters=filters,
            regions=cmd.get("regions"))
        t = self.datanode.catalog.table(
            cmd["catalog"], cmd["schema"], cmd["table"])
        fallback = None
        if t is not None:
            fallback = t.schema if cmd.get("projection") is None \
                else t.schema.project(cmd["projection"])
        return batches, fallback

    def _do_region_moments(self, cmd):
        from ..query.plan_codec import plan_from_dict
        return self.local.region_moments(
            cmd["catalog"], cmd["schema"], cmd["table"],
            plan_from_dict(cmd["plan"]), regions=cmd.get("regions"))


# ---------------------------------------------------------------------------
# frontend server (user-facing SQL-over-Flight, the reference's
# GreptimeService + FlightService pair)
# ---------------------------------------------------------------------------

class FlightFrontendServer(flight.FlightServerBase):
    def __init__(self, frontend, location: str = "grpc://127.0.0.1:0"):
        super().__init__(location)
        self.frontend = frontend
        self._location = location

    @property
    def address(self) -> str:
        return _advertised_address(self._location, self.port)

    def serve_in_background(self) -> threading.Thread:
        from ..common.runtime import new_thread
        t = new_thread(self.serve, daemon=True, name="flight-frontend",
                       propagate_context=False)
        t.start()
        return t

    def do_get(self, context, ticket):
        raw = ticket.ticket
        try:
            cmd = json.loads(raw)
        except (ValueError, UnicodeDecodeError):
            # greptime-proto plane: reference SDKs serialize a
            # GreptimeRequest protobuf into the ticket
            # (src/client/src/database.rs:209-231, decoded by the
            # server at src/servers/src/grpc/flight.rs:87-96)
            return self._do_get_proto(raw)
        if cmd.get("type") != "sql":
            raise GreptimeError(f"unsupported ticket {cmd.get('type')!r}")
        with remote_context(cmd.get("traceparent")):
            outputs = self.frontend.do_query(cmd["sql"])
        last = outputs[-1]
        if last.is_batches:
            return _batches_stream(last.batches)
        return _affected_stream(last.affected_rows or 0)

    def _do_get_proto(self, raw: bytes):
        from ..api import v1 as proto
        req = proto.decode_greptime_request(bytes(raw))
        ctx = self._proto_ctx(req)
        if req.query is not None and req.query.sql is not None:
            outputs = self.frontend.do_query(req.query.sql, ctx)
            last = outputs[-1]
            if last.is_batches:
                return _batches_stream(last.batches)
            return _affected_stream(last.affected_rows or 0,
                                    proto_metadata=True)
        if req.insert is not None:
            n = self._apply_proto_insert(req.insert, ctx)
            return _affected_stream(n, proto_metadata=True)
        if req.ddl is not None:
            return self._apply_proto_ddl(req.ddl, ctx)
        what = req.other or "empty"
        raise GreptimeError(
            f"unsupported GreptimeRequest variant {what!r} on do_get "
            "(use SQL DDL over the query plane)")

    @staticmethod
    def _proto_ctx(req):
        """RequestHeader catalog/schema/dbname → QueryContext (reference:
        every handler resolves names through the header's context,
        src/servers/src/grpc/handler.rs). dbname may carry
        'catalog-schema' form."""
        from ..session import QueryContext
        ctx = QueryContext()
        catalog, schema = req.catalog, req.schema
        if req.dbname:
            if "-" in req.dbname:
                catalog, _, schema = req.dbname.partition("-")
            else:
                schema = req.dbname
        if catalog:
            ctx.current_catalog = catalog
        if schema:
            ctx.current_schema = schema
        return ctx

    def _apply_proto_ddl(self, ddl, ctx):
        from ..api.v1 import create_table_to_sql
        if ddl.create_table is not None:
            sql = create_table_to_sql(ddl.create_table)
        elif ddl.drop_table is not None:
            sql = f'DROP TABLE "{ddl.drop_table[2]}"'
        elif ddl.create_database is not None:
            sql = f'CREATE DATABASE "{ddl.create_database}"'
        else:
            raise GreptimeError(
                f"unsupported DdlRequest variant {ddl.other!r}")
        outputs = self.frontend.do_query(sql, ctx)
        return _affected_stream(outputs[-1].affected_rows or 0,
                                proto_metadata=True)

    def _apply_proto_insert(self, ins, ctx) -> int:
        from ..api.v1 import SemanticType
        columns = {}
        tag_columns = []
        timestamp_column = "greptime_timestamp"
        for c in ins.columns:
            columns[c.column_name] = c.rows(ins.row_count)
            if c.semantic_type == SemanticType.TAG:
                tag_columns.append(c.column_name)
            elif c.semantic_type == SemanticType.TIMESTAMP:
                timestamp_column = c.column_name
        return self.frontend.handle_row_insert(
            ins.table_name, columns, tag_columns=tag_columns,
            timestamp_column=timestamp_column, ctx=ctx)

    def do_put(self, context, descriptor, reader, writer):
        cmd = json.loads(descriptor.command)
        kind = cmd.get("type")
        # same contract as do_get's ticket: the descriptor command may
        # carry the writer's W3C traceparent, so bulk writes stitch onto
        # the client's trace like queries do
        with remote_context(cmd.get("traceparent")):
            self._do_put_cmd(cmd, kind, reader, writer)

    def _do_put_cmd(self, cmd, kind, reader, writer):
        if kind == "row_insert":
            columns = _arrow_to_columns(reader.read_all())
            n = self.frontend.handle_row_insert(
                cmd["table"], columns,
                tag_columns=cmd.get("tag_columns", ()),
                timestamp_column=cmd.get("timestamp_column",
                                         "greptime_timestamp"))
        elif kind == "bulk_load":
            # WAL-less bulk path: keep columns arrow→ndarray end to end
            # when the table already exists (the bulk_ingest raw fast
            # path); fall back to python lists for auto-create inference
            from ..datatypes.record_batch import arrow_to_ingest_columns
            tbl = reader.read_all()
            from ..session import QueryContext
            ctx = QueryContext()
            target = self.frontend.catalog.table(
                ctx.current_catalog, ctx.current_schema, cmd["table"])
            columns = _arrow_to_columns(tbl) if target is None else \
                arrow_to_ingest_columns(tbl, target.schema, extra="keep")
            n = self.frontend.handle_bulk_load(
                cmd["table"], columns,
                tag_columns=cmd.get("tag_columns", ()),
                timestamp_column=cmd.get("timestamp_column",
                                         "greptime_timestamp"), ctx=ctx)
        else:
            raise GreptimeError(f"unsupported put {kind!r}")
        writer.write(pa.py_buffer(
            json.dumps({"affected_rows": n}).encode()))
