"""Pluggable authentication.

Reference behavior: src/servers/src/auth/user_provider.rs:290 — a
`UserProvider` resolving username/password, configured either from a static
option (`user=pwd`) or a htpasswd-style file.
"""

from __future__ import annotations

import base64
import hmac
from typing import Dict, Optional

from ..errors import AuthError


class UserProvider:
    #: wire protocols ask the client for credentials only when true
    requires_password = True

    def authenticate(self, username: str, password: str) -> bool:
        raise NotImplementedError

    def plain_password(self, username: str) -> Optional[str]:
        """Plaintext lookup for challenge-response schemes
        (mysql_native_password / postgres md5); None = unknown user."""
        return None

    def auth_http_basic(self, header: Optional[str]) -> str:
        """Validate an Authorization: Basic header; returns the username."""
        if not header or not header.lower().startswith("basic "):
            raise AuthError("missing basic auth")
        try:
            raw = base64.b64decode(header.split(" ", 1)[1]).decode()
            username, _, password = raw.partition(":")
        except Exception as e:
            raise AuthError("malformed basic auth") from e
        if not self.authenticate(username, password):
            raise AuthError("bad username or password")
        return username


class StaticUserProvider(UserProvider):
    """static_user_provider=cmd:user=pwd / file:path (reference syntax)."""

    def __init__(self, users: Dict[str, str]):
        self.users = dict(users)

    @staticmethod
    def from_option(option: str) -> "StaticUserProvider":
        kind, _, rest = option.partition(":")
        users: Dict[str, str] = {}
        if kind == "cmd":
            for pair in rest.split(","):
                name, _, pwd = pair.partition("=")
                if not name or not pwd:
                    raise ValueError(f"bad user option {pair!r}")
                users[name] = pwd
        elif kind == "file":
            with open(rest) as f:
                for line in f:
                    line = line.strip()
                    if not line or line.startswith("#"):
                        continue
                    name, _, pwd = line.partition("=")
                    users[name] = pwd
        else:
            raise ValueError(f"unknown user provider kind {kind!r}")
        return StaticUserProvider(users)

    def authenticate(self, username: str, password: str) -> bool:
        expected = self.users.get(username)
        if expected is None:
            return False
        return hmac.compare_digest(expected.encode(), password.encode())

    def plain_password(self, username: str) -> Optional[str]:
        return self.users.get(username)


class NoopUserProvider(UserProvider):
    requires_password = False

    def authenticate(self, username: str, password: str) -> bool:
        return True

    def auth_http_basic(self, header: Optional[str]) -> str:
        return "greptime"
