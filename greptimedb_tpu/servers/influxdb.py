"""InfluxDB line-protocol ingestion.

Reference behavior: src/servers/src/influxdb.rs + line_writer.rs — parse
`measurement[,tag=v] field=v[,f2=v2] [timestamp]` lines, group by
measurement, insert with auto create/alter. Timestamps arrive at a caller
precision (default ns) and are stored as ms.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ..errors import InvalidArgumentsError

PRECISION_MS = {"n": 1e-6, "ns": 1e-6, "u": 1e-3, "us": 1e-3,
                "ms": 1.0, "s": 1e3, "m": 6e4, "h": 3.6e6}

GREPTIME_TIMESTAMP = "greptime_timestamp"


def _split_escaped(s: str, sep: str, escapable: str) -> List[str]:
    out = []
    cur = []
    i = 0
    in_quote = False
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s) and s[i + 1] in escapable + '\\"':
            cur.append(s[i + 1])
            i += 2
            continue
        if c == '"':
            in_quote = not in_quote
            cur.append(c)
            i += 1
            continue
        if c == sep and not in_quote:
            out.append("".join(cur))
            cur = []
            i += 1
            continue
        cur.append(c)
        i += 1
    out.append("".join(cur))
    return out


def _parse_field_value(raw: str):
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        return raw[1:-1]
    if raw.endswith(("i", "u")) and raw[:-1].lstrip("+-").isdigit():
        return int(raw[:-1])
    low = raw.lower()
    if low in ("t", "true"):
        return True
    if low in ("f", "false"):
        return False
    try:
        return float(raw)
    except ValueError as e:
        raise InvalidArgumentsError(f"bad field value {raw!r}") from e


def parse_lines(body: str, precision: str = "ns"
                ) -> List[Tuple[str, Dict[str, object], Dict[str, object],
                                int]]:
    """→ [(measurement, tags, fields, ts_ms)]"""
    scale = PRECISION_MS.get(precision)
    if scale is None:
        raise InvalidArgumentsError(f"bad precision {precision!r}")
    now = int(time.time() * 1000)
    out = []
    for line in body.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = _split_escaped(line, " ", ", ")
        parts = [p for p in parts if p != ""]
        if len(parts) < 2:
            raise InvalidArgumentsError(f"bad line: {line!r}")
        head = _split_escaped(parts[0], ",", " ,=")
        measurement = head[0]
        if not measurement:
            raise InvalidArgumentsError(f"missing measurement: {line!r}")
        tags: Dict[str, object] = {}
        for kv in head[1:]:
            k, _, v = kv.partition("=")
            tags[k] = v
        fields: Dict[str, object] = {}
        for kv in _split_escaped(parts[1], ",", " ,="):
            k, _, v = kv.partition("=")
            if not k or not v:
                raise InvalidArgumentsError(f"bad field {kv!r} in {line!r}")
            fields[k] = _parse_field_value(v)
        if len(parts) >= 3:
            ts_ms = int(int(parts[2]) * scale)
        else:
            ts_ms = now
        out.append((measurement, tags, fields, ts_ms))
    return out


def body_to_inserts(body: str, precision: str = "ns"):
    """Line-protocol body → (per-measurement column dicts, per-
    measurement tag names) — the one-call shape the HTTP handler and
    the ingest coalescer share."""
    return lines_to_inserts(parse_lines(body, precision))


def lines_to_inserts(parsed) -> Dict[str, Dict[str, list]]:
    """Group parsed points per measurement into column dicts with aligned
    rows (missing tags/fields → None)."""
    by_table: Dict[str, List] = {}
    for m, tags, fields, ts in parsed:
        by_table.setdefault(m, []).append((tags, fields, ts))
    result = {}
    tag_cols_by_table = {}
    for m, rows in by_table.items():
        tag_names = sorted({k for tags, _, _ in rows for k in tags})
        field_names = sorted({k for _, fields, _ in rows for k in fields})
        cols: Dict[str, list] = {GREPTIME_TIMESTAMP: []}
        for t in tag_names:
            cols[t] = []
        for f in field_names:
            cols[f] = []
        for tags, fields, ts in rows:
            cols[GREPTIME_TIMESTAMP].append(ts)
            for t in tag_names:
                cols[t].append(tags.get(t, ""))
            for f in field_names:
                cols[f].append(fields.get(f))
        result[m] = cols
        tag_cols_by_table[m] = tag_names
    return result, tag_cols_by_table
