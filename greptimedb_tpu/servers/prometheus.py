"""Prometheus remote write / remote read.

Reference behavior: src/servers/src/prometheus.rs:286-373 — remote write
decodes snappy+prompb.WriteRequest into per-metric inserts (one table per
`__name__`, labels→tags, greptime_timestamp/greptime_value); remote read
runs time-range + matcher scans and re-encodes prompb.ReadResponse.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..utils import protowire as pw
from ..utils.snappy import compress, decompress

METRIC_NAME_LABEL = "__name__"
GREPTIME_TIMESTAMP = "greptime_timestamp"
GREPTIME_VALUE = "greptime_value"

# prompb.LabelMatcher.Type
MATCH_EQ, MATCH_NEQ, MATCH_RE, MATCH_NRE = 0, 1, 2, 3


@dataclass
class TimeSeries:
    labels: Dict[str, str] = field(default_factory=dict)
    samples: List[Tuple[float, int]] = field(default_factory=list)  # (v, ts)


def decode_write_request(body: bytes) -> List[TimeSeries]:
    raw = memoryview(decompress(body))
    series: List[TimeSeries] = []
    for fnum, wt, val in pw.iter_fields(raw):
        if fnum == 1 and wt == 2:                    # timeseries
            ts = TimeSeries()
            for f2, w2, v2 in pw.iter_fields(val):
                if f2 == 1 and w2 == 2:              # label
                    name = value = ""
                    for f3, w3, v3 in pw.iter_fields(v2):
                        if f3 == 1:
                            name = bytes(v3).decode()
                        elif f3 == 2:
                            value = bytes(v3).decode()
                    ts.labels[name] = value
                elif f2 == 2 and w2 == 2:            # sample
                    sval, sts = 0.0, 0
                    for f3, w3, v3 in pw.iter_fields(v2):
                        if f3 == 1 and w3 == 1:
                            sval = pw.decode_double(v3)
                        elif f3 == 2 and w3 == 0:
                            sts = pw.decode_sint64(v3)
                    ts.samples.append((sval, sts))
            series.append(ts)
    return series


def series_to_inserts(series: List[TimeSeries]):
    """Group samples per metric table (reference: prometheus.rs to_grpc_insert
    shape: labels→tags + ts + value)."""
    by_metric: Dict[str, List[TimeSeries]] = {}
    for ts in series:
        name = ts.labels.get(METRIC_NAME_LABEL)
        if not name:
            continue
        by_metric.setdefault(name, []).append(ts)
    result = {}
    tag_cols = {}
    for metric, sl in by_metric.items():
        tag_names = sorted({k for s in sl for k in s.labels
                            if k != METRIC_NAME_LABEL})
        cols: Dict[str, list] = {GREPTIME_TIMESTAMP: [],
                                 GREPTIME_VALUE: []}
        for t in tag_names:
            cols[t] = []
        for s in sl:
            for v, t_ms in s.samples:
                cols[GREPTIME_TIMESTAMP].append(t_ms)
                cols[GREPTIME_VALUE].append(v)
                for t in tag_names:
                    cols[t].append(s.labels.get(t, ""))
        result[metric] = cols
        tag_cols[metric] = tag_names
    return result, tag_cols


def write_request_to_inserts(body: bytes):
    """snappy prompb.WriteRequest body → (per-metric column dicts,
    per-metric tag names) — the one-call shape the HTTP handler and the
    ingest coalescer share."""
    return series_to_inserts(decode_write_request(body))


@dataclass
class Matcher:
    type: int
    name: str
    value: str

    def matches(self, v: str) -> bool:
        if self.type == MATCH_EQ:
            return v == self.value
        if self.type == MATCH_NEQ:
            return v != self.value
        if self.type == MATCH_RE:
            return re.fullmatch(self.value, v) is not None
        return re.fullmatch(self.value, v) is None


@dataclass
class ReadQuery:
    start_ms: int
    end_ms: int
    matchers: List[Matcher] = field(default_factory=list)

    def metric_name(self) -> Optional[str]:
        for m in self.matchers:
            if m.name == METRIC_NAME_LABEL and m.type == MATCH_EQ:
                return m.value
        return None


def decode_read_request(body: bytes) -> List[ReadQuery]:
    raw = memoryview(decompress(body))
    queries: List[ReadQuery] = []
    for fnum, wt, val in pw.iter_fields(raw):
        if fnum == 1 and wt == 2:                    # query
            q = ReadQuery(0, 0)
            for f2, w2, v2 in pw.iter_fields(val):
                if f2 == 1 and w2 == 0:
                    q.start_ms = pw.decode_sint64(v2)
                elif f2 == 2 and w2 == 0:
                    q.end_ms = pw.decode_sint64(v2)
                elif f2 == 3 and w2 == 2:
                    mt, name, value = 0, "", ""
                    for f3, w3, v3 in pw.iter_fields(v2):
                        if f3 == 1 and w3 == 0:
                            mt = v3
                        elif f3 == 2:
                            name = bytes(v3).decode()
                        elif f3 == 3:
                            value = bytes(v3).decode()
                    q.matchers.append(Matcher(mt, name, value))
            queries.append(q)
    return queries


def encode_read_response(results: List[List[TimeSeries]]) -> bytes:
    """results: one list of TimeSeries per query → snappy(prompb)."""
    body = bytearray()
    for series in results:
        qr = bytearray()
        for s in series:
            ts_msg = bytearray()
            for name, value in sorted(s.labels.items()):
                lbl = pw.field_bytes(1, name.encode()) + \
                    pw.field_bytes(2, value.encode())
                ts_msg += pw.field_bytes(1, lbl)
            for v, t_ms in s.samples:
                sample = pw.field_double(1, v) + pw.field_varint(2, t_ms)
                ts_msg += pw.field_bytes(2, sample)
            qr += pw.field_bytes(1, bytes(ts_msg))
        body += pw.field_bytes(1, bytes(qr))
    return compress(bytes(body))


def encode_write_request(series: List[TimeSeries]) -> bytes:
    """Build a snappy prompb.WriteRequest (test/client helper)."""
    body = bytearray()
    for s in series:
        ts_msg = bytearray()
        for name, value in s.labels.items():
            lbl = pw.field_bytes(1, name.encode()) + \
                pw.field_bytes(2, value.encode())
            ts_msg += pw.field_bytes(1, lbl)
        for v, t_ms in s.samples:
            sample = pw.field_double(1, v) + pw.field_varint(2, t_ms)
            ts_msg += pw.field_bytes(2, sample)
        body += pw.field_bytes(1, bytes(ts_msg))
    return compress(bytes(body))
