"""Prometheus-compatible HTTP API (/api/v1/*).

Reference behavior: src/servers/src/prom.rs — instant/range queries
returning Prometheus JSON, plus labels / series / label values metadata
endpoints. Query evaluation delegates to the PromQL engine.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List

from aiohttp import web

from ..common.time import parse_prom_duration, parse_prom_time
from ..errors import GreptimeError


def _error(typ: str, msg: str, status=400):
    return web.json_response(
        {"status": "error", "errorType": typ, "error": msg}, status=status)


async def _eval(server, request, *, instant: bool):
    ctx = server._ctx(request)
    query = await server._param(request, "query")
    if not query:
        return _error("bad_data", "missing query")
    try:
        if instant:
            t = parse_prom_time(await server._param(request, "time"),
                                default=time.time())
            start_ms = end_ms = t
            step_ms = 1000
        else:
            start_ms = parse_prom_time(await server._param(request, "start"))
            end_ms = parse_prom_time(await server._param(request, "end"))
            step_raw = await server._param(request, "step")
            if start_ms is None or end_ms is None or not step_raw:
                return _error("bad_data", "start/end/step are required")
            step_ms = parse_prom_duration(step_raw)
        engine = server.frontend.promql_engine()
        loop = asyncio.get_running_loop()
        explain = (await server._param(request, "explain")) in (
            "1", "true", "yes")
        if explain:
            # ?explain=1: render the plan the way SQL's EXPLAIN does —
            # the Prom expression tree plus the IR node each aggregate
            # lowered to (TpuAggregateExec / RawScan) and its dispatch
            lines = await loop.run_in_executor(
                None, lambda: engine.explain_lines(
                    query, start_ms, end_ms, step_ms, ctx))
            return web.json_response(
                {"status": "success",
                 "data": {"resultType": "explain", "result": lines}})
        result = await loop.run_in_executor(
            None, lambda: engine.query_to_prom_json(
                query, start_ms, end_ms, step_ms, ctx, instant=instant))
        return web.json_response({"status": "success", "data": result})
    except GreptimeError as e:
        return _error("execution", str(e), status=422)


async def instant_query(server, request):
    return await _eval(server, request, instant=True)


async def range_query(server, request):
    return await _eval(server, request, instant=False)


def _match_tables(server, request, ctx) -> List[str]:
    matches = request.query.getall("match[]", [])
    names = server.frontend.catalog.table_names(
        ctx.current_catalog, ctx.current_schema)
    if not matches:
        return names
    out = []
    for m in matches:
        name = m.split("{", 1)[0].strip()
        if name and name in names:
            out.append(name)
    return out


async def labels_query(server, request):
    ctx = server._ctx(request)
    labels = {"__name__"}
    for name in _match_tables(server, request, ctx):
        t = server.frontend.catalog.table(ctx.current_catalog,
                                          ctx.current_schema, name)
        if t is not None:
            labels.update(t.schema.tag_names())
    return web.json_response({"status": "success", "data": sorted(labels)})


async def label_values_query(server, request):
    ctx = server._ctx(request)
    label = request.match_info["name"]
    values = set()
    if label == "__name__":
        values.update(_match_tables(server, request, ctx))
    else:
        for name in _match_tables(server, request, ctx):
            t = server.frontend.catalog.table(ctx.current_catalog,
                                              ctx.current_schema, name)
            if t is None or label not in t.schema.tag_names():
                continue
            idx = t.schema.tag_names().index(label)
            for region in getattr(t, "regions", {}).values():
                sd = region.series_dict
                import numpy as np
                ids = np.arange(sd.num_series, dtype=np.int32)
                values.update(str(v) for v in sd.decode_tag_column(ids, idx))
    return web.json_response({"status": "success", "data": sorted(values)})


async def series_query(server, request):
    ctx = server._ctx(request)
    out: List[Dict[str, str]] = []
    for name in _match_tables(server, request, ctx):
        t = server.frontend.catalog.table(ctx.current_catalog,
                                          ctx.current_schema, name)
        if t is None or not hasattr(t, "regions"):
            continue
        tag_names = t.schema.tag_names()
        import numpy as np
        for region in t.regions.values():
            sd = region.series_dict
            ids = np.arange(sd.num_series, dtype=np.int32)
            cols = [sd.decode_tag_column(ids, i)
                    for i in range(len(tag_names))]
            for row in range(sd.num_series):
                entry = {"__name__": name}
                for i, tn in enumerate(tag_names):
                    entry[tn] = str(cols[i][row])
                out.append(entry)
    return web.json_response({"status": "success", "data": out})
