"""PostgreSQL wire-protocol (v3) server.

Reference behavior: src/servers/src/postgres/ — pgwire-based startup/auth
handling (auth_handler.rs:250) and simple + extended query support
(handler.rs:648). Implemented directly on the v3 message format: startup /
SSLRequest negotiation, cleartext-password auth against the shared
`UserProvider`, simple query ('Q'), and the extended Parse/Bind/Describe/
Execute/Sync flow with text-format parameters. Every SQL string funnels
into the same frontend `do_query` as the other protocols.
"""

from __future__ import annotations

import logging
import socket
import socketserver
import ssl as ssl_mod
import struct
import threading
from typing import Dict, List, Optional, Tuple

from ..errors import GreptimeError
from ..session import Channel, QueryContext

logger = logging.getLogger(__name__)

PROTOCOL_V3 = 196608
SSL_REQUEST = 80877103
CANCEL_REQUEST = 80877102

OID_BOOL, OID_INT8, OID_TEXT, OID_FLOAT8, OID_TIMESTAMP = 16, 20, 25, 701, 1114


def _pg_oid(dtype) -> int:
    if dtype.is_timestamp:
        return OID_TIMESTAMP
    if dtype.is_string:
        return OID_TEXT
    if dtype.is_float:
        return OID_FLOAT8
    if dtype.is_boolean:
        return OID_BOOL
    return OID_INT8


#: microseconds between the PG epoch (2000-01-01) and the Unix epoch
_PG_EPOCH_US = 946_684_800_000_000


def _decode_binary_param(raw: bytes, oid: int) -> str:
    """Binary-format Bind parameter → the text form the $N substitution
    consumes (reference pgwire accepts both formats, handler.rs:648).
    Decoding keys off the Parse-declared OID; length disambiguates when
    the driver declared none."""
    n = len(raw)
    if oid in (21, 23, 20):                                    # int2/4/8
        return str(int.from_bytes(raw, "big", signed=True))
    if oid == 700 and n == 4:                                  # float4
        return repr(struct.unpack("!f", raw)[0])
    if oid == 701 and n == 8:                                  # float8
        return repr(struct.unpack("!d", raw)[0])
    if oid == OID_BOOL and n == 1:
        return "true" if raw[0] else "false"
    if oid in (1114, 1184) and n == 8:       # timestamp[tz]: µs since 2000
        us = int.from_bytes(raw, "big", signed=True) + _PG_EPOCH_US
        import datetime as _dt
        # integer µs math: float-seconds rounds the last digit at
        # current-epoch magnitudes (float64 resolution ~0.24µs there)
        sec, us_rem = divmod(us, 1_000_000)
        dt = _dt.datetime.fromtimestamp(sec, _dt.timezone.utc) \
            + _dt.timedelta(microseconds=us_rem)
        return dt.strftime("%Y-%m-%d %H:%M:%S.%f")
    if oid == 1082 and n == 4:               # date: days since 2000-01-01
        days = int.from_bytes(raw, "big", signed=True)
        import datetime as _dt
        return str(_dt.date(2000, 1, 1) + _dt.timedelta(days=days))
    # text/varchar/unknown: binary representation is the utf8 bytes
    return raw.decode("utf-8", errors="replace")


def _pg_text(v, dtype) -> Optional[bytes]:
    if v is None:
        return None
    if dtype is not None and dtype.is_timestamp:
        from ..common.time import Timestamp
        return Timestamp(v, dtype.time_unit).to_datetime().strftime(
            "%Y-%m-%d %H:%M:%S.%f").encode()
    if isinstance(v, bool):
        return b"t" if v else b"f"
    return str(v).encode()


class _MessageIO:
    def __init__(self, sock: socket.socket):
        self.sock = sock

    def _read_n(self, n: int) -> Optional[bytes]:
        chunks = []
        while n > 0:
            chunk = self.sock.recv(n)
            if not chunk:
                return None
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def read_startup(self) -> Optional[Tuple[int, bytes]]:
        head = self._read_n(4)
        if head is None:
            return None
        length = struct.unpack("!I", head)[0]
        body = self._read_n(length - 4)
        if body is None or len(body) < 4:
            return None
        code = struct.unpack_from("!I", body, 0)[0]
        return code, body[4:]

    def read_message(self) -> Optional[Tuple[int, bytes]]:
        head = self._read_n(5)
        if head is None:
            return None
        tag = head[0]
        length = struct.unpack_from("!I", head, 1)[0]
        body = self._read_n(length - 4)
        return tag, body if body is not None else b""

    def send(self, tag: bytes, body: bytes = b"") -> None:
        self.sock.sendall(tag + struct.pack("!I", len(body) + 4) + body)

    def send_raw(self, data: bytes) -> None:
        self.sock.sendall(data)


_PG_ROW_RETURNING = {"select", "show", "describe", "desc", "tql", "explain",
                     "with", "values", "table"}


def _sqlstate(e: GreptimeError) -> str:
    """SQLSTATE for a taxonomy error: admission rejections map to
    53300 (too_many_connections — the class clients retry with
    backoff); everything else stays the generic internal_error."""
    from ..errors import OverloadedError
    return "53300" if isinstance(e, OverloadedError) else "XX000"


def _returns_rows(sql: str) -> bool:
    word = sql.lstrip().split(None, 1)
    return bool(word) and word[0].lower() in _PG_ROW_RETURNING


class _PgPortal:
    __slots__ = ("sql", "result", "described")

    def __init__(self, sql: str):
        self.sql = sql
        self.result = None     # Output cached by Describe, reused by Execute
        self.described = False  # Describe sent RowDescription already


class _PgConnection:
    def __init__(self, server: "PostgresServer", sock: socket.socket,
                 conn_id: int):
        self.server = server
        self.sock = sock
        self.io = _MessageIO(sock)
        self.conn_id = conn_id
        self.ctx = QueryContext(channel=Channel.POSTGRES)
        self.stmts: Dict[str, str] = {}       # name -> sql with $N params
        self.stmt_param_oids: Dict[str, List[int]] = {}
        self.portals: Dict[str, _PgPortal] = {}
        # v3 protocol: after an error in the extended protocol, discard
        # messages until Sync (a pipelined Execute after a failed Bind must
        # not run a stale portal)
        self._in_error = False

    # ---- message helpers ----
    def send_error(self, message: str, code: str = "XX000",
                   severity: str = "ERROR") -> None:
        fields = (b"S" + severity.encode() + b"\x00"
                  + b"C" + code.encode() + b"\x00"
                  + b"M" + message.encode() + b"\x00" + b"\x00")
        self.io.send(b"E", fields)

    def send_ready(self) -> None:
        self.io.send(b"Z", b"I")

    def ext_error(self, message: str, code: str = "XX000") -> None:
        """ErrorResponse inside the extended protocol: enter the
        skip-until-Sync state the v3 protocol requires."""
        self.send_error(message, code)
        self._in_error = True

    def send_row_description(self, schema) -> None:
        body = struct.pack("!H", len(schema.column_schemas))
        for col in schema.column_schemas:
            body += (col.name.encode() + b"\x00"
                     + struct.pack("!IHIhih", 0, 0, _pg_oid(col.dtype),
                                   -1, -1, 0))
        self.io.send(b"T", body)

    def send_rows(self, batches) -> int:
        n = 0
        for b in batches:
            dtypes = [c.dtype for c in b.schema.column_schemas]
            for row in b.rows():
                body = struct.pack("!H", len(row))
                for v, dt in zip(row, dtypes):
                    txt = _pg_text(v, dt)
                    if txt is None:
                        body += struct.pack("!i", -1)
                    else:
                        body += struct.pack("!i", len(txt)) + txt
                self.io.send(b"D", body)
                n += 1
        return n

    def send_complete(self, sql: str, output) -> None:
        word = sql.lstrip().split(None, 1)
        word = word[0].upper() if word else ""
        if output.is_batches:
            tag = f"SELECT {output.num_rows}"
        elif word == "INSERT":
            tag = f"INSERT 0 {output.affected_rows or 0}"
        elif word == "DELETE":
            tag = f"DELETE {output.affected_rows or 0}"
        else:
            tag = word or "OK"
        self.io.send(b"C", tag.encode() + b"\x00")

    # ---- startup/auth ----
    def startup(self) -> bool:
        while True:
            msg = self.io.read_startup()
            if msg is None:
                return False
            code, body = msg
            if code == SSL_REQUEST:
                if self.server.ssl_context is not None:
                    self.io.send_raw(b"S")
                    self.sock = self.server.ssl_context.wrap_socket(
                        self.sock, server_side=True)
                    self.io.sock = self.sock
                else:
                    self.io.send_raw(b"N")
                continue
            if code == CANCEL_REQUEST:
                return False
            if code != PROTOCOL_V3:
                self.send_error(f"unsupported protocol {code}", "0A000",
                                "FATAL")
                return False
            break
        params: Dict[str, str] = {}
        parts = body.split(b"\x00")
        for k, v in zip(parts[::2], parts[1::2]):
            if k:
                params[k.decode()] = v.decode()
        user = params.get("user", "greptime")
        if params.get("database"):
            self.ctx.set_current_schema(params["database"])

        provider = self.server.user_provider
        if provider is not None and provider.requires_password:
            if self.server.auth_method == "md5":
                # md5(md5(password + user) + salt), "md5"-prefixed hex
                # (reference: pgwire md5 flow, auth_handler.rs)
                import hashlib
                import os as _os
                salt = _os.urandom(4)
                self.io.send(b"R", struct.pack("!I", 5) + salt)
                msg = self.io.read_message()
                if msg is None or msg[0] != ord("p"):
                    return False
                got = msg[1].rstrip(b"\x00").decode()
                expected_pwd = provider.plain_password(user)
                ok = False
                if expected_pwd is not None:
                    inner = hashlib.md5(
                        (expected_pwd + user).encode()).hexdigest()
                    want = "md5" + hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    ok = got == want
                if not ok:
                    self.send_error(f'password authentication failed for '
                                    f'user "{user}"', "28P01", "FATAL")
                    return False
            else:
                self.io.send(b"R", struct.pack("!I", 3))  # cleartext
                msg = self.io.read_message()
                if msg is None or msg[0] != ord("p"):
                    return False
                password = msg[1].rstrip(b"\x00").decode()
                if not provider.authenticate(user, password):
                    self.send_error(f'password authentication failed for '
                                    f'user "{user}"', "28P01", "FATAL")
                    return False
        self.ctx.username = user
        self.io.send(b"R", struct.pack("!I", 0))       # AuthenticationOk
        for k, v in (("server_version", "16.0"),
                     ("server_encoding", "UTF8"),
                     ("client_encoding", "UTF8"),
                     ("DateStyle", "ISO, MDY"),
                     ("TimeZone", "UTC"),
                     ("integer_datetimes", "on")):
            self.io.send(b"S", k.encode() + b"\x00" + v.encode() + b"\x00")
        self.io.send(b"K", struct.pack("!II", self.conn_id, 0))
        self.send_ready()
        return True

    # ---- query execution ----
    def _execute_sql(self, sql: str, *, describe_only: bool = False):
        outputs = self.server.instance.do_query(sql, self.ctx)
        return outputs[-1]

    def handle_simple_query(self, sql: str) -> None:
        sql = sql.rstrip("\x00")
        if not sql.strip():
            self.io.send(b"I")
            self.send_ready()
            return
        try:
            out = self._execute_sql(sql)
            if out.is_batches:
                batches = out.batches
                if batches:
                    self.send_row_description(batches[0].schema)
                    self.send_rows(batches)
                else:
                    self.io.send(b"T", struct.pack("!H", 0))
            self.send_complete(sql, out)
        except GreptimeError as e:
            self.send_error(str(e), _sqlstate(e))
        except Exception as e:  # noqa: BLE001
            logger.exception("postgres query failed: %s", sql)
            self.send_error(str(e))
        self.send_ready()

    # ---- extended protocol ----
    def handle_parse(self, body: bytes) -> None:
        end = body.index(b"\x00")
        name = body[:end].decode()
        end2 = body.index(b"\x00", end + 1)
        sql = body[end + 1:end2].decode()
        # optional parameter-type OIDs: binary Bind values decode by them
        # (reference pgwire accepts both formats, handler.rs:648)
        pos = end2 + 1
        oids: List[int] = []
        if pos + 2 <= len(body):
            (noids,) = struct.unpack_from("!H", body, pos)
            pos += 2
            for _ in range(noids):
                if pos + 4 > len(body):
                    break
                oids.append(struct.unpack_from("!I", body, pos)[0])
                pos += 4
        self.stmts[name] = sql
        self.stmt_param_oids[name] = oids
        self.io.send(b"1")                              # ParseComplete

    def handle_bind(self, body: bytes) -> None:
        pos = body.index(b"\x00")
        portal = body[:pos].decode()
        end = body.index(b"\x00", pos + 1)
        stmt_name = body[pos + 1:end].decode()
        pos = end + 1
        nfmt = struct.unpack_from("!H", body, pos)[0]
        pos += 2
        fmts = list(struct.unpack_from(f"!{nfmt}H", body, pos)) \
            if nfmt else []
        pos += 2 * nfmt
        nparams = struct.unpack_from("!H", body, pos)[0]
        pos += 2
        sql = self.stmts.get(stmt_name)
        if sql is None:
            self.ext_error(
                f"prepared statement {stmt_name!r} does not exist", "26000")
            return
        oids = self.stmt_param_oids.get(stmt_name, [])
        params: List[Optional[str]] = []
        for i in range(nparams):
            plen = struct.unpack_from("!i", body, pos)[0]
            pos += 4
            if plen == -1:
                params.append(None)
                continue
            raw = body[pos:pos + plen]
            pos += plen
            # per-protocol: 0 codes = all text, 1 code = applies to all
            fmt = fmts[i] if i < len(fmts) else (fmts[0] if fmts else 0)
            if fmt == 1:
                oid = oids[i] if i < len(oids) else 0
                params.append(_decode_binary_param(raw, oid))
            else:
                params.append(raw.decode())
        self.portals[portal] = _PgPortal(_substitute_pg_params(sql, params))
        self.io.send(b"2")                              # BindComplete

    def handle_describe(self, body: bytes) -> None:
        """Describe must return the RowDescription for row-returning
        statements/portals (v3 protocol; the reference's pgwire plans at
        Describe, src/servers/src/postgres/handler.rs:648). JDBC and
        psycopg3 extended mode plan on this. Portals execute here and cache
        the result for Execute; parametrized statement Describe probes the
        schema with NULL-substituted params."""
        import re
        kind = chr(body[0])
        name = body[1:].rstrip(b"\x00").decode()
        if kind == "S":
            sql = self.stmts.get(name)
            if sql is None:
                self.ext_error(
                    f"prepared statement {name!r} does not exist", "26000")
                return
            nparams = len(set(re.findall(r"\$(\d+)", sql)))
            # all parameters described as text; values coerce at parse time
            self.io.send(b"t", struct.pack("!H", nparams)
                         + struct.pack("!I", OID_TEXT) * nparams)
            if _returns_rows(sql):
                probe = _substitute_pg_params(sql, [None] * nparams) \
                    if nparams else sql
                # prefer a LIMIT 0 probe: schema without scanning any rows
                # (Execute re-runs the statement through its portal anyway)
                word = probe.lstrip().split(None, 1)[0].lower()
                candidates = []
                if word in ("select", "with", "values", "table"):
                    # LIMIT 0 probe first (schema without scanning rows);
                    # the full probe is the fallback for statements the
                    # suffix breaks (e.g. an existing LIMIT clause)
                    candidates.append(probe.rstrip().rstrip(";") + " LIMIT 0")
                    candidates.append(probe)
                if word in ("show", "describe", "desc"):
                    candidates.append(probe)  # metadata queries are cheap
                # expensive non-LIMITable statements (TQL, EXPLAIN) fall
                # through to NoData rather than executing twice
                for cand in candidates:
                    try:
                        out = self._execute_sql(cand)
                    except Exception:  # noqa: BLE001 — try next / NoData
                        logger.debug("describe probe failed: %s", cand,
                                     exc_info=True)
                        continue
                    if out.is_batches and out.batches:
                        self.send_row_description(out.batches[0].schema)
                        return
            self.io.send(b"n")                          # NoData
            return
        portal = self.portals.get(name)
        if portal is None:
            self.ext_error(f"portal {name!r} does not exist", "34000")
            return
        if _returns_rows(portal.sql):
            try:
                portal.result = self._execute_sql(portal.sql)
            except GreptimeError as e:
                self.ext_error(str(e), _sqlstate(e))
                return
            except Exception as e:  # noqa: BLE001
                logger.exception("postgres describe failed: %s", portal.sql)
                self.ext_error(str(e))
                return
            if portal.result.is_batches and portal.result.batches:
                self.send_row_description(portal.result.batches[0].schema)
                portal.described = True
                return
        self.io.send(b"n")                              # NoData

    def handle_execute(self, body: bytes) -> None:
        name = body[:body.index(b"\x00")].decode()
        portal = self.portals.get(name)
        if portal is None:
            self.ext_error(f"portal {name!r} does not exist", "34000")
            return
        sql = portal.sql
        try:
            # reuse the result a preceding Describe already computed
            out, portal.result = portal.result, None
            described, portal.described = portal.described, False
            if out is None:
                out = self._execute_sql(sql)
            if out.is_batches:
                batches = out.batches
                if batches:
                    if not described:  # Describe already sent the 'T'
                        self.send_row_description(batches[0].schema)
                    self.send_rows(batches)
                elif not described:
                    self.io.send(b"T", struct.pack("!H", 0))
            self.send_complete(sql, out)
        except GreptimeError as e:
            self.ext_error(str(e), _sqlstate(e))
        except Exception as e:  # noqa: BLE001
            logger.exception("postgres execute failed: %s", sql)
            self.ext_error(str(e))

    def handle_close(self, body: bytes) -> None:
        kind = chr(body[0])
        name = body[1:].rstrip(b"\x00").decode()
        if kind == "S":
            self.stmts.pop(name, None)
        else:
            self.portals.pop(name, None)
        self.io.send(b"3")                              # CloseComplete

    # ---- main loop ----
    def run(self) -> None:
        try:
            if not self.startup():
                return
            while True:
                msg = self.io.read_message()
                if msg is None:
                    return
                tag, body = msg
                ch = chr(tag)
                if ch == "X":                           # Terminate
                    return
                if ch == "S":                           # Sync
                    self._in_error = False              # error state ends
                    # Describe-cached results live only within one pipeline
                    # batch: replaying them in a later cycle would miss
                    # intervening writes, and an un-Executed portal would
                    # pin its whole result set for the connection lifetime
                    for p in self.portals.values():
                        p.result = None
                    self.send_ready()
                elif ch == "Q":
                    self._in_error = False
                    self.handle_simple_query(body.decode())
                elif self._in_error and ch in "PBDECH":
                    pass  # v3: discard until Sync after an error
                elif ch == "P":
                    self.handle_parse(body)
                elif ch == "B":
                    self.handle_bind(body)
                elif ch == "D":
                    self.handle_describe(body)
                elif ch == "E":
                    self.handle_execute(body)
                elif ch == "C":
                    self.handle_close(body)
                elif ch == "H":                         # Flush
                    pass
                else:
                    self.send_error(f"unsupported message {ch!r}", "0A000")
                    self.send_ready()
        except (ConnectionError, OSError):
            pass
        except Exception:  # noqa: BLE001
            logger.exception("postgres connection %d crashed", self.conn_id)
        finally:
            try:
                self.sock.close()
            except OSError:
                pass


def _substitute_pg_params(sql: str, params: List[Optional[str]]) -> str:
    """Text-format $N substitution (reference pgwire handles typed params;
    values arrive as text and our parser coerces by column type)."""
    out = []
    i = 0
    in_str = False
    while i < len(sql):
        ch = sql[i]
        if ch == "'":
            in_str = not in_str
            out.append(ch)
            i += 1
        elif ch == "$" and not in_str and i + 1 < len(sql) \
                and sql[i + 1].isdigit():
            j = i + 1
            while j < len(sql) and sql[j].isdigit():
                j += 1
            idx = int(sql[i + 1:j]) - 1
            if 0 <= idx < len(params):
                v = params[idx]
                if v is None:
                    out.append("NULL")
                elif _is_number(v):
                    out.append(v)
                else:
                    out.append("'" + v.replace("'", "''") + "'")
                i = j
            else:
                out.append(ch)
                i += 1
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _is_number(s: str) -> bool:
    try:
        float(s)
        return True
    except ValueError:
        return False


class PostgresServer:
    """Threaded PostgreSQL protocol listener over a frontend instance."""

    def __init__(self, instance, host: str = "127.0.0.1", port: int = 0,
                 user_provider=None,
                 ssl_context: Optional[ssl_mod.SSLContext] = None,
                 auth_method: str = "md5"):
        self.instance = instance
        self.user_provider = user_provider
        self.ssl_context = ssl_context
        self.auth_method = auth_method
        self._next_conn_id = 1
        self._lock = threading.Lock()
        server_self = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with server_self._lock:
                    conn_id = server_self._next_conn_id
                    server_self._next_conn_id += 1
                _PgConnection(server_self, self.request, conn_id).run()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._tcp = Server((host, port), Handler)
        self.port = self._tcp.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def serve_in_background(self) -> threading.Thread:
        from ..common.runtime import new_thread
        self._thread = new_thread(self._tcp.serve_forever, daemon=True,
                                  name="postgres-server",
                                  propagate_context=False)
        self._thread.start()
        return self._thread

    # CLI lifecycle alias (cmd/main.py starts all servers uniformly)
    start = serve_in_background

    @property
    def host(self) -> str:
        return self._tcp.server_address[0]

    def shutdown(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
