"""HTTP API server (aiohttp).

Reference behavior: src/servers/src/http.rs:434-578 — routes /v1/sql,
/v1/promql, /v1/influxdb/write, /v1/opentsdb/api/put,
/v1/prometheus/{write,read}, /metrics, health/status, admin flush, plus the
Prometheus-compatible query API (src/servers/src/prom.rs) mounted under
/api/v1. Responses use the GreptimeDB JSON envelope
{"code": 0, "output": [...], "execution_time_ms": n}.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from aiohttp import web

from ..errors import AuthError, GreptimeError, StatusCode
from ..query.output import Output
from ..session import Channel, QueryContext
from .. import DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME
from . import influxdb as influx_mod
from . import opentsdb as tsdb_mod
from . import prometheus as prom_mod
from .auth import NoopUserProvider, UserProvider

logger = logging.getLogger(__name__)


def parse_db_param(db: Optional[str]) -> tuple:
    if not db:
        return DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME
    if "-" in db:
        catalog, _, schema = db.partition("-")
        return catalog, schema
    return DEFAULT_CATALOG_NAME, db


def output_to_json(out: Output) -> Dict[str, Any]:
    if not out.is_batches:
        return {"affectedrows": out.affected_rows or 0}
    schema = out.schema
    col_schemas = [{"name": c.name, "data_type": c.dtype.name}
                   for c in schema.column_schemas] if schema else []
    rows: List[list] = []
    for b in out.batches or []:
        for r in b.rows():
            rows.append([None if v != v else v
                         if isinstance(v, float) else v for v in r])
    return {"records": {"schema": {"column_schemas": col_schemas},
                        "rows": rows}}


class HttpServer:
    def __init__(self, frontend, user_provider: Optional[UserProvider] = None,
                 addr: str = "127.0.0.1:4000", ssl_context=None):
        self.frontend = frontend
        self.user_provider = user_provider or NoopUserProvider()
        self.ssl_context = ssl_context
        host, _, port = addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self._runner: Optional[web.AppRunner] = None
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._start_time = time.time()

    # ---- app ----
    def make_app(self) -> web.Application:
        app = web.Application(middlewares=[self._error_middleware])
        r = app.router
        r.add_route("*", "/v1/sql", self.handle_sql)
        r.add_route("*", "/v1/promql", self.handle_promql)
        r.add_post("/v1/influxdb/write", self.handle_influx_write)
        r.add_post("/v1/influxdb/api/v2/write", self.handle_influx_write)
        r.add_get("/v1/influxdb/health", self.handle_health)
        r.add_post("/v1/opentsdb/api/put", self.handle_opentsdb_put)
        r.add_post("/v1/prometheus/write", self.handle_prom_write)
        r.add_post("/v1/prometheus/read", self.handle_prom_read)
        r.add_get("/metrics", self.handle_metrics)
        r.add_get("/health", self.handle_health)
        r.add_get("/status", self.handle_status)
        r.add_get("/v1/trace/{trace_id}", self.handle_trace)
        r.add_post("/v1/admin/flush", self.handle_flush)
        r.add_post("/v1/admin/compact", self.handle_compact)
        r.add_post("/v1/admin/downsample", self.handle_downsample)
        r.add_route("*", "/v1/admin/failpoints", self.handle_failpoints)
        r.add_post("/v1/scripts", self.handle_scripts)
        r.add_post("/v1/run-script", self.handle_run_script)
        r.add_get("/v1/prof/mem", self.handle_mem_prof)
        r.add_get("/debug/prof/cpu", self.handle_cpu_prof)
        r.add_route("*", "/api/v1/query", self.handle_prom_api_query)
        r.add_route("*", "/api/v1/query_range", self.handle_prom_api_range)
        r.add_route("*", "/api/v1/labels", self.handle_prom_api_labels)
        r.add_route("*", "/api/v1/series", self.handle_prom_api_series)
        r.add_route("*", "/api/v1/label/{name}/values",
                    self.handle_prom_api_label_values)
        # Grafana/Prometheus compatibility probes
        r.add_get("/api/v1/status/buildinfo", self.handle_prom_buildinfo)
        r.add_route("*", "/api/v1/metadata", self.handle_prom_metadata)
        return app

    async def handle_prom_buildinfo(self, request):
        """Grafana probes this to detect the Prometheus flavor."""
        from .mysql import SERVER_VERSION
        return web.json_response({
            "status": "success",
            "data": {"version": "2.45.0",
                     "application": f"greptimedb-tpu {SERVER_VERSION}",
                     "revision": "", "branch": "", "buildUser": "",
                     "buildDate": "", "goVersion": ""}})

    async def handle_prom_metadata(self, request):
        """Metric metadata: every field column of every table, typed as
        untyped (the reference serves the same shape)."""
        ctx = self._ctx(request)
        out = {}
        catalog = ctx.current_catalog
        for schema_name in self.frontend.catalog.schema_names(catalog):
            for tname in self.frontend.catalog.table_names(catalog,
                                                           schema_name):
                t = self.frontend.catalog.table(catalog, schema_name,
                                                tname)
                if t is None:
                    continue
                out[tname] = [{"type": "untyped", "help": "", "unit": ""}]
        return web.json_response({"status": "success", "data": out})

    @web.middleware
    async def _error_middleware(self, request, handler):
        start = time.perf_counter()
        try:
            return await self._observed(request, handler, start)
        except AuthError as e:
            return web.json_response(
                {"code": int(StatusCode.USER_PASSWORD_MISMATCH),
                 "error": str(e)}, status=401)
        except GreptimeError as e:
            code = getattr(e, "status_code", StatusCode.INTERNAL)
            headers = None
            status = 400
            if code == StatusCode.RATE_LIMITED:
                # admission rejection: reject-with-retry-after, the
                # load-shedding contract (errors.py maps the code → 429)
                status = e.to_http_status()
                headers = {"Retry-After":
                           str(getattr(e, "retry_after_s", 1))}
            return web.json_response(
                {"code": int(code),
                 "error": str(e),
                 "execution_time_ms": int((time.perf_counter() - start) * 1e3)},
                status=status, headers=headers)
        except web.HTTPException:
            raise
        except Exception as e:  # pragma: no cover - defensive
            return web.json_response(
                {"code": int(StatusCode.INTERNAL), "error": str(e)},
                status=500)

    @staticmethod
    async def _observed(request, handler, start: float):
        """Per-route latency histogram (canonical route template, not
        the raw path, so /api/v1/label/{name}/values stays ONE series).
        Recorded in a finally so error responses — the requests an
        operator most needs in the distribution — count too."""
        try:
            return await handler(request)
        finally:
            resource = getattr(request.match_info.route, "resource", None)
            if resource is not None:
                from ..common.telemetry import observe_latency
                observe_latency("http_request",
                                time.perf_counter() - start,
                                route=resource.canonical)

    def _ctx(self, request) -> QueryContext:
        self.user_provider.auth_http_basic(
            request.headers.get("Authorization"))
        db = request.query.get("db") or request.headers.get("x-greptime-db")
        catalog, schema = parse_db_param(db)
        return QueryContext(catalog, schema, Channel.HTTP)

    def _traced_call(self, request, fn):
        """Run `fn` (on the executor thread) under the request's W3C
        `traceparent` header, so external clients can stitch the whole
        statement — frontend span, datanode RPCs, slow-query log lines —
        onto their own trace."""
        tp = request.headers.get("traceparent")

        def run():
            from ..common.telemetry import remote_context
            with remote_context(tp):
                return fn()
        return run

    async def _param(self, request, name: str) -> Optional[str]:
        if name in request.query:
            return request.query[name]
        if request.method == "POST":
            if request.content_type == "application/x-www-form-urlencoded":
                form = await request.post()
                if name in form:
                    return form[name]
            elif request.content_type in ("application/json",):
                try:
                    body = await request.json()
                    if isinstance(body, dict) and name in body:
                        return str(body[name])
                except ValueError:
                    # malformed client JSON: fall through to "parameter
                    # absent" — the handler's 400 names the parameter
                    return None
        return None

    # ---- handlers ----
    async def handle_sql(self, request):
        t0 = time.perf_counter()
        ctx = self._ctx(request)
        sql = await self._param(request, "sql")
        if not sql:
            return web.json_response(
                {"code": int(StatusCode.INVALID_ARGUMENTS),
                 "error": "missing 'sql' parameter"}, status=400)
        loop = asyncio.get_running_loop()
        outputs = await loop.run_in_executor(
            None,
            self._traced_call(request,
                              lambda: self.frontend.do_query(sql, ctx)))
        return web.json_response({
            "code": 0,
            "output": [output_to_json(o) for o in outputs],
            "execution_time_ms": int((time.perf_counter() - t0) * 1e3),
        })

    async def handle_promql(self, request):
        t0 = time.perf_counter()
        ctx = self._ctx(request)
        query = await self._param(request, "query")
        start = await self._param(request, "start")
        end = await self._param(request, "end")
        step = await self._param(request, "step")
        if not all([query, start, end, step]):
            return web.json_response(
                {"code": int(StatusCode.INVALID_ARGUMENTS),
                 "error": "query/start/end/step are required"}, status=400)
        from ..sql.ast import Tql
        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(
            None, self._traced_call(
                request, lambda: self.frontend.execute_tql(
                    Tql("eval", start, end, step, None, query), ctx)))
        return web.json_response({
            "code": 0,
            "output": [output_to_json(out)],
            "execution_time_ms": int((time.perf_counter() - t0) * 1e3),
        })

    # ---- coprocessor scripts (reference: /v1/scripts + /v1/run-script,
    # src/servers/src/http.rs:434-578 script routes) ----
    def _script_engine(self):
        engine = getattr(self.frontend, "script_engine", None)
        if engine is None:
            from ..script import ScriptEngine
            engine = ScriptEngine(self.frontend)
            self.frontend.script_engine = engine
        return engine

    async def handle_scripts(self, request):
        ctx = self._ctx(request)
        name = request.query.get("name")
        if request.query.get("db"):
            ctx.set_current_schema(request.query["db"])
        if not name:
            return web.json_response(
                {"code": int(StatusCode.INVALID_ARGUMENTS),
                 "error": "missing 'name' parameter"}, status=400)
        script = (await request.read()).decode()
        loop = asyncio.get_running_loop()
        engine = self._script_engine()
        await loop.run_in_executor(
            None, self._traced_call(
                request, lambda: engine.insert_script(name, script, ctx)))
        return web.json_response({"code": 0})

    async def handle_run_script(self, request):
        t0 = time.perf_counter()
        ctx = self._ctx(request)
        name = request.query.get("name")
        if request.query.get("db"):
            ctx.set_current_schema(request.query["db"])
        loop = asyncio.get_running_loop()
        engine = self._script_engine()
        if name:
            out = await loop.run_in_executor(
                None, self._traced_call(
                    request, lambda: engine.run(name, ctx=ctx)))
        else:
            script = (await request.read()).decode()
            if not script:
                return web.json_response(
                    {"code": int(StatusCode.INVALID_ARGUMENTS),
                     "error": "missing 'name' parameter or script body"},
                    status=400)
            out = await loop.run_in_executor(
                None, self._traced_call(
                    request, lambda: engine.run(script, ctx=ctx,
                                                is_script_text=True)))
        return web.json_response({
            "code": 0,
            "output": [output_to_json(out)],
            "execution_time_ms": int((time.perf_counter() - t0) * 1e3),
        })

    async def handle_influx_write(self, request):
        ctx = self._ctx_influx(request)
        precision = request.query.get("precision", "ns")
        body = (await request.read()).decode()
        loop = asyncio.get_running_loop()

        def work():
            from ..common.admission import GATE
            from .coalesce import COALESCER
            with GATE.admit_ingest(len(body)):
                inserts, tag_cols = influx_mod.body_to_inserts(body,
                                                               precision)
                n = 0
                for table, cols in inserts.items():
                    # concurrent small bodies for the same measurement
                    # merge into one shared bulk insert (one WAL record,
                    # one group-commit fsync) — the ack still covers
                    # exactly this request's rows
                    n += COALESCER.ingest(
                        self.frontend, table, cols,
                        tag_columns=tag_cols[table],
                        timestamp_column=influx_mod.GREPTIME_TIMESTAMP,
                        ctx=ctx)
                return n

        await loop.run_in_executor(None, self._traced_call(request, work))
        return web.Response(status=204)

    def _ctx_influx(self, request) -> QueryContext:
        # influxdb v1 auth: u/p params; v2: Token header; else basic
        u = request.query.get("u")
        p = request.query.get("p")
        if u is not None or p is not None:
            if not self.user_provider.authenticate(u or "", p or ""):
                raise AuthError("bad username or password")
        else:
            auth = request.headers.get("Authorization")
            if auth and auth.startswith("Token "):
                token = auth[len("Token "):]
                name, _, pwd = token.partition(":")
                if not self.user_provider.authenticate(name, pwd):
                    raise AuthError("bad token")
            else:
                self.user_provider.auth_http_basic(auth)
        db = request.query.get("db") or request.query.get("bucket")
        catalog, schema = parse_db_param(db)
        return QueryContext(catalog, schema, Channel.INFLUX)

    async def handle_opentsdb_put(self, request):
        ctx = self._ctx(request)
        raw = await request.read()
        loop = asyncio.get_running_loop()

        def work():
            from ..common.admission import GATE
            from .coalesce import COALESCER
            # reserve the RAW body size like the influx/prom handlers —
            # a short-metric-name flood must not slip a big JSON body
            # past the byte gate
            with GATE.admit_ingest(len(raw)):
                points = tsdb_mod.parse_http_put(json.loads(raw))
                inserts, tag_cols = tsdb_mod.points_to_inserts(points)
                for table, cols in inserts.items():
                    COALESCER.ingest(
                        self.frontend, table, cols,
                        tag_columns=tag_cols[table],
                        timestamp_column=tsdb_mod.GREPTIME_TIMESTAMP,
                        ctx=ctx)
                return len(points)

        n = await loop.run_in_executor(None,
                                       self._traced_call(request, work))
        return web.json_response({"success": n, "failed": 0}, status=200)

    async def handle_prom_write(self, request):
        ctx = self._ctx(request)
        body = await request.read()
        loop = asyncio.get_running_loop()

        def work():
            from ..common.admission import GATE
            from .coalesce import COALESCER
            with GATE.admit_ingest(len(body)):
                inserts, tag_cols = prom_mod.write_request_to_inserts(body)
                for table, cols in inserts.items():
                    COALESCER.ingest(
                        self.frontend, table, cols,
                        tag_columns=tag_cols[table],
                        timestamp_column=prom_mod.GREPTIME_TIMESTAMP,
                        ctx=ctx)

        await loop.run_in_executor(None, self._traced_call(request, work))
        return web.Response(status=204)

    async def handle_prom_read(self, request):
        ctx = self._ctx(request)
        body = await request.read()
        loop = asyncio.get_running_loop()

        def work():
            queries = prom_mod.decode_read_request(body)
            results = []
            for q in queries:
                results.append(self._remote_read_query(q, ctx))
            return prom_mod.encode_read_response(results)

        payload = await loop.run_in_executor(None,
                                             self._traced_call(request, work))
        return web.Response(body=payload,
                            content_type="application/x-protobuf",
                            headers={"Content-Encoding": "snappy"})

    def _remote_read_query(self, q, ctx) -> List[prom_mod.TimeSeries]:
        """Scan the metric table over [start, end] and re-assemble series
        (reference: prometheus.rs remote read → SQL)."""
        metric = q.metric_name()
        if metric is None:
            return []
        table = self.frontend.catalog.table(
            ctx.current_catalog, ctx.current_schema, metric)
        if table is None:
            return []
        from ..common.time import TimestampRange
        batches = table.scan_batches(
            time_range=TimestampRange(q.start_ms, q.end_ms + 1))
        tag_names = table.schema.tag_names()
        ts_name = table.schema.timestamp_column.name
        by_series: Dict[tuple, prom_mod.TimeSeries] = {}
        for b in batches:
            for row in b.to_pylist():
                labels = {t: str(row[t]) for t in tag_names if t in row}
                ok = True
                for m in q.matchers:
                    if m.name == prom_mod.METRIC_NAME_LABEL:
                        continue
                    if not m.matches(labels.get(m.name, "")):
                        ok = False
                        break
                if not ok:
                    continue
                key = tuple(sorted(labels.items()))
                s = by_series.get(key)
                if s is None:
                    full = dict(labels)
                    full[prom_mod.METRIC_NAME_LABEL] = metric
                    s = prom_mod.TimeSeries(labels=full)
                    by_series[key] = s
                val = row.get(prom_mod.GREPTIME_VALUE)
                if val is None:
                    fields = table.schema.field_names()
                    val = row.get(fields[0]) if fields else None
                if val is not None:
                    s.samples.append((float(val), int(row[ts_name])))
        return list(by_series.values())

    async def handle_trace(self, request):
        """GET /v1/trace/<trace_id> — the reassembled cross-node
        waterfall of one stored trace from greptime_private.trace_spans
        (the durable trace store). 'last' = the most recently retained
        trace on this frontend. 404 when the trace was sampled out,
        swept by retention, or never existed."""
        self.user_provider.auth_http_basic(
            request.headers.get("Authorization"))
        trace_id = request.match_info["trace_id"]

        def work():
            from ..common import trace_store
            clients = getattr(self.frontend, "clients", None)
            tid, rows = trace_store.sync_and_fetch(
                self.frontend.catalog, trace_id,
                clients=list(clients.values()) if clients else None)
            if not rows:
                return tid, None
            return tid, {
                "spans": rows,
                "waterfall": trace_store.waterfall_rows(rows),
            }

        loop = asyncio.get_running_loop()
        tid, doc = await loop.run_in_executor(
            None, self._traced_call(request, work))
        if doc is None:
            return web.json_response(
                {"code": int(StatusCode.INVALID_ARGUMENTS),
                 "error": f"trace {tid or trace_id!r} not found "
                          f"(sampled out, swept, or never existed)"},
                status=404)
        doc["trace_id"] = tid
        doc["span_count"] = len(doc["spans"])
        return web.json_response(doc)

    async def handle_cpu_prof(self, request):
        """GET /debug/prof/cpu?seconds=N&hz=H&format=folded|flamegraph|json
        — an on-demand high-rate CPU sampling burst (the reference's
        pprof-shaped /debug/prof/cpu, src/common/pprof). On a
        distributed frontend the burst fans out to every datanode over
        the Flight `profile` action concurrently and the folded stacks
        merge per node. Works with `SET profiling` off — the burst has
        its own clock and rate."""
        self.user_provider.auth_http_basic(
            request.headers.get("Authorization"))
        fmt = request.query.get("format", "folded")
        if fmt not in ("folded", "flamegraph", "json"):
            return web.json_response(
                {"code": int(StatusCode.INVALID_ARGUMENTS),
                 "error": f"format {fmt!r} not supported "
                          f"(folded | flamegraph | json)"}, status=400)
        try:
            seconds = float(request.query.get("seconds", "3"))
            hz = request.query.get("hz")
            hz_f = float(hz) if hz is not None else None
        except ValueError:
            return web.json_response(
                {"code": int(StatusCode.INVALID_ARGUMENTS),
                 "error": "seconds/hz must be numbers"}, status=400)

        def work():
            from ..common import profiler
            from ..common.runtime import parallel_map
            s = profiler.sampler()
            clients = list(getattr(self.frontend, "clients",
                                   {}).values())

            def one(target):
                try:
                    if target is None:
                        if s is None:
                            return []
                        return s.collect_burst(seconds, burst_hz=hz_f)
                    return target.profile(seconds=seconds, hz=hz_f)
                except Exception as e:  # noqa: BLE001 — a dead node
                    logger.warning(     # must not void the whole burst
                        "profile burst fan-out failed: %s", e)
                    return []

            merged: list = []
            for rows in parallel_map(one, [None] + clients,
                                     max_workers=len(clients) + 1):
                merged.extend(rows or [])
            return merged

        loop = asyncio.get_running_loop()
        rows = await loop.run_in_executor(
            None, self._traced_call(request, work))
        from ..common import profiler as prof_mod
        if fmt == "folded":
            return web.Response(text=prof_mod.folded_text(rows),
                                content_type="text/plain")
        if fmt == "flamegraph":
            return web.Response(
                text=prof_mod.flamegraph_svg(
                    rows, title=f"cpu {seconds:g}s burst"),
                content_type="image/svg+xml")
        return web.json_response({
            "seconds": seconds,
            "sample_count": sum(int(r.get("count") or 0) for r in rows),
            "rows": rows,
        })

    async def handle_mem_prof(self, request):
        """Heap profile dump (reference: jemalloc /v1/prof/mem,
        src/common/mem-prof; here a tracemalloc top-N snapshot). The
        first call enables tracing — subsequent calls diff against it."""
        import tracemalloc
        if not tracemalloc.is_tracing():
            tracemalloc.start()
            return web.Response(
                text="tracemalloc started; call again for a snapshot\n")
        snapshot = tracemalloc.take_snapshot()
        top = snapshot.statistics("lineno")[:50]
        lines = [f"{stat.size / 1024:.1f} KiB in {stat.count} blocks: "
                 f"{stat.traceback}" for stat in top]
        total = sum(s.size for s in snapshot.statistics("filename"))
        lines.insert(0, f"total traced: {total / 1048576:.2f} MiB")
        return web.Response(text="\n".join(lines) + "\n")

    async def handle_metrics(self, request):
        try:
            from prometheus_client import generate_latest
            return web.Response(body=generate_latest(),
                                content_type="text/plain")
        except ImportError:  # pragma: no cover
            return web.Response(text="")

    async def handle_health(self, request):
        return web.json_response({})

    async def handle_status(self, request):
        """Server status: version, uptime, region count, cache health and
        the latest ingest/scan stage profiles (reference: the /status
        build+state handler, src/servers/src/http/handler.rs) — the quick
        'what is this node doing' view the observability tests assert."""
        from .. import __version__
        regions = []
        try:
            cat = self.frontend.catalog
            for schema_name in cat.schema_names(DEFAULT_CATALOG_NAME):
                for tname in cat.table_names(DEFAULT_CATALOG_NAME,
                                             schema_name):
                    t = cat.table(DEFAULT_CATALOG_NAME, schema_name,
                                  tname)
                    regions.extend(
                        getattr(t, "regions", {}).values())
        except Exception:  # noqa: BLE001 — status must never 500
            from ..common.telemetry import increment_counter
            increment_counter("status_partial")
        ingest = scan = None
        for r in regions:
            p = getattr(r, "last_ingest_profile", None)
            if p is not None:
                ingest = p.describe()
            p = getattr(r, "last_scan_profile", None)
            if p is not None:
                scan = p.describe()
        from ..query.tpu_exec import SCAN_CACHE
        store = getattr(self.frontend.datanode, "store", None) \
            if hasattr(self.frontend, "datanode") else None
        ratio = store.hit_ratio() if hasattr(store, "hit_ratio") else None
        # degraded-mode health: regions whose background flush/compaction
        # has been failing, and the fault-injection state (robustness PR)
        background_errors = {}
        for r in regions:
            errs = getattr(r, "bg_errors", None)
            if errs:
                background_errors[r.name] = errs
        from ..common import failpoint
        from ..common.admission import GATE
        return web.json_response({
            "version": __version__,
            "admission": GATE.snapshot(),
            "uptime_s": round(time.time() - self._start_time, 3),
            "region_count": len(regions),
            "read_cache_hit_ratio": ratio,
            "scan_cache_resident_bytes": SCAN_CACHE.resident_bytes(),
            "last_ingest_profile": ingest,
            "last_scan_profile": scan,
            "background_errors": background_errors,
            "failpoints_active": failpoint.active_count(),
        })

    async def handle_flush(self, request):
        ctx = self._ctx(request)
        table_name = request.query.get("table")
        loop = asyncio.get_running_loop()

        def work():
            cat = self.frontend.catalog
            names = [table_name] if table_name else \
                cat.table_names(ctx.current_catalog, ctx.current_schema)
            for name in names:
                t = cat.table(ctx.current_catalog, ctx.current_schema, name)
                if t is not None:
                    t.flush()

        await loop.run_in_executor(None,
                                   self._traced_call(request, work))
        return web.json_response({"code": 0})

    async def handle_compact(self, request):
        ctx = self._ctx(request)
        table_name = request.query.get("table")
        loop = asyncio.get_running_loop()

        def work():
            cat = self.frontend.catalog
            names = [table_name] if table_name else \
                cat.table_names(ctx.current_catalog, ctx.current_schema)
            for name in names:
                t = cat.table(ctx.current_catalog, ctx.current_schema, name)
                for region in getattr(t, "regions", {}).values():
                    region.compact()

        await loop.run_in_executor(None,
                                   self._traced_call(request, work))
        return web.json_response({"code": 0})

    async def handle_failpoints(self, request):
        """Fault-injection admin surface (common/failpoint.py):

        - GET  /v1/admin/failpoints                  — list points
        - POST /v1/admin/failpoints?name=X&action=A  — arm (A='off' clears)
        - DELETE /v1/admin/failpoints[?name=X]       — disarm one / all
        """
        from ..common import failpoint
        self.user_provider.auth_http_basic(
            request.headers.get("Authorization"))
        if request.method == "GET":
            return web.json_response({"code": 0,
                                      "failpoints": failpoint.list_points()})
        if request.method == "DELETE":
            name = request.query.get("name")
            if name:
                try:
                    failpoint.configure(name, None)
                except ValueError as e:
                    return web.json_response(
                        {"code": int(StatusCode.INVALID_ARGUMENTS),
                         "error": str(e)}, status=400)
            else:
                failpoint.clear_all()
            return web.json_response({"code": 0})
        if request.method != "POST":
            return web.json_response(
                {"code": int(StatusCode.INVALID_ARGUMENTS),
                 "error": f"unsupported method {request.method}"},
                status=405)
        name = await self._param(request, "name")
        action = await self._param(request, "action")
        if not name:
            return web.json_response(
                {"code": int(StatusCode.INVALID_ARGUMENTS),
                 "error": "missing 'name' parameter"}, status=400)
        if not action:
            # a bare POST must not silently disarm a live experiment —
            # DELETE is the disarm surface
            return web.json_response(
                {"code": int(StatusCode.INVALID_ARGUMENTS),
                 "error": "missing 'action' parameter ('off' or DELETE "
                          "disarms)"}, status=400)
        try:
            failpoint.configure(name, action)
        except ValueError as e:
            return web.json_response(
                {"code": int(StatusCode.INVALID_ARGUMENTS),
                 "error": str(e)}, status=400)
        return web.json_response({"code": 0})

    async def handle_downsample(self, request):
        """POST /v1/admin/downsample?src=raw&dst=agg&stride=60s[&agg=avg]
        — aggregate src's rows into stride buckets and append to dst (the
        device-resident maintenance job, storage/downsample.py). This
        build's extension over the reference (v0.2 compaction only
        merges files)."""
        from ..common.time import parse_duration_ms
        from ..storage.downsample import downsample_region
        ctx = self._ctx(request)
        src_name = request.query.get("src")
        dst_name = request.query.get("dst")
        stride = request.query.get("stride", "60s")
        agg = request.query.get("agg", "avg")
        if not src_name or not dst_name:
            return web.json_response(
                {"code": 1004, "error": "src and dst are required"},
                status=400)
        try:
            stride_ms = parse_duration_ms(stride)
        except (ValueError, TypeError):
            return web.json_response(
                {"code": 1004, "error": f"bad stride {stride!r}"},
                status=400)
        cat = self.frontend.catalog
        src = cat.table(ctx.current_catalog, ctx.current_schema, src_name)
        dst = cat.table(ctx.current_catalog, ctx.current_schema, dst_name)
        if src is None or dst is None:
            return web.json_response(
                {"code": 4001, "error": "src or dst table not found"},
                status=404)
        loop = asyncio.get_running_loop()

        def work():
            total = 0
            src_regions = list(getattr(src, "regions", {}).values())
            dst_regions = list(getattr(dst, "regions", {}).values())
            if not src_regions or not dst_regions:
                raise ValueError("downsample needs region-backed tables")
            fields = [c.name for c in src.schema.field_columns()
                      if not src.schema.column_schema(c.name)
                      .dtype.is_string]
            aggs = {f: agg for f in fields}
            for region in src_regions:
                # destination rows go through the TABLE so a partitioned
                # dst routes each bucket row to its region via the
                # partition rule (partition/splitter.py); this endpoint
                # stays the manual backfill path for flows
                total += downsample_region(region, dst,
                                           stride_ms=stride_ms, aggs=aggs)
            return total

        try:
            rows = await loop.run_in_executor(
                None, self._traced_call(request, work))
        except Exception as e:  # noqa: BLE001 — surface as API error
            return web.json_response({"code": 1004, "error": str(e)},
                                     status=400)
        return web.json_response({"code": 0, "rows_written": rows})

    # ---- Prometheus HTTP API (prom.rs) ----
    async def handle_prom_api_query(self, request):
        from .prom_api import instant_query
        return await instant_query(self, request)

    async def handle_prom_api_range(self, request):
        from .prom_api import range_query
        return await range_query(self, request)

    async def handle_prom_api_labels(self, request):
        from .prom_api import labels_query
        return await labels_query(self, request)

    async def handle_prom_api_series(self, request):
        from .prom_api import series_query
        return await series_query(self, request)

    async def handle_prom_api_label_values(self, request):
        from .prom_api import label_values_query
        return await label_values_query(self, request)

    # ---- lifecycle (thread-hosted event loop) ----
    def start(self) -> None:
        from ..common.runtime import new_thread
        self._thread = new_thread(self._run, daemon=True,
                                  name="http-server",
                                  propagate_context=False)
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("http server failed to start")

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        async def boot():
            app = self.make_app()
            self._runner = web.AppRunner(app)
            await self._runner.setup()
            site = web.TCPSite(self._runner, self.host, self.port,
                               ssl_context=self.ssl_context)
            await site.start()
            if self.port == 0:
                self.port = self._runner.addresses[0][1]
            self._started.set()

        loop.run_until_complete(boot())
        loop.run_forever()

    def shutdown(self) -> None:
        if self._loop is None:
            return

        async def stop():
            if self._runner is not None:
                await self._runner.cleanup()
            asyncio.get_event_loop().stop()

        self._loop.call_soon_threadsafe(
            lambda: asyncio.ensure_future(stop()))
        if self._thread is not None:
            self._thread.join(timeout=5)
