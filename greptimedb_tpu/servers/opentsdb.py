"""OpenTSDB ingestion: telnet `put` lines and the HTTP /api/put JSON body.

Reference behavior: src/servers/src/opentsdb/codec.rs:291 — a DataPoint
(metric, ts, value, tags) stored as table=metric, tags→tags,
greptime_timestamp/greptime_value columns — and opentsdb.rs:60-120, the
line-based TCP listener on its own port (`OpentsdbServer` below).
"""

from __future__ import annotations

import socketserver
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..errors import InvalidArgumentsError

GREPTIME_TIMESTAMP = "greptime_timestamp"
GREPTIME_VALUE = "greptime_value"


@dataclass
class DataPoint:
    metric: str
    ts_ms: int
    value: float
    tags: Dict[str, str] = field(default_factory=dict)


def parse_telnet_put(line: str) -> DataPoint:
    """`put <metric> <timestamp> <value> <tagk=tagv> [...]`"""
    parts = line.strip().split()
    if not parts or parts[0] != "put":
        raise InvalidArgumentsError(
            "unknown command (expected 'put')" if parts else "empty line")
    if len(parts) < 4:
        raise InvalidArgumentsError(f"bad put line: {line!r}")
    metric = parts[1]
    ts = int(parts[2])
    # seconds vs milliseconds heuristic (OpenTSDB convention)
    ts_ms = ts * 1000 if ts < 10_000_000_000 else ts
    value = float(parts[3])
    tags = {}
    for kv in parts[4:]:
        k, sep, v = kv.partition("=")
        if not sep or not k:
            raise InvalidArgumentsError(f"bad tag {kv!r}")
        tags[k] = v
    return DataPoint(metric, ts_ms, value, tags)


def parse_http_put(body) -> List[DataPoint]:
    items = body if isinstance(body, list) else [body]
    out = []
    for it in items:
        try:
            ts = int(it["timestamp"])
            out.append(DataPoint(
                str(it["metric"]),
                ts * 1000 if ts < 10_000_000_000 else ts,
                float(it["value"]),
                {str(k): str(v) for k, v in (it.get("tags") or {}).items()}))
        except (KeyError, TypeError, ValueError) as e:
            raise InvalidArgumentsError(f"bad datapoint: {it!r}") from e
    return out


class OpentsdbServer:
    """Telnet-style TCP listener: one `put` line per data point.

    Reference behavior: src/servers/src/opentsdb.rs:60-120 — accept
    connections, read lines, insert each `put`, answer errors as text
    lines (classic OpenTSDB only replies on error), close on `exit`/
    `quit`, answer `version`.
    """

    def __init__(self, instance, host: str = "127.0.0.1", port: int = 0):
        self.instance = instance
        server_self = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    raw = self.rfile.readline()
                    if not raw:
                        return
                    try:
                        line = raw.decode("utf-8").strip()
                    except UnicodeDecodeError:
                        self.wfile.write(b"error: invalid utf-8\n")
                        continue
                    if not line:
                        continue
                    cmd = line.split(None, 1)[0].lower()
                    if cmd in ("exit", "quit"):
                        return
                    if cmd == "version":
                        self.wfile.write(b"net.opentsdb tsd built from "
                                         b"greptimedb-tpu\n")
                        continue
                    try:
                        server_self._ingest_line(line)
                    # the error IS the response: telnet clients get the
                    # first line back as text
                    except Exception as e:  # greptlint: disable=GL01
                        msg = str(e).split("\n")[0][:200]
                        self.wfile.write(f"error: {msg}\n".encode())

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._tcp = Server((host, port), Handler)
        self.port = self._tcp.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _ingest_line(self, line: str) -> None:
        from ..session import Channel, QueryContext
        point = parse_telnet_put(line)
        inserts, tag_cols = points_to_inserts([point])
        ctx = QueryContext(channel=Channel.OPENTSDB)
        for table, cols in inserts.items():
            self.instance.handle_row_insert(
                table, cols, tag_columns=tag_cols[table],
                timestamp_column=GREPTIME_TIMESTAMP, ctx=ctx)

    def serve_in_background(self) -> threading.Thread:
        from ..common.runtime import new_thread
        self._thread = new_thread(self._tcp.serve_forever, daemon=True,
                                  name="opentsdb-server",
                                  propagate_context=False)
        self._thread.start()
        return self._thread

    start = serve_in_background

    @property
    def host(self) -> str:
        return self._tcp.server_address[0]

    def shutdown(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()


def points_to_inserts(points: List[DataPoint]):
    """Group per metric into aligned column dicts."""
    by_metric: Dict[str, List[DataPoint]] = {}
    for p in points:
        by_metric.setdefault(p.metric, []).append(p)
    result = {}
    tag_cols = {}
    for metric, pts in by_metric.items():
        tag_names = sorted({k for p in pts for k in p.tags})
        cols: Dict[str, list] = {GREPTIME_TIMESTAMP: [],
                                 GREPTIME_VALUE: []}
        for t in tag_names:
            cols[t] = []
        for p in pts:
            cols[GREPTIME_TIMESTAMP].append(p.ts_ms)
            cols[GREPTIME_VALUE].append(p.value)
            for t in tag_names:
                cols[t].append(p.tags.get(t, ""))
        result[metric] = cols
        tag_cols[metric] = tag_names
    return result, tag_cols
