"""OpenTSDB ingestion: telnet `put` lines and the HTTP /api/put JSON body.

Reference behavior: src/servers/src/opentsdb/codec.rs:291 — a DataPoint
(metric, ts, value, tags) stored as table=metric, tags→tags,
greptime_timestamp/greptime_value columns.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from ..errors import InvalidArgumentsError

GREPTIME_TIMESTAMP = "greptime_timestamp"
GREPTIME_VALUE = "greptime_value"


@dataclass
class DataPoint:
    metric: str
    ts_ms: int
    value: float
    tags: Dict[str, str] = field(default_factory=dict)


def parse_telnet_put(line: str) -> DataPoint:
    """`put <metric> <timestamp> <value> <tagk=tagv> [...]`"""
    parts = line.strip().split()
    if not parts or parts[0] != "put":
        raise InvalidArgumentsError(
            "unknown command (expected 'put')" if parts else "empty line")
    if len(parts) < 4:
        raise InvalidArgumentsError(f"bad put line: {line!r}")
    metric = parts[1]
    ts = int(parts[2])
    # seconds vs milliseconds heuristic (OpenTSDB convention)
    ts_ms = ts * 1000 if ts < 10_000_000_000 else ts
    value = float(parts[3])
    tags = {}
    for kv in parts[4:]:
        k, sep, v = kv.partition("=")
        if not sep or not k:
            raise InvalidArgumentsError(f"bad tag {kv!r}")
        tags[k] = v
    return DataPoint(metric, ts_ms, value, tags)


def parse_http_put(body) -> List[DataPoint]:
    items = body if isinstance(body, list) else [body]
    out = []
    for it in items:
        try:
            ts = int(it["timestamp"])
            out.append(DataPoint(
                str(it["metric"]),
                ts * 1000 if ts < 10_000_000_000 else ts,
                float(it["value"]),
                {str(k): str(v) for k, v in (it.get("tags") or {}).items()}))
        except (KeyError, TypeError, ValueError) as e:
            raise InvalidArgumentsError(f"bad datapoint: {it!r}") from e
    return out


def points_to_inserts(points: List[DataPoint]):
    """Group per metric into aligned column dicts."""
    by_metric: Dict[str, List[DataPoint]] = {}
    for p in points:
        by_metric.setdefault(p.metric, []).append(p)
    result = {}
    tag_cols = {}
    for metric, pts in by_metric.items():
        tag_names = sorted({k for p in pts for k in p.tags})
        cols: Dict[str, list] = {GREPTIME_TIMESTAMP: [],
                                 GREPTIME_VALUE: []}
        for t in tag_names:
            cols[t] = []
        for p in pts:
            cols[GREPTIME_TIMESTAMP].append(p.ts_ms)
            cols[GREPTIME_VALUE].append(p.value)
            for t in tag_names:
                cols[t].append(p.tags.get(t, ""))
        result[metric] = cols
        tag_cols[metric] = tag_names
    return result, tag_cols
