"""MySQL wire-protocol server.

Reference behavior: src/servers/src/mysql/ — opensrv-mysql based shim with
auth + prepared-statement emulation (server.rs:20-60, handler.rs:386) and
"federated" fabricated answers for client bootstrap queries such as
`SELECT @@version_comment` (federated.rs:398). Here the protocol is
implemented directly: HandshakeV10 / HandshakeResponse41,
mysql_native_password auth, COM_QUERY text result sets, COM_STMT_*
prepared-statement emulation (client-side substitution, like the
reference), and the federated shim table. The server is a thin host-side
adapter — every query goes through the same frontend `do_query` the other
protocols use.
"""

from __future__ import annotations

import hashlib
import logging
import re
import socket
import socketserver
import ssl as ssl_mod
import struct
import threading
from typing import Dict, List, Optional, Tuple

from ..errors import GreptimeError
from ..session import Channel, QueryContext

logger = logging.getLogger(__name__)

SERVER_VERSION = "8.4.0-greptimedb-tpu"

# capability flags
CLIENT_LONG_PASSWORD = 0x1
CLIENT_CONNECT_WITH_DB = 0x8
CLIENT_PROTOCOL_41 = 0x200
CLIENT_SSL = 0x800
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_MULTI_STATEMENTS = 0x10000
CLIENT_MULTI_RESULTS = 0x20000
CLIENT_PLUGIN_AUTH = 0x80000
CLIENT_PLUGIN_AUTH_LENENC = 0x200000
CLIENT_DEPRECATE_EOF = 0x1000000

SERVER_CAPABILITIES = (
    CLIENT_LONG_PASSWORD | CLIENT_CONNECT_WITH_DB | CLIENT_PROTOCOL_41
    | CLIENT_TRANSACTIONS | CLIENT_SECURE_CONNECTION
    | CLIENT_MULTI_STATEMENTS | CLIENT_MULTI_RESULTS | CLIENT_PLUGIN_AUTH)

SERVER_STATUS_AUTOCOMMIT = 0x0002
CHARSET_UTF8MB4 = 45
CHARSET_BINARY = 63

# column types
T_TINY, T_SHORT, T_LONG, T_FLOAT, T_DOUBLE = 1, 2, 3, 4, 5
T_NULL, T_TIMESTAMP, T_LONGLONG = 6, 7, 8
T_DATETIME, T_VARCHAR, T_BLOB, T_VAR_STRING, T_STRING = 12, 15, 252, 253, 254

# commands
COM_QUIT, COM_INIT_DB, COM_QUERY, COM_FIELD_LIST = 0x01, 0x02, 0x03, 0x04
COM_PROCESS_KILL = 0x0C
COM_PING = 0x0E
COM_STMT_PREPARE, COM_STMT_EXECUTE = 0x16, 0x17
COM_STMT_CLOSE, COM_STMT_RESET = 0x19, 0x1A


# ---------------------------------------------------------------------------
# low-level codec
# ---------------------------------------------------------------------------

def lenenc_int(n: int) -> bytes:
    if n < 0xFB:
        return bytes([n])
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n)
    if n < 1 << 24:
        return b"\xfd" + struct.pack("<I", n)[:3]
    return b"\xfe" + struct.pack("<Q", n)


def lenenc_str(s: bytes) -> bytes:
    return lenenc_int(len(s)) + s


def read_lenenc_int(buf: bytes, pos: int) -> Tuple[int, int]:
    first = buf[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if first == 0xFD:
        return int.from_bytes(buf[pos + 1:pos + 4], "little"), pos + 4
    return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9


def read_lenenc_str(buf: bytes, pos: int) -> Tuple[bytes, int]:
    n, pos = read_lenenc_int(buf, pos)
    return buf[pos:pos + n], pos + n


def native_password_scramble(password: str, nonce: bytes) -> bytes:
    """mysql_native_password: SHA1(pwd) XOR SHA1(nonce + SHA1(SHA1(pwd)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(nonce + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


class PacketIO:
    """3-byte length + 1-byte sequence framing over a socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.seq = 0

    def read_packet(self) -> Optional[bytes]:
        header = self._read_n(4)
        if header is None:
            return None
        length = int.from_bytes(header[:3], "little")
        self.seq = (header[3] + 1) & 0xFF
        body = self._read_n(length)
        return body

    def _read_n(self, n: int) -> Optional[bytes]:
        chunks = []
        while n > 0:
            chunk = self.sock.recv(n)
            if not chunk:
                return None
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def write_packet(self, payload: bytes) -> None:
        offset = 0
        while True:
            chunk = payload[offset:offset + 0xFFFFFF]
            header = len(chunk).to_bytes(3, "little") + bytes([self.seq])
            self.seq = (self.seq + 1) & 0xFF
            self.sock.sendall(header + chunk)
            offset += len(chunk)
            if len(chunk) < 0xFFFFFF:
                break

    def reset_seq(self) -> None:
        self.seq = 0


# ---------------------------------------------------------------------------
# federated shims (reference: src/servers/src/mysql/federated.rs)
# ---------------------------------------------------------------------------

_FEDERATED_VARS = {
    "version_comment": "GreptimeDB TPU edition",
    "version": SERVER_VERSION,
    "max_allowed_packet": "16777216",
    "system_time_zone": "UTC",
    "time_zone": "SYSTEM",
    "session.time_zone": "SYSTEM",
    "auto_increment_increment": "1",
    "session.auto_increment_increment": "1",
    "sql_mode": ("ONLY_FULL_GROUP_BY,STRICT_TRANS_TABLES,"
                 "NO_ZERO_IN_DATE,NO_ZERO_DATE,"
                 "ERROR_FOR_DIVISION_BY_ZERO,NO_ENGINE_SUBSTITUTION"),
    "lower_case_table_names": "0",
    "transaction_isolation": "REPEATABLE-READ",
    "session.transaction_isolation": "REPEATABLE-READ",
    "tx_isolation": "REPEATABLE-READ",
    "session.tx_isolation": "REPEATABLE-READ",
    "wait_timeout": "28800",
    "interactive_timeout": "28800",
    "net_write_timeout": "60",
    "performance_schema": "0",
    "license": "Apache-2.0",
}

_SET_RE = re.compile(r"^\s*set\s+", re.I)
_SHOW_VARIABLES_RE = re.compile(r"^\s*show\s+(session\s+|global\s+)?"
                                r"variables", re.I)
_SHOW_COLLATION_RE = re.compile(r"^\s*show\s+(collation|character\s+set)",
                                re.I)
_SELECT_VAR_RE = re.compile(r"^\s*select\s+@@([\w.]+)\s*(;)?\s*$", re.I)
_SELECT_VERSION_RE = re.compile(r"^\s*select\s+version\(\)\s*(;)?\s*$", re.I)
_SELECT_DATABASE_RE = re.compile(r"^\s*select\s+database\(\)\s*(;)?\s*$",
                                 re.I)
_TX_RE = re.compile(r"^\s*(begin|start\s+transaction|commit|rollback)\b",
                    re.I)
_USE_RE = re.compile(r"^\s*use\s+`?(\w+)`?\s*(;)?\s*$", re.I)


def federated_answer(sql: str, ctx: QueryContext
                     ) -> Optional[Tuple[List[str], List[List]]]:
    """Fabricated (columns, rows) for client bootstrap queries, or None.
    An empty columns list means 'answer with plain OK'."""
    if _SET_RE.match(sql) or _TX_RE.match(sql):
        return [], []
    m = _SELECT_VAR_RE.match(sql)
    if m:
        var = m.group(1)
        val = _FEDERATED_VARS.get(var.lower())
        return [f"@@{var}"], [[val]]
    if _SELECT_VERSION_RE.match(sql):
        return ["version()"], [[SERVER_VERSION]]
    if _SELECT_DATABASE_RE.match(sql):
        return ["database()"], [[ctx.current_schema]]
    if _SHOW_VARIABLES_RE.match(sql):
        return ["Variable_name", "Value"], []
    if _SHOW_COLLATION_RE.match(sql):
        return ["Collation", "Charset", "Id", "Default", "Compiled",
                "Sortlen"], []
    return None


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _PreparedStatement:
    __slots__ = ("sql", "num_params")

    def __init__(self, sql: str):
        self.sql = sql
        self.num_params = sql.count("?")


class _Connection:
    def __init__(self, server: "MysqlServer", sock: socket.socket,
                 conn_id: int):
        self.server = server
        self.io = PacketIO(sock)
        self.sock = sock
        self.conn_id = conn_id
        self.ctx = QueryContext(channel=Channel.MYSQL)
        self.client_caps = 0
        self.stmts: Dict[int, _PreparedStatement] = {}
        self.next_stmt_id = 1

    # ---- packets out ----
    def send_ok(self, affected: int = 0, status: int =
                SERVER_STATUS_AUTOCOMMIT) -> None:
        self.io.write_packet(b"\x00" + lenenc_int(affected) + lenenc_int(0)
                             + struct.pack("<HH", status, 0))

    def send_err(self, message: str, errno: int = 1105,
                 sqlstate: str = "HY000") -> None:
        self.io.write_packet(b"\xff" + struct.pack("<H", errno) + b"#"
                             + sqlstate.encode()[:5].ljust(5, b"0")
                             + message.encode()[:512])

    def send_eof(self, status: int = SERVER_STATUS_AUTOCOMMIT) -> None:
        self.io.write_packet(b"\xfe" + struct.pack("<HH", 0, status))

    def _column_def(self, name: str, col_type: int,
                    charset: int = CHARSET_UTF8MB4,
                    length: int = 1024) -> bytes:
        return (lenenc_str(b"def") + lenenc_str(b"") + lenenc_str(b"")
                + lenenc_str(b"") + lenenc_str(name.encode())
                + lenenc_str(name.encode()) + b"\x0c"
                + struct.pack("<HIBHB", charset, length, col_type, 0, 31)
                + b"\x00\x00")

    def send_resultset(self, names: List[str], types: List[int],
                       rows, binary: bool = False) -> None:
        self.io.write_packet(lenenc_int(len(names)))
        for name, t in zip(names, types):
            charset = CHARSET_UTF8MB4 if t in (
                T_VAR_STRING, T_STRING, T_VARCHAR, T_BLOB) else CHARSET_BINARY
            self.io.write_packet(self._column_def(name, t, charset))
        self.send_eof()
        for row in rows:
            self.io.write_packet(
                self._binary_row(row) if binary else self._text_row(row))
        self.send_eof()

    @staticmethod
    def _text_row(row) -> bytes:
        out = b""
        for v in row:
            if v is None:
                out += b"\xfb"
            else:
                out += lenenc_str(str(v).encode())
        return out

    @staticmethod
    def _binary_row(row) -> bytes:
        """Binary protocol row with every column declared VAR_STRING (the
        prepared-statement emulation path, like the reference's rewrite)."""
        ncols = len(row)
        null_bitmap = bytearray((ncols + 9) // 8)
        values = b""
        for i, v in enumerate(row):
            if v is None:
                null_bitmap[(i + 2) // 8] |= 1 << ((i + 2) % 8)
            else:
                values += lenenc_str(str(v).encode())
        return b"\x00" + bytes(null_bitmap) + values

    # ---- handshake ----
    def handshake(self) -> bool:
        # Per-connection random salt, printable non-zero bytes (0x21-0x7E)
        # as real MySQL servers send: NUL would truncate the scramble in
        # libmysqlclient-style clients, and a deterministic salt would let a
        # sniffed mysql_native_password response be replayed.
        import secrets
        nonce = bytes(0x21 + secrets.randbelow(0x7F - 0x21)
                      for _ in range(20))
        caps = SERVER_CAPABILITIES
        if self.server.ssl_context is not None:
            caps |= CLIENT_SSL
        greeting = (b"\x0a" + SERVER_VERSION.encode() + b"\x00"
                    + struct.pack("<I", self.conn_id)
                    + nonce[:8] + b"\x00"
                    + struct.pack("<H", caps & 0xFFFF)
                    + bytes([CHARSET_UTF8MB4])
                    + struct.pack("<H", SERVER_STATUS_AUTOCOMMIT)
                    + struct.pack("<H", caps >> 16)
                    + bytes([21]) + b"\x00" * 10
                    + nonce[8:20] + b"\x00"
                    + b"mysql_native_password\x00")
        self.io.write_packet(greeting)
        resp = self.io.read_packet()
        if resp is None:
            return False
        client_caps = struct.unpack_from("<I", resp, 0)[0]
        if client_caps & CLIENT_SSL and self.server.ssl_context is not None:
            # SSLRequest is a truncated handshake response; upgrade now
            self.sock = self.server.ssl_context.wrap_socket(
                self.sock, server_side=True)
            self.io.sock = self.sock
            resp = self.io.read_packet()
            if resp is None:
                return False
            client_caps = struct.unpack_from("<I", resp, 0)[0]
        self.client_caps = client_caps
        pos = 4 + 4 + 1 + 23
        end = resp.index(b"\x00", pos)
        username = resp[pos:end].decode()
        pos = end + 1
        if client_caps & CLIENT_PLUGIN_AUTH_LENENC:
            auth, pos = read_lenenc_str(resp, pos)
        elif client_caps & CLIENT_SECURE_CONNECTION:
            alen = resp[pos]
            auth = resp[pos + 1:pos + 1 + alen]
            pos += 1 + alen
        else:
            end = resp.index(b"\x00", pos)
            auth = resp[pos:end]
            pos = end + 1
        database = None
        if client_caps & CLIENT_CONNECT_WITH_DB and pos < len(resp):
            end = resp.index(b"\x00", pos)
            database = resp[pos:end].decode()
            pos = end + 1

        if not self._check_auth(username, auth, nonce):
            self.send_err("Access denied for user "
                          f"'{username}'", errno=1045, sqlstate="28000")
            return False
        self.ctx.username = username
        if database:
            self.ctx.set_current_schema(database)
        self.send_ok()
        return True

    def _check_auth(self, username: str, auth: bytes, nonce: bytes) -> bool:
        provider = self.server.user_provider
        if provider is None:
            return True
        password = provider.plain_password(username)
        if password is None:
            # no stored secret (e.g. noop provider): defer to authenticate
            return provider.authenticate(username, "")
        expected = native_password_scramble(password, nonce)
        return auth == expected

    # ---- command loop ----
    def run(self) -> None:
        try:
            if not self.handshake():
                return
            while True:
                self.io.reset_seq()
                packet = self.io.read_packet()
                if packet is None or packet[0] == COM_QUIT:
                    return
                self.dispatch(packet)
        except (ConnectionError, OSError):
            pass
        except Exception:  # noqa: BLE001
            logger.exception("mysql connection %d crashed", self.conn_id)
        finally:
            try:
                self.sock.close()
            except OSError:
                pass

    def dispatch(self, packet: bytes) -> None:
        cmd, body = packet[0], packet[1:]
        if cmd == COM_PING:
            self.send_ok()
        elif cmd == COM_INIT_DB:
            self.ctx.set_current_schema(body.decode())
            self.send_ok()
        elif cmd == COM_QUERY:
            self.handle_query(body.decode())
        elif cmd == COM_FIELD_LIST:
            self.send_eof()
        elif cmd == COM_STMT_PREPARE:
            self.handle_stmt_prepare(body.decode())
        elif cmd == COM_STMT_EXECUTE:
            self.handle_stmt_execute(body)
        elif cmd == COM_STMT_CLOSE:
            self.stmts.pop(struct.unpack_from("<I", body, 0)[0], None)
        elif cmd == COM_STMT_RESET:
            self.send_ok()
        elif cmd == COM_PROCESS_KILL:
            # `mysqladmin kill` / the wire form of KILL <id>: same
            # registry and same clean-error semantics as the SQL path
            from ..common import process_list
            pid = struct.unpack_from("<I", body, 0)[0]
            try:
                process_list.REGISTRY.kill(pid)
            except GreptimeError as e:
                self.send_err(str(e), errno=1094)  # ER_NO_SUCH_THREAD
                return
            self.send_ok()
        else:
            self.send_err(f"unsupported command 0x{cmd:02x}", errno=1047)

    def handle_query(self, sql: str, binary: bool = False) -> None:
        m = _USE_RE.match(sql)
        if m:
            self.ctx.set_current_schema(m.group(1))
            self.send_ok()
            return
        fed = federated_answer(sql, self.ctx)
        if fed is not None:
            names, rows = fed
            if not names:
                self.send_ok()
            else:
                self.send_resultset(names, [T_VAR_STRING] * len(names),
                                    rows, binary=binary)
            return
        try:
            outputs = self.server.instance.do_query(sql, self.ctx)
        except GreptimeError as e:
            from ..errors import OverloadedError
            if isinstance(e, OverloadedError):
                # clean server-busy: ER_CON_COUNT_ERROR is the MySQL
                # error clients already treat as "back off and retry"
                self.send_err(str(e), errno=1040)
                return
            self.send_err(str(e))
            return
        except Exception as e:  # noqa: BLE001
            logger.exception("mysql query failed: %s", sql)
            self.send_err(str(e))
            return
        out = outputs[-1]
        if not out.is_batches:
            self.send_ok(affected=out.affected_rows or 0)
            return
        batches = out.batches
        if not batches:
            self.send_ok()
            return
        schema = batches[0].schema
        names = schema.names()
        types = [_mysql_type(c.dtype) for c in schema.column_schemas]
        if binary:
            types = [T_VAR_STRING] * len(names)
        rows = (self._format_row(schema, row)
                for b in batches for row in b.rows())
        self.send_resultset(names, types, rows, binary=binary)

    @staticmethod
    def _format_row(schema, row) -> List:
        out = []
        for col, v in zip(schema.column_schemas, row):
            if v is None:
                out.append(None)
            elif col.dtype.is_timestamp:
                from ..common.time import Timestamp
                out.append(Timestamp(v, col.dtype.time_unit).to_datetime()
                           .strftime("%Y-%m-%d %H:%M:%S.%f")[:-3])
            elif isinstance(v, bool):
                out.append(1 if v else 0)
            else:
                out.append(v)
        return out

    # ---- prepared statements (emulation) ----
    def handle_stmt_prepare(self, sql: str) -> None:
        stmt = _PreparedStatement(sql)
        stmt_id = self.next_stmt_id
        self.next_stmt_id += 1
        self.stmts[stmt_id] = stmt
        self.io.write_packet(b"\x00" + struct.pack("<I", stmt_id)
                             + struct.pack("<HH", 0, stmt.num_params)
                             + b"\x00" + struct.pack("<H", 0))
        if stmt.num_params:
            for _ in range(stmt.num_params):
                self.io.write_packet(self._column_def("?", T_VAR_STRING))
            self.send_eof()

    def handle_stmt_execute(self, body: bytes) -> None:
        stmt_id = struct.unpack_from("<I", body, 0)[0]
        stmt = self.stmts.get(stmt_id)
        if stmt is None:
            self.send_err(f"unknown statement {stmt_id}", errno=1243)
            return
        pos = 4 + 1 + 4
        params: List = []
        if stmt.num_params:
            nbytes = (stmt.num_params + 7) // 8
            null_bitmap = body[pos:pos + nbytes]
            pos += nbytes
            bound = body[pos]
            pos += 1
            types = []
            if bound:
                for _ in range(stmt.num_params):
                    types.append(struct.unpack_from("<H", body, pos)[0])
                    pos += 2
            else:
                types = [T_VAR_STRING] * stmt.num_params
            for i in range(stmt.num_params):
                if null_bitmap[i // 8] & (1 << (i % 8)):
                    params.append(None)
                    continue
                v, pos = _read_binary_value(body, pos, types[i] & 0xFF)
                params.append(v)
        sql = _substitute_params(stmt.sql, params)
        self.handle_query(sql, binary=True)


def _read_binary_value(buf: bytes, pos: int, t: int) -> Tuple[object, int]:
    if t == T_NULL:
        return None, pos
    if t == T_TINY:
        return struct.unpack_from("<b", buf, pos)[0], pos + 1
    if t == T_SHORT:
        return struct.unpack_from("<h", buf, pos)[0], pos + 2
    if t == T_LONG:
        return struct.unpack_from("<i", buf, pos)[0], pos + 4
    if t == T_LONGLONG:
        return struct.unpack_from("<q", buf, pos)[0], pos + 8
    if t == T_FLOAT:
        return struct.unpack_from("<f", buf, pos)[0], pos + 4
    if t == T_DOUBLE:
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if t in (T_TIMESTAMP, T_DATETIME):
        n = buf[pos]
        pos += 1
        fields = buf[pos:pos + n]
        pos += n
        if n == 0:
            return "0000-00-00 00:00:00", pos
        year, month, day = struct.unpack_from("<HBB", fields, 0)
        h = m = s = us = 0
        if n >= 7:
            h, m, s = fields[4], fields[5], fields[6]
        if n == 11:
            us = struct.unpack_from("<I", fields, 7)[0]
        return (f"{year:04d}-{month:02d}-{day:02d} "
                f"{h:02d}:{m:02d}:{s:02d}.{us:06d}"), pos
    # string-ish types: lenenc
    raw, pos = read_lenenc_str(buf, pos)
    return raw.decode(), pos


def _substitute_params(sql: str, params: List) -> str:
    """Client-side parameter substitution (the reference emulates prepared
    statements the same way through opensrv)."""
    out = []
    it = iter(params)
    in_str = False
    for ch in sql:
        if ch == "'":
            in_str = not in_str
            out.append(ch)
        elif ch == "?" and not in_str:
            v = next(it)
            if v is None:
                out.append("NULL")
            elif isinstance(v, str):
                escaped = v.replace("'", "''")
                out.append(f"'{escaped}'")
            else:
                out.append(repr(v))
        else:
            out.append(ch)
    return "".join(out)


def _mysql_type(dtype) -> int:
    if dtype.is_timestamp:
        return T_DATETIME
    if dtype.is_string:
        return T_VAR_STRING
    if dtype.is_float:
        return T_DOUBLE
    if dtype.is_boolean:
        return T_TINY
    return T_LONGLONG


class MysqlServer:
    """Threaded MySQL protocol listener over a frontend instance."""

    def __init__(self, instance, host: str = "127.0.0.1", port: int = 0,
                 user_provider=None, ssl_context: Optional[
                     ssl_mod.SSLContext] = None):
        self.instance = instance
        self.user_provider = user_provider
        self.ssl_context = ssl_context
        self._next_conn_id = 1
        self._lock = threading.Lock()
        server_self = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with server_self._lock:
                    conn_id = server_self._next_conn_id
                    server_self._next_conn_id += 1
                _Connection(server_self, self.request, conn_id).run()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._tcp = Server((host, port), Handler)
        self.port = self._tcp.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def serve_in_background(self) -> threading.Thread:
        from ..common.runtime import new_thread
        self._thread = new_thread(self._tcp.serve_forever, daemon=True,
                                  name="mysql-server",
                                  propagate_context=False)
        self._thread.start()
        return self._thread

    # CLI lifecycle alias (cmd/main.py starts all servers uniformly)
    start = serve_in_background

    @property
    def host(self) -> str:
        return self._tcp.server_address[0]

    def shutdown(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
