"""TLS configuration for protocol servers.

Reference behavior: src/servers/src/tls.rs:240 — `TlsOption` with modes
disable | prefer | require, certificate + key paths, building the
server-side TLS config consumed by the MySQL and Postgres listeners
(both of which upgrade mid-handshake: MySQL via the SSLRequest
capability, Postgres via the SSLRequest startup message).
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass
from typing import Optional


@dataclass
class TlsOption:
    mode: str = "disable"             # disable | prefer | require
    cert_path: Optional[str] = None
    key_path: Optional[str] = None

    def setup(self) -> Optional[ssl.SSLContext]:
        """Build the server SSLContext, or None when disabled."""
        if self.mode == "disable":
            return None
        if not self.cert_path or not self.key_path:
            raise ValueError(
                f"tls mode {self.mode!r} needs cert_path and key_path")
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_path, self.key_path)
        return ctx

    @staticmethod
    def from_config(doc: dict) -> "TlsOption":
        return TlsOption(mode=doc.get("mode", "disable"),
                         cert_path=doc.get("cert_path"),
                         key_path=doc.get("key_path"))


def make_self_signed(cert_path: str, key_path: str,
                     common_name: str = "greptimedb-tpu") -> None:
    """Generate a self-signed certificate (tests / dev bootstrap)."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName("localhost")]), critical=False)
            .sign(key, hashes.SHA256()))
    with open(key_path, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
