"""SQL query interceptor hooks.

Reference behavior: src/servers/src/interceptor.rs:26 —
`SqlQueryInterceptor` plugin with pre/post hooks around parse and
execute; every protocol frontend consults the plugin chain so operators
can rewrite, audit, or reject queries without touching the engine.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..query.output import Output
from ..session import QueryContext


class SqlQueryInterceptor:
    """Override any subset of hooks; raise to reject the query."""

    def pre_parsing(self, sql: str, ctx: QueryContext) -> str:
        """May rewrite the raw SQL before parsing."""
        return sql

    def post_parsing(self, statements: List, ctx: QueryContext) -> List:
        """May rewrite the parsed statement list."""
        return statements

    def pre_execute(self, statement, ctx: QueryContext) -> None:
        """Called before executing each statement."""

    def post_execute(self, output: Output, ctx: QueryContext) -> Output:
        """May replace each statement's output."""
        return output


class InterceptorChain(SqlQueryInterceptor):
    def __init__(self, interceptors: Sequence[SqlQueryInterceptor] = ()):
        self.interceptors = list(interceptors)

    def append(self, interceptor: SqlQueryInterceptor) -> None:
        self.interceptors.append(interceptor)

    def pre_parsing(self, sql, ctx):
        for i in self.interceptors:
            sql = i.pre_parsing(sql, ctx)
        return sql

    def post_parsing(self, statements, ctx):
        for i in self.interceptors:
            statements = i.post_parsing(statements, ctx)
        return statements

    def pre_execute(self, statement, ctx):
        for i in self.interceptors:
            i.pre_execute(statement, ctx)

    def post_execute(self, output, ctx):
        for i in self.interceptors:
            output = i.post_execute(output, ctx)
        return output
