"""gRPC service façade for the CLI.

Reference behavior: src/servers/src/grpc/ — tonic `GreptimeService` whose
query results stream over Arrow Flight `do_get` (flight.rs:40-120). In
this build the Flight endpoint *is* the gRPC service (Flight rides gRPC);
`GrpcServer` adapts `FlightFrontendServer` to the uniform CLI server
lifecycle (start/shutdown, addr string).
"""

from __future__ import annotations

from .flight import FlightFrontendServer


class GrpcServer:
    def __init__(self, instance, user_provider=None,
                 addr: str = "127.0.0.1:4001"):
        host, _, port = addr.partition(":")
        self.host = host or "127.0.0.1"
        self._flight = FlightFrontendServer(
            instance, f"grpc://{self.host}:{int(port or 0)}")
        self.user_provider = user_provider

    @property
    def port(self) -> int:
        return self._flight.port

    def start(self):
        return self._flight.serve_in_background()

    serve_in_background = start

    def shutdown(self) -> None:
        self._flight.shutdown()
