"""Recursive-descent SQL parser producing greptimedb_tpu.sql.ast nodes.

Grammar follows the reference's sqlparser-rs dialect plus the GreptimeDB
extensions (src/sql/src/parsers/): TIME INDEX column option and constraint,
PARTITION BY RANGE COLUMNS with MAXVALUE bounds, ENGINE=/WITH() table
options, TQL EVAL/EXPLAIN/ANALYZE, COPY TO/FROM.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from .ast import *  # noqa: F401,F403
from .ast import (
    AddColumn, Admin, AlterTable, Between, BinaryOp, Case, Cast, Column,
    ColumnDef,
    Copy, CreateDatabase, CreateFlow, CreateTable, Delete, DescribeTable,
    DropColumn, DropDatabase, DropFlow, DropTable, Explain, Expr,
    FunctionCall, InList, Insert, Interval, IsNull, Join, Kill, Literal,
    ObjectName, PartitionEntry, Partitions, Placeholder, Query, RenameTable,
    SelectItem, SetQuery, SetVariable, ShowCreateTable, ShowDatabases,
    ShowFlows, ShowProcessList, ShowTables, ShowVariable, Star, Statement,
    Subquery, TableRef, Tql, TruncateTable, UnaryOp, Use,
)
from ..errors import SyntaxError_
from .tokenizer import EOF, IDENT, NUMBER, OP, QIDENT, STRING, Token, tokenize


class ParserError(SyntaxError_, ValueError):
    """SQL parse failure. Joins the errors.* taxonomy (INVALID_SYNTAX)
    so a parse error crossing any protocol boundary carries a real
    status code (HTTP 400, not a generic 500 — the greptlint GL10
    burn-down); still a ValueError for the pre-taxonomy `except
    ValueError` call sites."""


# keywords that terminate a SELECT item list's expression context
_CLAUSE_KEYWORDS = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "UNION",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "CROSS", "ON", "AS", "ASC",
    "DESC", "AND", "OR", "NOT", "THEN", "ELSE", "END", "WHEN",
}

_TYPE_KEYWORDS = {
    "BOOLEAN", "BOOL", "TINYINT", "SMALLINT", "INT", "INTEGER", "BIGINT",
    "FLOAT", "DOUBLE", "REAL", "STRING", "TEXT", "VARCHAR", "CHAR", "BINARY",
    "VARBINARY", "BLOB", "BYTEA", "DATE", "DATETIME", "TIMESTAMP", "INT8",
    "INT16", "INT32", "INT64", "UINT8", "UINT16", "UINT32", "UINT64",
    "FLOAT32", "FLOAT64", "TIMESTAMP_S", "TIMESTAMP_MS", "TIMESTAMP_US",
    "TIMESTAMP_NS",
}


def parse_sql(sql: str) -> Statement:
    """Parse a single SQL statement."""
    stmts = parse_statements(sql)
    if len(stmts) != 1:
        raise ParserError(f"expected one statement, got {len(stmts)}")
    return stmts[0]


def parse_statements(sql: str) -> List[Statement]:
    stmt = _fast_parse_insert(sql)
    if stmt is not None:
        return [stmt]
    return Parser(sql).parse_statements()


# bulk INSERT ... VALUES hot path: one C-speed regex scan instead of the
# general tokenizer (which builds ~9 Token objects per row — tokenize alone
# cost 31ms per 2000-row statement; this scanner takes ~2ms)
import re as _re2  # noqa: E402

_INS_HEAD = _re2.compile(
    r"""\s*INSERT\s+INTO\s+
        (?P<name>[A-Za-z_$][\w$]*(?:\.[A-Za-z_$][\w$]*){0,2}
         |"[^"]+"|`[^`]+`)\s*
        (?:\(\s*(?P<cols>[^)]*?)\s*\)\s*)?
        VALUES\s*""", _re2.I | _re2.X)
_INS_VALUE = _re2.compile(
    r"""\s*(?:
        (?P<str>'(?:[^'\\]|''|\\.)*')
      | (?P<num>[-+]?(?:0[xX][0-9a-fA-F]+|(?:\d+\.?\d*|\.\d+)
                      (?:[eE][+-]?\d+)?))
      | (?P<kw>[Nn][Uu][Ll][Ll]|[Tt][Rr][Uu][Ee]|[Ff][Aa][Ll][Ss][Ee])
        )\s*(?P<sep>[,)])""", _re2.X)
_INS_ROW_SEP = _re2.compile(r"\s*(?:,\s*\(|\(|;?\s*$)")
_SIMPLE_INS_STR = _re2.compile(r"'[^'\\]*'\Z")


def _fast_parse_insert(sql: str):
    """Parse `INSERT INTO t [(cols)] VALUES (...), ...` without the
    tokenizer. Returns None (fall back to the grammar) on anything
    fancier: expressions, functions, placeholders, INSERT..SELECT."""
    m = _INS_HEAD.match(sql)
    if m is None:
        return None
    name = m.group("name")
    if name[0] in "\"`":
        parts = [name[1:-1]]
    else:
        parts = name.split(".")
    columns: List[str] = []
    if m.group("cols"):
        for c in m.group("cols").split(","):
            c = c.strip()
            if c and c[0] in "\"`":
                c = c[1:-1]
            if not c or not _re2.fullmatch(r"[\w$]+|\S+", c):
                return None
            columns.append(c)
    pos = m.end()
    n = len(sql)
    rows: List[List[Expr]] = []
    match_row = _INS_ROW_SEP.match
    match_val = _INS_VALUE.match
    lit = Literal
    while True:
        rs = match_row(sql, pos)
        if rs is None:
            return None
        tok = rs.group().strip()
        if tok in ("", ";"):
            if rs.end() < n or not rows:
                return None
            return Insert(ObjectName(parts), columns, rows)
        pos = rs.end()
        row: List[Expr] = []
        append = row.append
        while True:
            vm = match_val(sql, pos)
            if vm is None:
                return None          # expression / DEFAULT / empty tuple
            pos = vm.end()
            s, num, kw, sep = vm.group("str", "num", "kw", "sep")
            if num is not None:
                low = num.lower()
                if "." in num or "e" in low:
                    v = float(num)
                elif "x" in low:
                    v = int(num, 16)
                else:
                    v = int(num)
                append(lit(v, "number"))
            elif s is not None:
                if _SIMPLE_INS_STR.match(s):
                    append(lit(s[1:-1], "string"))
                else:
                    from .tokenizer import _read_quoted
                    val, _ = _read_quoted(s, 0, "'")
                    append(lit(val, "string"))
            else:
                kw = kw.upper()
                append(lit(None, "null") if kw == "NULL"
                       else lit(kw == "TRUE", "bool"))
            if sep == ")":
                break
        rows.append(row)


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.toks = tokenize(sql)
        self.i = 0
        self._placeholders = 0

    # ---- token helpers ----
    def peek(self, ahead: int = 0) -> Token:
        j = min(self.i + ahead, len(self.toks) - 1)
        return self.toks[j]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != EOF:
            self.i += 1
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == IDENT and t.upper() in words

    def match_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.next()
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.match_kw(word):
            t = self.peek()
            raise ParserError(
                f"expected {word}, found {t.value!r} at offset {t.pos}")

    def match_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == OP and t.value == op:
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.match_op(op):
            t = self.peek()
            raise ParserError(
                f"expected {op!r}, found {t.value!r} at offset {t.pos}")

    def parse_identifier(self) -> str:
        t = self.peek()
        if t.kind in (IDENT, QIDENT):
            self.next()
            return t.value
        raise ParserError(f"expected identifier, found {t.value!r} at {t.pos}")

    def parse_object_name(self) -> ObjectName:
        parts = [self.parse_identifier()]
        while self.match_op("."):
            parts.append(self.parse_identifier())
        if len(parts) > 3:
            raise ParserError(f"too many name parts: {'.'.join(parts)}")
        return ObjectName(parts)

    # ---- statements ----
    def parse_statements(self) -> List[Statement]:
        stmts: List[Statement] = []
        while True:
            while self.match_op(";"):
                pass
            if self.peek().kind == EOF:
                return stmts
            stmts.append(self.parse_statement())
            if not (self.match_op(";") or self.peek().kind == EOF):
                t = self.peek()
                raise ParserError(
                    f"unexpected {t.value!r} at offset {t.pos}")

    def parse_statement(self) -> Statement:
        t = self.peek()
        kw = t.upper() if t.kind == IDENT else ""
        if kw == "SELECT" or (t.kind == OP and t.value == "("):
            return self.parse_query()
        if kw == "WITH":
            return self.parse_with()
        if kw == "CREATE":
            return self.parse_create()
        if kw == "DROP":
            return self.parse_drop()
        if kw == "INSERT":
            return self.parse_insert()
        if kw == "DELETE":
            return self.parse_delete()
        if kw == "ALTER":
            return self.parse_alter()
        if kw == "SHOW":
            return self.parse_show()
        if kw in ("DESCRIBE", "DESC"):
            self.next()
            self.match_kw("TABLE")
            return DescribeTable(table=self.parse_object_name())
        if kw == "USE":
            self.next()
            return Use(database=self.parse_identifier())
        if kw == "TQL":
            return self.parse_tql()
        if kw == "COPY":
            return self.parse_copy()
        if kw == "EXPLAIN":
            return self.parse_explain()
        if kw == "SET":
            return self.parse_set()
        if kw == "TRUNCATE":
            self.next()
            self.match_kw("TABLE")
            return TruncateTable(name=self.parse_object_name())
        if kw == "KILL":
            return self.parse_kill()
        if kw == "ADMIN":
            return self.parse_admin()
        raise ParserError(f"unsupported statement start: {t.value!r} at {t.pos}")

    def parse_admin(self) -> Admin:
        """Elastic region administration:

        - ADMIN MIGRATE REGION <table> <region> TO <node_id>
        - ADMIN SPLIT REGION <table> <region> [AT <literal>]
        - ADMIN REBALANCE [TABLE <table>]
        - ADMIN ADD REPLICA <table> <region> TO <node_id>
        - ADMIN REMOVE REPLICA <table> <region> FROM <node_id>

        Plus table maintenance (storage surface, both deployments):

        - ADMIN FLUSH TABLE <table>
        - ADMIN COMPACT TABLE <table>

        And the observability surfaces:

        - ADMIN SHOW TRACE '<trace_id>'  ('last' = most recently
          retained trace on this frontend)
        - ADMIN SHOW PROFILE '<query_id>'|'<trace_id>'|'last' — the
          continuous profiler's per-node self/total frame tree
        """
        self.expect_kw("ADMIN")
        if self.match_kw("SHOW"):
            what = "TRACE" if self.match_kw("TRACE") else \
                ("PROFILE" if self.match_kw("PROFILE") else None)
            if what is None:
                t = self.peek()
                raise ParserError(
                    f"expected TRACE or PROFILE after ADMIN SHOW, "
                    f"found {t.value!r} at {t.pos}")
            t = self.next()
            if t.kind != STRING:
                raise ParserError(
                    f"ADMIN SHOW {what} needs a quoted id (or 'last'), "
                    f"found {t.value!r} at {t.pos}")
            kind = "show_trace" if what == "TRACE" else "show_profile"
            return Admin(kind=kind, trace_id=str(t.value))
        if self.match_kw("FLUSH"):
            self.expect_kw("TABLE")
            return Admin(kind="flush_table",
                         table=self.parse_object_name())
        if self.match_kw("COMPACT"):
            self.expect_kw("TABLE")
            return Admin(kind="compact_table",
                         table=self.parse_object_name())
        if self.match_kw("REBALANCE"):
            table = None
            if self.match_kw("TABLE"):
                table = self.parse_object_name()
            return Admin(kind="rebalance", table=table)
        if self.match_kw("MIGRATE"):
            self.expect_kw("REGION")
            table = self.parse_object_name()
            region = self._parse_int("region number")
            self.expect_kw("TO")
            target = self._parse_int("target datanode id")
            return Admin(kind="migrate_region", table=table,
                         region=region, target_node=target)
        if self.match_kw("SPLIT"):
            self.expect_kw("REGION")
            table = self.parse_object_name()
            region = self._parse_int("region number")
            at_value = None
            if self.match_kw("AT"):
                at_value = self._parse_literal_value()
                if at_value is None:
                    raise ParserError("ADMIN SPLIT ... AT needs a "
                                      "concrete literal, not NULL")
            return Admin(kind="split_region", table=table, region=region,
                         at_value=at_value)
        if self.match_kw("ADD"):
            self.expect_kw("REPLICA")
            table = self.parse_object_name()
            region = self._parse_int("region number")
            self.expect_kw("TO")
            target = self._parse_int("target datanode id")
            return Admin(kind="add_replica", table=table,
                         region=region, target_node=target)
        if self.match_kw("REMOVE"):
            self.expect_kw("REPLICA")
            table = self.parse_object_name()
            region = self._parse_int("region number")
            self.expect_kw("FROM")
            target = self._parse_int("replica datanode id")
            return Admin(kind="remove_replica", table=table,
                         region=region, target_node=target)
        t = self.peek()
        raise ParserError(
            f"expected MIGRATE REGION / SPLIT REGION / REBALANCE / "
            f"ADD REPLICA / REMOVE REPLICA / FLUSH TABLE / "
            f"COMPACT TABLE / SHOW TRACE / SHOW PROFILE "
            f"after ADMIN, found {t.value!r} at {t.pos}")

    def parse_kill(self) -> Kill:
        """KILL [QUERY] <id> — the id is the `id` column of
        information_schema.processes / SHOW PROCESSLIST."""
        self.expect_kw("KILL")
        self.match_kw("QUERY")
        t = self.next()
        if t.kind != NUMBER:
            raise ParserError(
                f"KILL expects a numeric query id, got {t.value!r} at "
                f"{t.pos}")
        return Kill(process_id=self._to_int(t))

    # ---- WITH (CTE) ----
    def parse_with(self) -> Statement:
        """WITH name [(cols)] AS (query) [, ...] SELECT ...

        CTEs are inlined as derived tables (the FROM-subquery form the
        planner already executes); each reference gets its own deep copy,
        so a CTE used twice behaves like two subqueries — the reference
        gets the same semantics from sqlparser-rs + DataFusion
        (src/sql/src/parsers/query_parser.rs via sqlparser::parse_query).
        """
        self.expect_kw("WITH")
        if self.match_kw("RECURSIVE"):
            raise ParserError("recursive CTEs are not supported")
        ctes: dict = {}
        while True:
            name = self.parse_identifier()
            cols: List[str] = []
            if self.match_op("("):
                cols.append(self.parse_identifier())
                while self.match_op(","):
                    cols.append(self.parse_identifier())
                self.expect_op(")")
            self.expect_kw("AS")
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            _inline_ctes(q, ctes)       # earlier CTEs visible to later ones
            if cols:
                _apply_cte_column_aliases(q, cols, name)
            if name.lower() in ctes:
                raise ParserError(f"duplicate CTE name {name!r}")
            ctes[name.lower()] = q
            if not self.match_op(","):
                break
        t = self.peek()
        if not (self.at_kw("SELECT") or (t.kind == OP and t.value == "(")):
            raise ParserError(
                f"expected SELECT after WITH clause, found {t.value!r}")
        body = self.parse_query()
        _inline_ctes(body, ctes)
        return body

    # ---- SELECT ----
    def parse_query(self) -> Query:
        q = self.parse_query_body()
        while self.match_kw("UNION"):
            all_ = bool(self.match_kw("ALL"))
            self.match_kw("DISTINCT")
            right = self.parse_query_body()
            q = SetQuery(left=q, right=right, all=all_)
        return self._query_tail(q)

    def parse_query_body(self) -> Query:
        """One SELECT core (or parenthesized query) without the
        ORDER/LIMIT tail — the tail binds to the outermost set op."""
        if self.match_op("("):
            q = self.parse_query()
            self.expect_op(")")
            return q
        self.expect_kw("SELECT")
        distinct = self.match_kw("DISTINCT")
        self.match_kw("ALL")
        projections = [self.parse_select_item()]
        while self.match_op(","):
            projections.append(self.parse_select_item())
        q = Query(projections=projections, distinct=distinct)
        if self.match_kw("FROM"):
            q.from_ = self.parse_table_ref()
            while True:
                join = self.parse_join_opt()
                if join is None:
                    break
                q.joins.append(join)
        if self.match_kw("WHERE"):
            q.where = self.parse_expr()
        if self.match_kw("GROUP"):
            self.expect_kw("BY")
            q.group_by.append(self.parse_expr())
            while self.match_op(","):
                q.group_by.append(self.parse_expr())
        if self.match_kw("HAVING"):
            q.having = self.parse_expr()
        return q

    def _query_tail(self, q: Query) -> Query:
        if self.match_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                e = self.parse_expr()
                asc = True
                if self.match_kw("DESC"):
                    asc = False
                else:
                    self.match_kw("ASC")
                nulls_first: Optional[bool] = None
                if self.match_kw("NULLS"):
                    if self.match_kw("FIRST"):
                        nulls_first = True
                    elif self.match_kw("LAST"):
                        nulls_first = False
                    else:
                        raise ParserError(
                            "expected FIRST or LAST after NULLS")
                q.order_by.append((e, asc))
                q.order_nulls.append(nulls_first)
                if not self.match_op(","):
                    break
        if self.match_kw("LIMIT"):
            q.limit = self._parse_int("LIMIT")
        if self.match_kw("OFFSET"):
            q.offset = self._parse_int("OFFSET")
        return q

    def _parse_int(self, what: str) -> int:
        t = self.next()
        if t.kind != NUMBER:
            raise ParserError(f"expected integer after {what}, got {t.value!r}")
        return self._to_int(t)

    @staticmethod
    def _to_int(t: Token) -> int:
        try:
            if t.value.lower().startswith("0x"):
                return int(t.value, 16)
            return int(t.value, 10)
        except ValueError as e:
            raise ParserError(f"invalid integer {t.value!r} at {t.pos}") from e

    def parse_select_item(self) -> SelectItem:
        t = self.peek()
        if t.kind == OP and t.value == "*":
            self.next()
            return SelectItem(Star())
        expr = self.parse_expr()
        alias = None
        if self.match_kw("AS"):
            alias = self.parse_identifier()
        else:
            nt = self.peek()
            if nt.kind == QIDENT or (nt.kind == IDENT and
                                     nt.upper() not in _CLAUSE_KEYWORDS):
                alias = self.parse_identifier()
        return SelectItem(expr, alias)

    def parse_table_ref(self) -> TableRef:
        if self.match_op("("):
            sub = self.parse_query()
            self.expect_op(")")
            alias = None
            self.match_kw("AS")
            nt = self.peek()
            if nt.kind in (IDENT, QIDENT) and nt.upper() not in _CLAUSE_KEYWORDS:
                alias = self.parse_identifier()
            return TableRef(subquery=sub, alias=alias)
        name = self.parse_object_name()
        alias = None
        if self.match_kw("AS"):
            alias = self.parse_identifier()
        else:
            nt = self.peek()
            if nt.kind == QIDENT or (nt.kind == IDENT and
                                     nt.upper() not in _CLAUSE_KEYWORDS and
                                     nt.upper() not in ("SET",)):
                alias = self.parse_identifier()
        return TableRef(name=name, alias=alias)

    def parse_join_opt(self) -> Optional[Join]:
        kind = None
        if self.match_kw("CROSS"):
            kind = "cross"
        elif self.match_kw("INNER"):
            kind = "inner"
        elif self.match_kw("LEFT"):
            self.match_kw("OUTER")
            kind = "left"
        elif self.match_kw("RIGHT"):
            self.match_kw("OUTER")
            kind = "right"
        elif self.match_kw("FULL"):
            self.match_kw("OUTER")
            kind = "full"
        elif self.at_kw("JOIN"):
            kind = "inner"
        elif self.match_op(","):
            kind = "cross"
            return Join(kind, self.parse_table_ref())
        if kind is None:
            return None
        self.expect_kw("JOIN")
        table = self.parse_table_ref()
        on = None
        if self.match_kw("ON"):
            on = self.parse_expr()
        return Join(kind, table, on)

    # ---- expressions (precedence climbing) ----
    def parse_expr(self) -> Expr:
        return self.parse_or()

    def parse_or(self) -> Expr:
        left = self.parse_and()
        while self.match_kw("OR"):
            left = BinaryOp("or", left, self.parse_and())
        return left

    def parse_and(self) -> Expr:
        left = self.parse_not()
        while self.match_kw("AND"):
            left = BinaryOp("and", left, self.parse_not())
        return left

    def parse_not(self) -> Expr:
        if self.match_kw("NOT"):
            return UnaryOp("not", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> Expr:
        left = self.parse_additive()
        while True:
            t = self.peek()
            if t.kind == OP and t.value in ("=", "!=", "<>", "<", "<=", ">",
                                            ">=", "<=>"):
                self.next()
                op = {"<>": "!=", "<=>": "="}.get(t.value, t.value)
                left = BinaryOp(op, left, self.parse_additive())
                continue
            if t.kind == IDENT:
                kw = t.upper()
                negated = False
                save = self.i
                if kw == "NOT":
                    self.next()
                    nxt = self.peek()
                    if nxt.kind == IDENT and nxt.upper() in (
                            "LIKE", "ILIKE", "IN", "BETWEEN", "REGEXP"):
                        negated = True
                        kw = nxt.upper()
                        t = nxt
                    else:
                        self.i = save
                        break
                if kw in ("LIKE", "ILIKE"):
                    self.next()
                    node = BinaryOp(kw.lower(), left, self.parse_additive())
                    left = UnaryOp("not", node) if negated else node
                    continue
                if kw == "REGEXP":
                    self.next()
                    node = BinaryOp("regexp", left, self.parse_additive())
                    left = UnaryOp("not", node) if negated else node
                    continue
                if kw == "IN":
                    self.next()
                    self.expect_op("(")
                    if self.at_kw("SELECT"):
                        sub = self.parse_query()
                        self.expect_op(")")
                        left = InList(left, [Subquery(sub)], negated)
                        continue
                    items = [self.parse_expr()]
                    while self.match_op(","):
                        items.append(self.parse_expr())
                    self.expect_op(")")
                    left = InList(left, items, negated)
                    continue
                if kw == "BETWEEN":
                    self.next()
                    low = self.parse_additive()
                    self.expect_kw("AND")
                    high = self.parse_additive()
                    left = Between(left, low, high, negated)
                    continue
                if kw == "IS":
                    self.next()
                    neg = self.match_kw("NOT")
                    self.expect_kw("NULL")
                    left = IsNull(left, neg)
                    continue
            break
        return left

    def parse_additive(self) -> Expr:
        left = self.parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == OP and t.value in ("+", "-", "||"):
                self.next()
                left = BinaryOp(t.value, left, self.parse_multiplicative())
            else:
                return left

    def parse_multiplicative(self) -> Expr:
        left = self.parse_unary()
        while True:
            t = self.peek()
            if t.kind == OP and t.value in ("*", "/", "%"):
                self.next()
                left = BinaryOp(t.value, left, self.parse_unary())
            else:
                return left

    def parse_unary(self) -> Expr:
        if self.match_op("-"):
            return UnaryOp("-", self.parse_unary())
        if self.match_op("+"):
            return self.parse_unary()
        return self.parse_postfix()

    def parse_postfix(self) -> Expr:
        e = self.parse_primary()
        while self.match_op("::"):
            type_name = self._parse_type_name()
            e = Cast(e, type_name)
        return e

    def parse_primary(self) -> Expr:
        t = self.peek()
        if t.kind == NUMBER:
            self.next()
            txt = t.value
            if txt.lower().startswith("0x"):
                return Literal(int(txt, 16), "number")
            val = float(txt) if ("." in txt or "e" in txt.lower()) else int(txt)
            return Literal(val, "number")
        if t.kind == STRING:
            self.next()
            return Literal(t.value, "string")
        if t.kind == OP and t.value == "(":
            self.next()
            if self.at_kw("SELECT"):
                sub = self.parse_query()
                self.expect_op(")")
                return Subquery(sub)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == OP and t.value == "*":
            self.next()
            return Star()
        if t.kind == OP and t.value == "?":
            self.next()
            self._placeholders += 1
            return Placeholder(self._placeholders)
        if t.kind == QIDENT:
            return self._parse_compound_identifier()
        if t.kind == IDENT:
            kw = t.upper()
            if kw in ("TRUE", "FALSE"):
                self.next()
                return Literal(kw == "TRUE", "bool")
            if kw == "NULL":
                self.next()
                return Literal(None, "null")
            if kw == "INTERVAL":
                self.next()
                lit = self.next()
                if lit.kind != STRING:
                    raise ParserError("expected string after INTERVAL")
                unit_tok = self.peek()
                text = lit.value
                if unit_tok.kind == IDENT and unit_tok.upper() in (
                        "SECOND", "SECONDS", "MINUTE", "MINUTES", "HOUR",
                        "HOURS", "DAY", "DAYS", "MILLISECOND", "MILLISECONDS"):
                    self.next()
                    text = f"{text} {unit_tok.value}"
                return Interval(text)
            if kw == "CASE":
                return self._parse_case()
            if kw == "CAST":
                self.next()
                self.expect_op("(")
                e = self.parse_expr()
                self.expect_kw("AS")
                tn = self._parse_type_name()
                self.expect_op(")")
                return Cast(e, tn)
            if kw in ("DATE", "TIMESTAMP") and self.peek(1).kind == STRING:
                self.next()
                lit = self.next()
                return Cast(Literal(lit.value, "string"), kw.lower())
            if kw == "EXISTS" and self.peek(1).kind == OP and \
                    self.peek(1).value == "(":
                self.next()
                self.expect_op("(")
                sub = self.parse_query()
                self.expect_op(")")
                return FunctionCall("exists", [Subquery(sub)])
            if kw in _CLAUSE_KEYWORDS:
                raise ParserError(
                    f"unexpected keyword {t.value!r} at offset {t.pos} "
                    f"(quote it to use as an identifier)")
            return self._parse_compound_identifier()
        raise ParserError(f"unexpected token {t.value!r} at offset {t.pos}")

    def _parse_window_spec(self) -> WindowSpec:
        """OVER ( [PARTITION BY e,...] [ORDER BY e [ASC|DESC],...]
        [ROWS frame] ) — reference: DataFusion's window planning
        (src/query/src/datafusion.rs:61-232 delegates to it)."""
        self.expect_op("(")
        spec = WindowSpec()
        if self.match_kw("PARTITION"):
            self.expect_kw("BY")
            spec.partition_by.append(self.parse_expr())
            while self.match_op(","):
                spec.partition_by.append(self.parse_expr())
        if self.match_kw("ORDER"):
            self.expect_kw("BY")

            def one():
                e = self.parse_expr()
                asc = True
                if self.match_kw("DESC"):
                    asc = False
                elif self.match_kw("ASC"):
                    pass
                return (e, asc)
            spec.order_by.append(one())
            while self.match_op(","):
                spec.order_by.append(one())
        if self.at_kw("ROWS") or self.at_kw("RANGE"):
            kind = self.next().upper()
            if kind == "RANGE":
                raise ParserError("RANGE frames are not supported; "
                                  "use ROWS")

            def bound(default_side: int) -> Optional[int]:
                if self.match_kw("UNBOUNDED"):
                    if not (self.match_kw("PRECEDING") or
                            self.match_kw("FOLLOWING")):
                        raise ParserError("expected PRECEDING/FOLLOWING "
                                          "after UNBOUNDED")
                    return None
                if self.match_kw("CURRENT"):
                    self.expect_kw("ROW")
                    return 0
                n = self._parse_int("frame bound")
                if self.match_kw("PRECEDING"):
                    return -n
                if self.match_kw("FOLLOWING"):
                    return n
                raise ParserError("expected PRECEDING or FOLLOWING")
            if self.match_kw("BETWEEN"):
                lo = bound(-1)
                self.expect_kw("AND")
                hi = bound(1)
            else:
                lo = bound(-1)
                hi = 0
            spec.frame = (lo, hi)
        self.expect_op(")")
        return spec

    def _parse_case(self) -> Expr:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        whens: List[Tuple[Expr, Expr]] = []
        while self.match_kw("WHEN"):
            cond = self.parse_expr()
            self.expect_kw("THEN")
            whens.append((cond, self.parse_expr()))
        else_ = None
        if self.match_kw("ELSE"):
            else_ = self.parse_expr()
        self.expect_kw("END")
        return Case(operand, whens, else_)

    def _parse_compound_identifier(self) -> Expr:
        name = self.parse_identifier()
        # function call?
        if self.peek().kind == OP and self.peek().value == "(":
            self.next()
            distinct = self.match_kw("DISTINCT")
            args: List[Expr] = []
            if not (self.peek().kind == OP and self.peek().value == ")"):
                args.append(self.parse_expr())
                while self.match_op(","):
                    args.append(self.parse_expr())
            self.expect_op(")")
            fc = FunctionCall(name.lower(), args, distinct)
            if self.at_kw("OVER"):
                self.next()
                fc.over = self._parse_window_spec()
            return fc
        parts = [name]
        while self.peek().kind == OP and self.peek().value == ".":
            # a.b or a.*
            if self.peek(1).kind in (IDENT, QIDENT):
                self.next()
                parts.append(self.parse_identifier())
            elif self.peek(1).kind == OP and self.peek(1).value == "*":
                self.next()
                self.next()
                return Star(table=".".join(parts))
            else:
                break
        if len(parts) == 1:
            return Column(parts[0])
        return Column(parts[-1], table=".".join(parts[:-1]))

    def _parse_type_name(self) -> str:
        base = self.parse_identifier()
        out = base
        # TIMESTAMP(3), VARCHAR(255)
        if self.peek().kind == OP and self.peek().value == "(":
            self.next()
            inner = []
            while not (self.peek().kind == OP and self.peek().value == ")"):
                t = self.next()
                if t.kind == EOF:
                    raise ParserError(
                        f"unterminated type parameter list for {base!r}")
                inner.append(t.value)
            self.expect_op(")")
            if base.upper() == "TIMESTAMP":
                out = f"{base}({','.join(inner)})"
            # length params on varchar/char are ignored
        if self.at_kw("UNSIGNED"):
            self.next()
            out = f"{out} unsigned"
        return out

    # ---- CREATE ----
    def parse_create(self) -> Statement:
        self.expect_kw("CREATE")
        external = self.match_kw("EXTERNAL")
        if self.match_kw("DATABASE") or self.match_kw("SCHEMA"):
            ine = self._parse_if_not_exists()
            return CreateDatabase(self.parse_identifier(), ine)
        if self.at_kw("FLOW"):
            return self.parse_create_flow()
        self.expect_kw("TABLE")
        ine = self._parse_if_not_exists()
        name = self.parse_object_name()
        stmt = CreateTable(name=name, if_not_exists=ine, external=external)
        if self.match_op("("):
            self._parse_create_body(stmt)
        while True:
            if self.match_kw("ENGINE"):
                self.expect_op("=")
                stmt.engine = self.parse_identifier()
            elif self.match_kw("PARTITION"):
                self._parse_partitions(stmt)
            elif self.match_kw("WITH"):
                self.expect_op("(")
                stmt.options.update(self._parse_kv_list())
                self.expect_op(")")
            else:
                break
        # enforce TIME INDEX presence like the reference does for non-external
        if not stmt.external and stmt.columns and stmt.time_index is None:
            raise ParserError("missing TIME INDEX constraint in CREATE TABLE")
        return stmt

    def parse_create_flow(self) -> CreateFlow:
        """CREATE FLOW [IF NOT EXISTS] name [SINK TO table] AS SELECT ...
        (reference: GreptimeDB flow DDL, simplified — the SELECT must be
        a single-table aggregate over date_bin/date_trunc)."""
        self.expect_kw("FLOW")
        ine = self._parse_if_not_exists()
        name = self.parse_identifier()
        sink = None
        if self.match_kw("SINK"):
            self.expect_kw("TO")
            sink = self.parse_identifier()
        self.expect_kw("AS")
        start_pos = self.peek().pos
        if not self.at_kw("SELECT"):
            raise ParserError("expected SELECT after CREATE FLOW ... AS")
        query = self.parse_query()
        end_pos = self.peek().pos if self.peek().kind != EOF \
            else len(self.sql)
        raw = self.sql[start_pos:end_pos].strip().rstrip(";").strip()
        return CreateFlow(name=name, query=query, sink=sink,
                          if_not_exists=ine, raw_sql=raw)

    def _parse_if_not_exists(self) -> bool:
        if self.match_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            return True
        return False

    def _parse_create_body(self, stmt: CreateTable) -> None:
        while True:
            if self.match_kw("PRIMARY"):
                self.expect_kw("KEY")
                self.expect_op("(")
                while True:
                    stmt.primary_keys.append(self.parse_identifier())
                    if not self.match_op(","):
                        break
                self.expect_op(")")
            elif self.at_kw("TIME") and self.peek(1).kind == IDENT and \
                    self.peek(1).upper() == "INDEX":
                # TIME INDEX(col) — lookahead so a column named `time` works
                self.next()
                self.next()
                self.expect_op("(")
                stmt.time_index = self.parse_identifier()
                self.expect_op(")")
            elif self.at_kw("TIMESTAMP_INDEX") and self.peek(1).kind == OP \
                    and self.peek(1).value == "(":
                self.next()
                self.expect_op("(")
                stmt.time_index = self.parse_identifier()
                self.expect_op(")")
            else:
                col = self._parse_column_def()
                stmt.columns.append(col)
                if col.is_time_index:
                    if stmt.time_index is not None and stmt.time_index != col.name:
                        raise ParserError("multiple TIME INDEX columns")
                    stmt.time_index = col.name
                if col.is_primary_key and col.name not in stmt.primary_keys:
                    stmt.primary_keys.append(col.name)
            if self.match_op(","):
                continue
            self.expect_op(")")
            break
        if stmt.time_index and stmt.time_index not in [c.name for c in stmt.columns]:
            raise ParserError(f"TIME INDEX column {stmt.time_index!r} not defined")
        for pk in stmt.primary_keys:
            if pk not in [c.name for c in stmt.columns]:
                raise ParserError(f"PRIMARY KEY column {pk!r} not defined")

    def _parse_column_def(self) -> ColumnDef:
        name = self.parse_identifier()
        type_name = self._parse_type_name()
        col = ColumnDef(name=name, type_name=type_name)
        while True:
            if self.match_kw("NOT"):
                self.expect_kw("NULL")
                col.nullable = False
            elif self.match_kw("NULL"):
                col.nullable = True
            elif self.match_kw("DEFAULT"):
                col.default = self.parse_expr()
            elif self.match_kw("TIME"):
                self.expect_kw("INDEX")
                col.is_time_index = True
                col.nullable = False
            elif self.match_kw("PRIMARY"):
                self.expect_kw("KEY")
                col.is_primary_key = True
            elif self.match_kw("COMMENT"):
                t = self.next()
                col.comment = t.value
            else:
                return col

    def _parse_partitions(self, stmt: CreateTable) -> None:
        # PARTITION BY RANGE COLUMNS (a, b) (PARTITION p0 VALUES LESS THAN (...), ...)
        # PARTITION BY HASH (a, b) PARTITIONS n
        self.expect_kw("BY")
        if self.match_kw("HASH"):
            self.expect_op("(")
            cols = [self.parse_identifier()]
            while self.match_op(","):
                cols.append(self.parse_identifier())
            self.expect_op(")")
            self.expect_kw("PARTITIONS")
            t = self.next()
            try:
                n = int(t.value)
            except (TypeError, ValueError):
                raise ParserError(
                    f"PARTITIONS expects an integer, got {t.value!r} "
                    f"at {t.pos}")
            if n < 1:
                raise ParserError(f"PARTITIONS must be >= 1, got {n}")
            stmt.partitions = Partitions(cols, [], kind="hash",
                                         num_partitions=n)
            return
        self.expect_kw("RANGE")
        self.expect_kw("COLUMNS")
        self.expect_op("(")
        cols = [self.parse_identifier()]
        while self.match_op(","):
            cols.append(self.parse_identifier())
        self.expect_op(")")
        self.expect_op("(")
        entries: List[PartitionEntry] = []
        while True:
            self.expect_kw("PARTITION")
            pname = self.parse_identifier()
            self.expect_kw("VALUES")
            self.expect_kw("LESS")
            self.expect_kw("THAN")
            self.expect_op("(")
            values: List[Any] = []
            while True:
                if self.match_kw("MAXVALUE"):
                    values.append("MAXVALUE")
                else:
                    values.append(self._parse_literal_value())
                if not self.match_op(","):
                    break
            self.expect_op(")")
            entries.append(PartitionEntry(pname, values))
            if not self.match_op(","):
                break
        self.expect_op(")")
        stmt.partitions = Partitions(cols, entries)

    def _parse_literal_value(self) -> Any:
        neg = self.match_op("-")
        t = self.next()
        if t.kind == NUMBER:
            if "." in t.value or "e" in t.value.lower():
                try:
                    v = float(t.value)
                except ValueError as e:
                    raise ParserError(
                        f"invalid number {t.value!r} at {t.pos}") from e
            else:
                v = self._to_int(t)
            return -v if neg else v
        if t.kind == STRING:
            return t.value
        if t.kind == IDENT and t.upper() in ("TRUE", "FALSE"):
            return t.upper() == "TRUE"
        if t.kind == IDENT and t.upper() == "NULL":
            return None
        raise ParserError(f"expected literal, found {t.value!r} at {t.pos}")

    def _parse_kv_list(self) -> dict:
        opts = {}
        if self.peek().kind == OP and self.peek().value == ")":
            return opts
        while True:
            key_parts = [self.parse_identifier()]
            while self.match_op("."):
                key_parts.append(self.parse_identifier())
            self.expect_op("=")
            opts[".".join(key_parts).lower()] = self._parse_literal_value()
            if not self.match_op(","):
                return opts

    # ---- DROP / ALTER ----
    def parse_drop(self) -> Statement:
        self.expect_kw("DROP")
        if self.match_kw("DATABASE") or self.match_kw("SCHEMA"):
            ie = self._parse_if_exists()
            return DropDatabase(self.parse_identifier(), ie)
        if self.match_kw("FLOW"):
            ie = self._parse_if_exists()
            return DropFlow(self.parse_identifier(), ie)
        self.expect_kw("TABLE")
        ie = self._parse_if_exists()
        return DropTable(self.parse_object_name(), ie)

    def _parse_if_exists(self) -> bool:
        if self.match_kw("IF"):
            self.expect_kw("EXISTS")
            return True
        return False

    def parse_alter(self) -> Statement:
        self.expect_kw("ALTER")
        self.expect_kw("TABLE")
        table = self.parse_object_name()
        if self.match_kw("ADD"):
            self.match_kw("COLUMN")
            col = self._parse_column_def()
            location = None
            if self.match_kw("FIRST"):
                location = "FIRST"
            elif self.match_kw("AFTER"):
                location = f"AFTER {self.parse_identifier()}"
            return AlterTable(table, AddColumn(col, location))
        if self.match_kw("DROP"):
            self.match_kw("COLUMN")
            return AlterTable(table, DropColumn(self.parse_identifier()))
        if self.match_kw("RENAME"):
            self.match_kw("TO")
            return AlterTable(table, RenameTable(self.parse_identifier()))
        t = self.peek()
        raise ParserError(f"unsupported ALTER operation {t.value!r}")

    # ---- INSERT / DELETE ----
    def parse_insert(self) -> Insert:
        self.expect_kw("INSERT")
        self.expect_kw("INTO")
        table = self.parse_object_name()
        columns: List[str] = []
        if self.match_op("("):
            columns.append(self.parse_identifier())
            while self.match_op(","):
                columns.append(self.parse_identifier())
            self.expect_op(")")
        if self.at_kw("SELECT"):
            return Insert(table, columns, select=self.parse_query())
        self.expect_kw("VALUES")
        rows: List[List[Expr]] = []
        while True:
            row = self._fast_values_row()
            if row is None:
                self.expect_op("(")
                row = []
                if not (self.peek().kind == OP and
                        self.peek().value == ")"):
                    row.append(self.parse_expr())
                    while self.match_op(","):
                        row.append(self.parse_expr())
                self.expect_op(")")
            rows.append(row)
            if not self.match_op(","):
                break
        return Insert(table, columns, rows)

    def _fast_values_row(self) -> Optional[List[Expr]]:
        """Direct token walk for the all-literal VALUES tuple (the bulk
        INSERT hot path); bails to the expression grammar on anything
        fancier (functions, arithmetic, placeholders)."""
        toks = self.toks
        i = self.i
        t = toks[i]
        if not (t.kind == OP and t.value == "("):
            return None
        i += 1
        row: List[Expr] = []
        while True:
            t = toks[i]
            k = t.kind
            neg = False
            if k == OP and t.value in ("-", "+"):
                neg = t.value == "-"
                i += 1
                t = toks[i]
                k = t.kind
                if k != NUMBER:
                    return None
            if k == NUMBER:
                txt = t.value
                if txt.lower().startswith("0x"):
                    v = int(txt, 16)
                else:
                    v = float(txt) if ("." in txt or "e" in txt.lower()) \
                        else int(txt)
                row.append(Literal(-v if neg else v, "number"))
            elif k == STRING:
                row.append(Literal(t.value, "string"))
            elif k == IDENT:
                kw = t.value.upper()
                if kw == "NULL":
                    row.append(Literal(None, "null"))
                elif kw in ("TRUE", "FALSE"):
                    row.append(Literal(kw == "TRUE", "bool"))
                else:
                    return None
            else:
                return None
            i += 1
            t = toks[i]
            if t.kind == OP and t.value == ",":
                i += 1
                continue
            if t.kind == OP and t.value == ")":
                self.i = i + 1
                return row
            return None

    def parse_delete(self) -> Delete:
        self.expect_kw("DELETE")
        self.expect_kw("FROM")
        table = self.parse_object_name()
        where = None
        if self.match_kw("WHERE"):
            where = self.parse_expr()
        return Delete(table, where)

    # ---- SHOW ----
    def parse_show(self) -> Statement:
        self.expect_kw("SHOW")
        full = self.match_kw("FULL")
        if self.match_kw("DATABASES") or self.match_kw("SCHEMAS"):
            like, where = self._parse_show_filter()
            return ShowDatabases(like, where)
        if self.match_kw("TABLES"):
            database = None
            if self.match_kw("FROM") or self.match_kw("IN"):
                database = self.parse_identifier()
            like, where = self._parse_show_filter()
            return ShowTables(database, like, where, full)
        if self.match_kw("FLOWS"):
            like, where = self._parse_show_filter()
            if where is not None:
                raise ParserError("SHOW FLOWS supports LIKE, not WHERE")
            return ShowFlows(like)
        if self.match_kw("PROCESSLIST"):
            return ShowProcessList(full=full)
        if self.match_kw("CREATE"):
            self.expect_kw("TABLE")
            return ShowCreateTable(self.parse_object_name())
        # SHOW VARIABLES / SHOW <ident> — MySQL-compat surface
        rest = []
        while self.peek().kind != EOF and not (
                self.peek().kind == OP and self.peek().value == ";"):
            rest.append(self.next().value)
        return ShowVariable(" ".join(rest))

    def _parse_show_filter(self):
        like = where = None
        if self.match_kw("LIKE"):
            t = self.next()
            like = t.value
        elif self.match_kw("WHERE"):
            where = self.parse_expr()
        return like, where

    # ---- TQL ----
    def parse_tql(self) -> Tql:
        self.expect_kw("TQL")
        if self.match_kw("EVAL") or self.match_kw("EVALUATE"):
            kind = "eval"
        elif self.match_kw("EXPLAIN"):
            kind = "analyze" if self.match_kw("ANALYZE") else "explain"
        elif self.match_kw("ANALYZE"):
            kind = "analyze"
        else:
            raise ParserError("expected EVAL/EXPLAIN/ANALYZE after TQL")
        start, end, step, lookback = "0", "0", "5m", None
        if self.match_op("("):
            params = []
            depth = 1
            cur: List[str] = []
            while depth > 0:
                t = self.next()
                if t.kind == EOF:
                    raise ParserError("unterminated TQL parameter list")
                if t.kind == OP and t.value == "(":
                    depth += 1
                elif t.kind == OP and t.value == ")":
                    depth -= 1
                    if depth == 0:
                        break
                elif t.kind == OP and t.value == "," and depth == 1:
                    params.append("".join(cur))
                    cur = []
                    continue
                if t.kind == STRING:
                    cur.append(t.value)
                else:
                    cur.append(t.value)
            params.append("".join(cur))
            if len(params) < 3:
                raise ParserError(
                    f"TQL expects (start, end, step), got {len(params)} "
                    f"parameter(s)")
            start, end, step = params[0], params[1], params[2]
            if len(params) >= 4:
                lookback = params[3]
        # the rest of the statement (up to ;) is the raw PromQL text — sliced
        # from the source string so PromQL syntax never has to be valid SQL
        start_pos = self.peek().pos
        while self.peek().kind != EOF and not (
                self.peek().kind == OP and self.peek().value == ";"):
            self.next()
        end_pos = self.peek().pos if self.peek().kind != EOF else len(self.sql)
        query = self.sql[start_pos:end_pos].strip()
        return Tql(kind, start, end, step, lookback, query)

    # ---- COPY ----
    def parse_copy(self) -> Copy:
        self.expect_kw("COPY")
        table = self.parse_object_name()
        if self.match_kw("TO"):
            direction = "to"
        elif self.match_kw("FROM"):
            direction = "from"
        else:
            raise ParserError("expected TO or FROM in COPY")
        t = self.next()
        if t.kind != STRING:
            raise ParserError("expected file path string in COPY")
        options = {}
        if self.match_kw("WITH"):
            self.expect_op("(")
            options = self._parse_kv_list()
            self.expect_op(")")
        return Copy(table, direction, t.value, options)

    # ---- EXPLAIN / SET ----
    def parse_explain(self) -> Explain:
        self.expect_kw("EXPLAIN")
        analyze = self.match_kw("ANALYZE")
        verbose = self.match_kw("VERBOSE")
        return Explain(self.parse_statement(), analyze, verbose)

    def parse_set(self) -> SetVariable:
        self.expect_kw("SET")
        self.match_kw("SESSION") or self.match_kw("GLOBAL") or \
            self.match_kw("LOCAL")
        parts = [self.parse_identifier()]
        while self.match_op("."):
            parts.append(self.parse_identifier())
        if self.match_op("="):
            value = self._parse_set_value()
        elif self.match_kw("TO"):
            value = self._parse_set_value()
        else:
            value = None
        return SetVariable(".".join(parts), value)

    def _parse_set_value(self):
        neg = self.match_op("-")
        t = self.next()
        if t.kind == NUMBER:
            if "." in t.value or "e" in t.value.lower():
                v = float(t.value)
            else:
                v = self._to_int(t)
            return -v if neg else v
        if neg:
            raise ParserError(f"expected number after '-' at {t.pos}")
        return t.value


# --------------------------------------------------------------------------
# CTE inlining (parse_with): rewrite CTE references into derived tables
# --------------------------------------------------------------------------

def _inline_ctes(node, ctes: dict) -> None:
    """Replace every TableRef naming a CTE with a deep copy of the CTE's
    query as a derived table, recursing through set ops, joins, derived
    tables, and expression subqueries (EXISTS / IN / scalar)."""
    if not ctes:
        return
    import copy as _copy
    if isinstance(node, SetQuery):
        _inline_ctes(node.left, ctes)
        _inline_ctes(node.right, ctes)
        for e, _ in node.order_by:
            _inline_expr(e, ctes)
        return
    if not isinstance(node, Query):
        return
    for ref in [node.from_] + [j.table for j in node.joins]:
        if ref is None:
            continue
        if ref.subquery is not None:
            _inline_ctes(ref.subquery, ctes)
        elif (ref.name is not None and len(ref.name.parts) == 1
                and ref.name.table.lower() in ctes):
            cte_q = ctes[ref.name.table.lower()]
            ref.alias = ref.alias or ref.name.table
            ref.name = None
            ref.subquery = _copy.deepcopy(cte_q)
    for item in node.projections:
        _inline_expr(item.expr, ctes)
    for e in (node.where, node.having):
        if e is not None:
            _inline_expr(e, ctes)
    for e in node.group_by:
        _inline_expr(e, ctes)
    for e, _ in node.order_by:
        _inline_expr(e, ctes)
    for j in node.joins:
        if j.on is not None:
            _inline_expr(j.on, ctes)


def _inline_expr(e, ctes: dict) -> None:
    """Walk an expression tree, inlining CTEs inside embedded queries."""
    if isinstance(e, Subquery):
        _inline_ctes(e.query, ctes)
        return
    for v in vars(e).values():
        if isinstance(v, Expr):
            _inline_expr(v, ctes)
        elif isinstance(v, WindowSpec):
            for pe in v.partition_by:
                _inline_expr(pe, ctes)
            for oe, _ in v.order_by:
                _inline_expr(oe, ctes)
        elif isinstance(v, list):
            for x in v:
                if isinstance(x, Expr):
                    _inline_expr(x, ctes)
                elif isinstance(x, tuple):
                    for y in x:
                        if isinstance(y, Expr):
                            _inline_expr(y, ctes)


def _apply_cte_column_aliases(q, cols: List[str], name: str) -> None:
    """WITH t(a, b) AS (...) renames the CTE's output columns: alias each
    branch's projections positionally (Postgres semantics)."""
    if isinstance(q, SetQuery):
        _apply_cte_column_aliases(q.left, cols, name)
        _apply_cte_column_aliases(q.right, cols, name)
        return
    if any(isinstance(p.expr, Star) for p in q.projections):
        raise ParserError(
            f"CTE {name!r}: a column list cannot rename SELECT *")
    if len(q.projections) != len(cols):
        raise ParserError(
            f"CTE {name!r} has {len(cols)} column names but its SELECT "
            f"returns {len(q.projections)} columns")
    for p, c in zip(q.projections, cols):
        p.alias = c
