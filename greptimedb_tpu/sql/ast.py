"""SQL AST nodes (statements + expressions).

Statement surface mirrors the reference `Statement` enum
(src/sql/src/statements/statement.rs:34-64): Query, Insert, Delete,
CreateTable, CreateExternalTable, CreateDatabase, DropTable, Alter,
ShowDatabases, ShowTables, ShowCreateTable, DescribeTable, Explain, Use,
Tql, Copy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple

__all__ = [
    "Expr", "Literal", "Column", "Star", "BinaryOp", "UnaryOp",
    "FunctionCall", "WindowSpec", "Between", "InList", "IsNull", "Cast", "Case",
    "Interval", "Placeholder", "Subquery",
    "Statement", "SelectItem", "TableRef", "Join", "Query", "Insert",
    "Delete", "ColumnDef", "PartitionEntry", "Partitions", "CreateTable",
    "CreateDatabase", "DropTable", "DropDatabase", "AlterTable", "AddColumn",
    "DropColumn", "RenameTable", "ShowDatabases", "ShowTables",
    "ShowCreateTable", "DescribeTable", "ShowVariable", "Use", "Tql", "Copy",
    "Explain", "SetVariable", "TruncateTable", "ObjectName",
    "CreateFlow", "DropFlow", "ShowFlows", "Admin",
]


class Expr:
    pass


@dataclass
class Literal(Expr):
    value: Any                      # python value; None for NULL
    kind: str = "auto"              # number | string | bool | null | auto

    def __str__(self):
        if self.value is None:
            return "NULL"
        if self.kind == "string":
            return "'" + str(self.value).replace("'", "''") + "'"
        if self.kind == "bool":
            return "TRUE" if self.value else "FALSE"
        return str(self.value)


@dataclass
class Column(Expr):
    name: str
    table: Optional[str] = None

    def __str__(self):
        return f"{self.table}.{self.name}" if self.table else self.name


@dataclass
class Star(Expr):
    table: Optional[str] = None

    def __str__(self):
        return f"{self.table}.*" if self.table else "*"


@dataclass
class BinaryOp(Expr):
    op: str                         # lowercase: and/or/=/!=/</<=/>/>=/+/-/*///%/like/regexp/||
    left: Expr
    right: Expr

    def __str__(self):
        return f"({self.left} {self.op.upper()} {self.right})"


@dataclass
class UnaryOp(Expr):
    op: str                         # not | - | +
    operand: Expr

    def __str__(self):
        return f"({self.op.upper()} {self.operand})"


@dataclass
class WindowSpec:
    """OVER (...) clause: partitioning, intra-partition order, row frame.

    frame is None (default frame: RANGE UNBOUNDED PRECEDING..CURRENT ROW
    when order_by is set, the whole partition otherwise) or a ROWS frame
    (lo, hi) with offsets relative to the current row — negative =
    preceding, None = unbounded on that side."""
    partition_by: List["Expr"] = field(default_factory=list)
    order_by: List[Tuple["Expr", bool]] = field(default_factory=list)
    frame: Optional[Tuple[Optional[int], Optional[int]]] = None

    def __str__(self):
        parts = []
        if self.partition_by:
            parts.append("PARTITION BY " +
                         ", ".join(str(e) for e in self.partition_by))
        if self.order_by:
            parts.append("ORDER BY " + ", ".join(
                f"{e}{'' if asc else ' DESC'}" for e, asc in self.order_by))
        if self.frame is not None:
            def bound(v, side):
                if v is None:
                    return f"UNBOUNDED {side}"
                if v == 0:
                    return "CURRENT ROW"
                return f"{-v} PRECEDING" if v < 0 else f"{v} FOLLOWING"
            parts.append(f"ROWS BETWEEN {bound(self.frame[0], 'PRECEDING')} "
                         f"AND {bound(self.frame[1], 'FOLLOWING')}")
        return " ".join(parts)


@dataclass
class FunctionCall(Expr):
    name: str                       # lowercase
    args: List[Expr] = field(default_factory=list)
    distinct: bool = False
    over: Optional[WindowSpec] = None   # set → window function

    def __str__(self):
        inner = ", ".join(str(a) for a in self.args)
        if self.distinct:
            inner = "DISTINCT " + inner
        base = f"{self.name}({inner})"
        if self.over is not None:
            return f"{base} OVER ({self.over})"
        return base


@dataclass
class Between(Expr):
    expr: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass
class InList(Expr):
    expr: Expr
    items: List[Expr] = field(default_factory=list)
    negated: bool = False


@dataclass
class IsNull(Expr):
    expr: Expr
    negated: bool = False


@dataclass
class Cast(Expr):
    expr: Expr
    type_name: str


@dataclass
class Case(Expr):
    operand: Optional[Expr]
    whens: List[Tuple[Expr, Expr]] = field(default_factory=list)
    else_: Optional[Expr] = None


@dataclass
class Interval(Expr):
    text: str                       # e.g. "5 minutes" / "1h"


@dataclass
class Placeholder(Expr):
    index: int


@dataclass
class Subquery(Expr):
    query: "Query"


# --------------------------------------------------------------------------
# Statements
# --------------------------------------------------------------------------

class Statement:
    pass


@dataclass
class ObjectName:
    """Up-to-three-part dotted name: [catalog.][schema.]table."""
    parts: List[str]

    @property
    def table(self) -> str:
        return self.parts[-1]

    @property
    def schema(self) -> Optional[str]:
        return self.parts[-2] if len(self.parts) >= 2 else None

    @property
    def catalog(self) -> Optional[str]:
        return self.parts[-3] if len(self.parts) >= 3 else None

    def __str__(self):
        return ".".join(self.parts)


@dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclass
class TableRef:
    name: Optional[ObjectName] = None
    alias: Optional[str] = None
    subquery: Optional["Query"] = None


@dataclass
class Join:
    kind: str                       # inner | left | right | cross
    table: TableRef
    on: Optional[Expr] = None


@dataclass
class Query(Statement):
    projections: List[SelectItem]
    from_: Optional[TableRef] = None
    joins: List[Join] = field(default_factory=list)
    where: Optional[Expr] = None
    group_by: List[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    order_by: List[Tuple[Expr, bool]] = field(default_factory=list)  # (expr, asc)
    #: per-order-key NULLS FIRST(True)/LAST(False); None = SQL default
    #: (NULLS LAST for ASC, NULLS FIRST for DESC — the Postgres rule)
    order_nulls: List[Optional[bool]] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    distinct: bool = False


@dataclass
class SetQuery(Statement):
    """UNION [ALL] chain; order/limit/offset apply to the whole set."""
    left: Statement                  # Query | SetQuery
    right: "Query" = None
    all: bool = False
    order_by: List[Tuple[Expr, bool]] = field(default_factory=list)
    order_nulls: List[Optional[bool]] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None


@dataclass
class Insert(Statement):
    table: ObjectName
    columns: List[str] = field(default_factory=list)
    rows: List[List[Expr]] = field(default_factory=list)
    select: Optional[Query] = None


@dataclass
class Delete(Statement):
    table: ObjectName
    where: Optional[Expr] = None


@dataclass
class ColumnDef:
    name: str
    type_name: str
    nullable: bool = True
    default: Optional[Expr] = None
    comment: Optional[str] = None
    is_time_index: bool = False
    is_primary_key: bool = False


@dataclass
class PartitionEntry:
    name: str
    values: List[Any]               # literal bound per partition column; "MAXVALUE" sentinel


@dataclass
class Partitions:
    columns: List[str]
    entries: List[PartitionEntry] = field(default_factory=list)
    kind: str = "range"             # "range" | "hash"
    num_partitions: Optional[int] = None   # hash only: bucket count


@dataclass
class CreateTable(Statement):
    name: ObjectName
    columns: List[ColumnDef] = field(default_factory=list)
    time_index: Optional[str] = None
    primary_keys: List[str] = field(default_factory=list)
    partitions: Optional[Partitions] = None
    engine: str = "mito"
    options: dict = field(default_factory=dict)
    if_not_exists: bool = False
    external: bool = False


@dataclass
class CreateDatabase(Statement):
    name: str
    if_not_exists: bool = False


@dataclass
class DropTable(Statement):
    name: ObjectName
    if_exists: bool = False


@dataclass
class DropDatabase(Statement):
    name: str
    if_exists: bool = False


@dataclass
class AddColumn:
    column: ColumnDef
    location: Optional[str] = None  # FIRST / AFTER <col>


@dataclass
class DropColumn:
    name: str


@dataclass
class RenameTable:
    new_name: str


@dataclass
class AlterTable(Statement):
    table: ObjectName
    operation: Any                  # AddColumn | DropColumn | RenameTable


@dataclass
class CreateFlow(Statement):
    """CREATE FLOW name [SINK TO table] AS SELECT <aggs> FROM src
    GROUP BY date_bin(stride, ts)[, tags...] — a continuous rollup
    (reference: GreptimeDB's flow engine CREATE FLOW statement)."""
    name: str
    query: "Query" = None
    sink: Optional[str] = None      # defaults to the flow name
    if_not_exists: bool = False
    raw_sql: str = ""               # SELECT text for SHOW FLOWS


@dataclass
class DropFlow(Statement):
    name: str = ""
    if_exists: bool = False


@dataclass
class ShowFlows(Statement):
    like: Optional[str] = None


@dataclass
class ShowDatabases(Statement):
    like: Optional[str] = None
    where: Optional[Expr] = None


@dataclass
class ShowTables(Statement):
    database: Optional[str] = None
    like: Optional[str] = None
    where: Optional[Expr] = None
    full: bool = False


@dataclass
class ShowCreateTable(Statement):
    table: ObjectName = None


@dataclass
class ShowVariable(Statement):
    name: str = ""


@dataclass
class ShowProcessList(Statement):
    full: bool = False


@dataclass
class Kill(Statement):
    """KILL [QUERY] <id> — cooperative cancellation of a running
    statement from information_schema.processes / SHOW PROCESSLIST."""
    process_id: int = 0


@dataclass
class Admin(Statement):
    """Elastic region administration (meta balancer surface):

    - ``ADMIN MIGRATE REGION <table> <region> TO <node_id>``
    - ``ADMIN SPLIT REGION <table> <region> [AT <literal>]``
    - ``ADMIN REBALANCE [TABLE <table>]``
    - ``ADMIN ADD REPLICA <table> <region> TO <node_id>``
    - ``ADMIN REMOVE REPLICA <table> <region> FROM <node_id>``

    Table maintenance (storage surface; works standalone too):

    - ``ADMIN FLUSH TABLE <table>``
    - ``ADMIN COMPACT TABLE <table>``

    Observability (works on both deployments):

    - ``ADMIN SHOW TRACE '<trace_id>'`` — the reassembled cross-node
      waterfall from ``greptime_private.trace_spans`` ('last' = the
      most recently retained trace on this frontend)
    - ``ADMIN SHOW PROFILE '<query_id>'|'<trace_id>'|'last'`` — the
      continuous profiler's per-node self/total frame tree from
      ``greptime_private.profile_samples`` (``trace_id`` carries the
      id for both SHOW forms)
    """
    #: migrate_region | split_region | rebalance | flush_table |
    #: compact_table | show_trace | show_profile
    kind: str = ""
    table: Optional[ObjectName] = None
    region: Optional[int] = None
    target_node: Optional[int] = None
    at_value: Any = None
    trace_id: Optional[str] = None


@dataclass
class DescribeTable(Statement):
    table: ObjectName = None


@dataclass
class Use(Statement):
    database: str = ""


@dataclass
class Tql(Statement):
    kind: str                       # eval | explain | analyze
    start: str = "0"
    end: str = "0"
    step: str = "5m"
    lookback: Optional[str] = None
    query: str = ""


@dataclass
class Copy(Statement):
    table: ObjectName
    direction: str                  # to | from
    path: str = ""
    options: dict = field(default_factory=dict)


@dataclass
class Explain(Statement):
    statement: Statement = None
    analyze: bool = False
    verbose: bool = False


@dataclass
class SetVariable(Statement):
    name: str = ""
    value: Any = None


@dataclass
class TruncateTable(Statement):
    name: ObjectName = None
