"""SQL front end: tokenizer, AST, and recursive-descent parser.

Reference behavior: src/sql — a wrapper over sqlparser-rs adding GreptimeDB
statements and clauses (`src/sql/src/statements/statement.rs:34-64`): CREATE
TABLE with TIME INDEX / PRIMARY KEY / PARTITION BY RANGE COLUMNS / ENGINE
(`src/sql/src/parsers/create_parser.rs:144-260`), the `TQL EVAL(start, end,
step) <promql>` extension (`src/sql/src/parsers/tql_parser.rs:31-70`), COPY
(`src/sql/src/parsers/copy_parser.rs`), SHOW/DESCRIBE, ALTER, DELETE, and
INSERT. Implemented here as a hand-rolled lexer + recursive-descent parser
(no sqlparser dependency exists for Python at parity)."""

from .ast import *  # noqa: F401,F403
from .parser import ParserError, parse_sql, parse_statements
from . import ast

__all__ = ["parse_sql", "parse_statements", "ParserError"] + ast.__all__
