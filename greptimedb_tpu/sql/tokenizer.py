"""SQL lexer: whitespace/comment-skipping tokenizer with position tracking."""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

IDENT = "ident"
QIDENT = "qident"     # "quoted" or `backticked` identifier
STRING = "string"
NUMBER = "number"
OP = "op"
EOF = "eof"

# multi-char operators first so maximal munch works; [ ] { } : pass through
# for TQL-embedded PromQL text (reparsed by the PromQL engine, not SQL)
_OPERATORS = ["<=>", "<>", "<=", ">=", "!=", "::", "||", "<", ">", "=", "+",
              "-", "*", "/", "%", "(", ")", ",", ";", ".", "?", "~", "!",
              "[", "]", "{", "}", ":"]


@dataclass
class Token:
    kind: str
    value: str
    pos: int

    def upper(self) -> str:
        return self.value.upper()


class TokenizeError(ValueError):
    pass


def tokenize(sql: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                raise TokenizeError(f"unterminated block comment at {i}")
            i = j + 2
            continue
        if c == "'":
            start = i
            val, i = _read_quoted(sql, i, "'")
            toks.append(Token(STRING, val, start))
            continue
        if c == '"':
            start = i
            val, i = _read_quoted(sql, i, '"')
            toks.append(Token(QIDENT, val, start))
            continue
        if c == "`":
            start = i
            val, i = _read_quoted(sql, i, "`")
            toks.append(Token(QIDENT, val, start))
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    # "1.." (range) shouldn't happen in SQL; treat greedily
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j + 1 < n and (
                        sql[j + 1].isdigit() or sql[j + 1] in "+-"):
                    seen_exp = True
                    j += 2 if sql[j + 1] in "+-" else 1
                elif ch in "xX" and sql[i] == "0" and j == i + 1:
                    j += 1
                    while j < n and sql[j] in "0123456789abcdefABCDEF":
                        j += 1
                    break
                else:
                    break
            toks.append(Token(NUMBER, sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_" or c == "@" or c == "$":
            j = i + 1
            while j < n and (sql[j].isalnum() or sql[j] in "_$@"):
                j += 1
            toks.append(Token(IDENT, sql[i:j], i))
            i = j
            continue
        for op in _OPERATORS:
            if sql.startswith(op, i):
                toks.append(Token(OP, op, i))
                i += len(op)
                break
        else:
            raise TokenizeError(f"unexpected character {c!r} at offset {i}")
    toks.append(Token(EOF, "", n))
    return toks


def _read_quoted(sql: str, start: int, q: str):
    i = start + 1
    out = []
    n = len(sql)
    while i < n:
        c = sql[i]
        if c == q:
            if i + 1 < n and sql[i + 1] == q:  # doubled-quote escape
                out.append(q)
                i += 2
                continue
            return "".join(out), i + 1
        if c == "\\" and q == "'" and i + 1 < n:
            # MySQL-style backslash escapes in strings
            esc = sql[i + 1]
            out.append({"n": "\n", "t": "\t", "r": "\r", "0": "\0",
                        "\\": "\\", "'": "'", '"': '"'}.get(esc, esc))
            i += 2
            continue
        out.append(c)
        i += 1
    raise TokenizeError(f"unterminated {q}-quoted literal at {start}")
