"""SQL lexer: whitespace/comment-skipping tokenizer with position tracking."""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..errors import SyntaxError_

IDENT = "ident"
QIDENT = "qident"     # "quoted" or `backticked` identifier
STRING = "string"
NUMBER = "number"
OP = "op"
EOF = "eof"

# multi-char operators first so maximal munch works; [ ] { } : pass through
# for TQL-embedded PromQL text (reparsed by the PromQL engine, not SQL)
_OPERATORS = ["<=>", "<>", "<=", ">=", "!=", "::", "||", "<", ">", "=", "+",
              "-", "*", "/", "%", "(", ")", ",", ";", ".", "?", "~", "!",
              "[", "]", "{", "}", ":"]


@dataclass
class Token:
    kind: str
    value: str
    pos: int

    def upper(self) -> str:
        return self.value.upper()


class TokenizeError(SyntaxError_, ValueError):
    """SQL tokenize failure: taxonomy-typed (INVALID_SYNTAX) for the
    wire, ValueError for pre-taxonomy call sites — same dual contract
    as ParserError (greptlint GL10)."""


import re as _re

# master scanner: one compiled alternation, longest-match-first operator
# branch (bulk INSERT statements tokenize 6x faster than the char walk)
_MASTER = _re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<lcomment>--[^\n]*\n?)
  | (?P<bcomment>/\*.*?\*/)
  | (?P<number>(?:0[xX][0-9a-fA-F]+)
        |(?:(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?))
  | (?P<ident>[\w@$][\w$@]*)
  | (?P<sstr>'(?:[^'\\]|''|\\.)*')
  | (?P<qident>"(?:[^"]|"")*"|`(?:[^`]|``)*`)
  | (?P<op><=>|<>|<=|>=|!=|::|\|\||[<>=+\-*/%(),;.?~!\[\]{}:])
    """, _re.VERBOSE | _re.DOTALL)

_SIMPLE_SSTR = _re.compile(r"'[^'\\]*'\Z")


def tokenize(sql: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(sql)
    append = toks.append
    while i < n:
        m = _MASTER.match(sql, i)
        if m is None:
            c = sql[i]
            if c in "'\"`":
                # unterminated quote (the regex only matches closed ones)
                _read_quoted(sql, i, c)
            raise TokenizeError(f"unexpected character {c!r} at offset {i}")
        kind = m.lastgroup
        j = m.end()
        if kind == "ws" or kind == "lcomment" or kind == "bcomment":
            i = j
            continue
        text = m.group()
        if kind == "number":
            append(Token(NUMBER, text, i))
        elif kind == "ident":
            append(Token(IDENT, text, i))
        elif kind == "sstr":
            if _SIMPLE_SSTR.match(text):
                append(Token(STRING, text[1:-1], i))
            else:       # escapes / doubled quotes: exact unescape walk
                val, j = _read_quoted(sql, i, "'")
                append(Token(STRING, val, i))
        elif kind == "qident":
            q = text[0]
            body = text[1:-1]
            if q + q in body:
                body = body.replace(q + q, q)
            append(Token(QIDENT, body, i))
        else:
            if text == "/" and sql.startswith("/*", i):
                # bcomment branch only matches *closed* comments; an open
                # one falls through to the op branch as '/' then '*'
                raise TokenizeError(f"unterminated block comment at {i}")
            append(Token(OP, text, i))
        i = j
    toks.append(Token(EOF, "", n))
    return toks


def _read_quoted(sql: str, start: int, q: str):
    i = start + 1
    out = []
    n = len(sql)
    while i < n:
        c = sql[i]
        if c == q:
            if i + 1 < n and sql[i + 1] == q:  # doubled-quote escape
                out.append(q)
                i += 2
                continue
            return "".join(out), i + 1
        if c == "\\" and q == "'" and i + 1 < n:
            # MySQL-style backslash escapes in strings
            esc = sql[i + 1]
            out.append({"n": "\n", "t": "\t", "r": "\r", "0": "\0",
                        "\\": "\\", "'": "'", '"': '"'}.get(esc, esc))
            i += 2
            continue
        out.append(c)
        i += 1
    raise TokenizeError(f"unterminated {q}-quoted literal at {start}")
