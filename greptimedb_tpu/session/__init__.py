"""Session state: QueryContext.

Reference behavior: src/session/src/context.rs:28 — current catalog/schema
plus the protocol channel the query arrived on.
"""

from __future__ import annotations

import enum
from typing import Optional

from .. import DEFAULT_CATALOG_NAME, DEFAULT_SCHEMA_NAME


class Channel(enum.Enum):
    HTTP = "http"
    MYSQL = "mysql"
    POSTGRES = "postgres"
    GRPC = "grpc"
    INFLUX = "influxdb"
    OPENTSDB = "opentsdb"
    PROMETHEUS = "prometheus"


class QueryContext:
    def __init__(self, current_catalog: str = DEFAULT_CATALOG_NAME,
                 current_schema: str = DEFAULT_SCHEMA_NAME,
                 channel: Channel = Channel.HTTP,
                 username: Optional[str] = None):
        self.current_catalog = current_catalog
        self.current_schema = current_schema
        self.channel = channel
        self.username = username
        self.time_zone = "UTC"

    def set_current_schema(self, schema: str) -> None:
        self.current_schema = schema

    def resolve(self, name) -> tuple:
        """Resolve a sql.ast.ObjectName to (catalog, schema, table)."""
        catalog = name.catalog or self.current_catalog
        schema = name.schema or self.current_schema
        return catalog, schema, name.table

    def __repr__(self):  # pragma: no cover
        return (f"QueryContext({self.current_catalog}."
                f"{self.current_schema}, {self.channel.value})")
