"""Datanode client: the router↔worker data-plane interface.

Reference behavior: src/client — `Database` sends per-region inserts and
ships plans to datanodes, streaming results back over Arrow Flight
(database.rs:39,209-260). The same surface here has two implementations:

- `LocalDatanodeClient`: direct in-process calls (the reference's
  MockDistributedInstance topology, frontend/src/tests.rs:60) — also the
  fast path when router and worker share a host;
- a Flight/gRPC client implements the identical surface over sockets for
  multi-host (servers/flight.py).

Aggregate pushdown note: v0.2 of the reference pushes only scans
(projection/filter/limit) to datanodes and aggregates on the frontend
(frontend/src/table.rs:109-156). Here `region_moments` pushes the
*aggregation moments* down: each worker reduces its regions with the TPU
kernel and returns per-run moment frames that the frontend folds — a
strict upgrade the SURVEY (§3.4) calls for.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import pandas as pd

from ..table.requests import CreateTableRequest, DropTableRequest


class DatanodeClient:
    """Abstract data-plane client for one datanode."""

    def ddl_create_table(self, request: CreateTableRequest) -> None:
        raise NotImplementedError

    def ddl_drop_table(self, catalog: str, schema: str, name: str) -> bool:
        raise NotImplementedError

    def ddl_alter_table(self, request) -> None:
        raise NotImplementedError

    def write_region(self, catalog: str, schema: str, table: str,
                     region_number: int, columns: Dict[str, Sequence],
                     op: str = "put") -> int:
        raise NotImplementedError

    def region_moments(self, catalog: str, schema: str, table: str,
                       plan, regions: Optional[Sequence[int]] = None
                       ) -> List[pd.DataFrame]:
        """Run the TPU aggregate plan over this node's regions of the
        table (restricted to `regions` when the frontend pruned);
        returns per-region moment frames for the frontend fold."""
        raise NotImplementedError

    def scan_batches(self, catalog: str, schema: str, table: str,
                     projection: Optional[Sequence[str]] = None,
                     time_range=None, limit: Optional[int] = None,
                     filters: Optional[Sequence] = None,
                     regions: Optional[Sequence[int]] = None) -> list:
        raise NotImplementedError

    def flush_table(self, catalog: str, schema: str, table: str) -> None:
        raise NotImplementedError

    def describe_table(self, catalog: str, schema: str, name: str):
        """(TableInfo, partition_rule) of a hosted table, or None."""
        raise NotImplementedError


class LocalDatanodeClient(DatanodeClient):
    def __init__(self, datanode):
        self.datanode = datanode

    @property
    def node_id(self) -> int:
        return self.datanode.opts.node_id

    def _table(self, catalog: str, schema: str, name: str):
        from ..errors import TableNotFoundError
        t = self.datanode.catalog.table(catalog, schema, name)
        if t is None:
            raise TableNotFoundError(f"table {catalog}.{schema}.{name} "
                                     f"not on datanode {self.node_id}")
        return t

    def ddl_create_table(self, request: CreateTableRequest) -> None:
        table = self.datanode.mito.create_table(request)
        cat = self.datanode.catalog
        if cat.table(request.catalog_name, request.schema_name,
                     request.table_name) is None:
            cat.register_table(request.catalog_name, request.schema_name,
                               request.table_name, table)

    def ddl_drop_table(self, catalog: str, schema: str, name: str) -> bool:
        ok = self.datanode.mito.drop_table(
            DropTableRequest(name, catalog, schema))
        self.datanode.catalog.deregister_table(catalog, schema, name)
        return ok

    def ddl_alter_table(self, request) -> None:
        table = self.datanode.mito.alter_table(request)
        cat = self.datanode.catalog
        cat.deregister_table(request.catalog_name, request.schema_name,
                             request.table_name)
        cat.register_table(request.catalog_name, request.schema_name,
                           request.new_table_name or request.table_name,
                           table)

    def _node_ctx(self):
        # in-process cluster: datanode work runs on the frontend's own
        # threads, so the sampler needs the per-node label pushed here
        # (a no-op context while nothing samples)
        from ..common import profiler
        return profiler.node_context(f"dn{self.node_id}")

    def write_region(self, catalog: str, schema: str, table: str,
                     region_number: int, columns: Dict[str, Sequence],
                     op: str = "put") -> int:
        with self._node_ctx():
            return self._table(catalog, schema, table).write_region(
                region_number, columns, op)

    def region_moments(self, catalog: str, schema: str, table: str,
                       plan, regions: Optional[Sequence[int]] = None
                       ) -> List[pd.DataFrame]:
        from ..query.tpu_exec import region_moment_frames
        with self._node_ctx():
            return region_moment_frames(
                self._table(catalog, schema, table), plan,
                regions=regions)

    def scan_batches(self, catalog: str, schema: str, table: str,
                     projection: Optional[Sequence[str]] = None,
                     time_range=None, limit: Optional[int] = None,
                     filters: Optional[Sequence] = None,
                     regions: Optional[Sequence[int]] = None) -> list:
        from ..common import exec_stats
        with self._node_ctx(), exec_stats.stage("scan"):
            batches = self._table(catalog, schema, table).scan_batches(
                projection=projection, time_range=time_range, limit=limit,
                filters=filters, regions=regions)
        # same stage name the Flight datanode server records, so the
        # per-node EXPLAIN ANALYZE tree is identical on both transports
        exec_stats.record("scan", rows=sum(b.num_rows for b in batches))
        return batches

    def flush_table(self, catalog: str, schema: str, table: str) -> None:
        with self._node_ctx():
            self._table(catalog, schema, table).flush()

    def describe_table(self, catalog: str, schema: str, name: str):
        t = self.datanode.catalog.table(catalog, schema, name)
        if t is None:
            return None
        return t.info, getattr(t, "partition_rule", None)

    def ping(self) -> int:
        return self.node_id

    def repl_apply(self, catalog: str, schema: str, table: str,
                   region_number: int, entries: list,
                   leader_flushed: int = 0) -> dict:
        """Apply shipped WAL records to this node's standby replica of
        the region (the continuous-replication consumer side)."""
        with self._node_ctx():
            return self.datanode.repl_apply(
                catalog, schema, table, region_number, entries,
                leader_flushed=leader_flushed)

    def background_jobs(self) -> list:
        """In-process twin of the Flight action. The registry is
        process-wide, so for an in-process cluster these rows duplicate
        the frontend's own — the view dedups by (node, job_id)."""
        from ..common import background_jobs
        return background_jobs.rows()

    def profile(self, *, seconds: Optional[float] = None,
                hz: Optional[float] = None, drain: bool = False) -> list:
        """In-process twin of the Flight `profile` action. The sampler
        is process-wide (the frontend's own), so draining or bursting
        here would double-count it — per-node attribution instead rides
        the `node_context` pushed around the data-plane calls above."""
        return []
