"""Flight clients: the wire twins of the in-process data-plane clients.

Reference behavior: src/client/src/database.rs:39,209-260 — `Database`
sends inserts over gRPC and ships queries whose results stream back over
Arrow Flight `do_get`. Two clients here:

- `FlightDatanodeClient` implements the `DatanodeClient` surface over a
  `FlightDatanodeServer`, so a `DistInstance` routes across real sockets
  with zero code changes (swap it for `LocalDatanodeClient`).
- `Database` is the user-facing client against a `FlightFrontendServer`:
  `sql()` and auto-create `insert()` — the README quick-start surface.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

import pandas as pd
import pyarrow as pa
import pyarrow.flight as flight

from ..common import exec_stats
from ..common.telemetry import current_traceparent
from ..datatypes.record_batch import RecordBatch
from ..errors import GreptimeError, TableNotFoundError
from ..table.metadata import TableInfo
from ..table.requests import CreateTableRequest
from . import DatanodeClient


def _traced(body: dict) -> dict:
    """Attach the caller's W3C trace context so the server joins this
    trace (servers pop the key before dispatching) — plus, from a
    verdict-deciding (root) trace sink, the recent tail-sampling
    verdicts: datanodes buffer spans blind, and the verdicts piggyback
    on whatever RPC happens next (released spans ride its response)."""
    from ..common import trace_store
    tp = current_traceparent()
    out = {**body, "traceparent": tp} if tp is not None else body
    sink = trace_store.sink()
    if sink is not None and sink.role == "root":
        verdicts = sink.recent_verdicts()
        if verdicts:
            out = dict(out)
            out[trace_store.TRACE_VERDICTS_BODY_KEY] = verdicts
    return out


def _absorb_wire_spans(rows) -> None:
    """Buffered datanode spans released by a piggybacked verdict: queue
    them on the local (root) sink for the next trace-store flush."""
    if not rows:
        return
    from ..common import trace_store
    sink = trace_store.sink()
    if sink is not None and isinstance(rows, list):
        sink.absorb_spans(rows)


def _absorb_stream_stats(schema: pa.Schema) -> None:
    """Replay datanode-side ExecStats riding the stream schema into the
    active collector (the per-RPC node sub-collector during a scatter),
    and absorb any trace spans the datanode's sink released."""
    meta = schema.metadata or {}
    raw = meta.get(exec_stats.EXEC_STATS_WIRE_KEY)
    if raw:
        try:
            exec_stats.absorb_remote(json.loads(raw))
        except (ValueError, TypeError, KeyError):
            pass             # stats are advisory; never fail a read
    from ..common import trace_store
    raw_spans = meta.get(trace_store.TRACE_SPANS_WIRE_KEY)
    if raw_spans:
        try:
            _absorb_wire_spans(json.loads(raw_spans))
        except (ValueError, TypeError):
            pass             # spans are advisory too


def _columns_to_arrow(columns: Dict[str, Sequence]) -> pa.Table:
    return pa.table({k: list(v) for k, v in columns.items()})


def _to_greptime_error(e: flight.FlightError) -> GreptimeError:
    """Server-side GreptimeErrors cross the wire as gRPC status messages;
    rebuild the closest taxonomy member so callers keep one except path.
    Unavailable/timeout faults map to TransientRpcError so the
    distributed fan-out's retry loop recognizes real network hops; the
    'stale route' marker maps to StaleRouteError so the DistTable's
    route-refresh retry works across real sockets too."""
    from ..errors import OverloadedError, StaleRouteError, TransientRpcError
    msg = str(e).split(". gRPC client debug context:")[0]
    if isinstance(e, (flight.FlightUnavailableError,
                      flight.FlightTimedOutError)):
        return TransientRpcError(msg)
    if StaleRouteError.WIRE_MARKER in msg:
        return StaleRouteError(msg)
    if OverloadedError.WIRE_MARKER in msg:
        # admission rejection crossing the wire: keep the type so a
        # routing frontend re-maps it to 429/server-busy, not 500
        return OverloadedError(msg)
    from ..query.plan_codec import WIRE_UNSUPPORTED_MARKER
    if WIRE_UNSUPPORTED_MARKER in msg:
        # version-skewed plan rejected by an older datanode: keep the
        # type so the frontend degrades the statement to the raw path
        from ..errors import UnsupportedError
        return UnsupportedError(msg)
    if "not found" in msg or "not on datanode" in msg:
        return TableNotFoundError(msg)
    return GreptimeError(msg)


class _FlightBase:
    def __init__(self, address: str):
        self.address = address
        self._conn: Optional[flight.FlightClient] = None

    @property
    def conn(self) -> flight.FlightClient:
        if self._conn is None:
            self._conn = flight.FlightClient(self.address)
        return self._conn

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def _action(self, kind: str, body: dict) -> dict:
        try:
            results = list(self.conn.do_action(
                flight.Action(kind, json.dumps(_traced(body)).encode())))
            resp = json.loads(results[0].body.to_pybytes())
        except flight.FlightError as e:
            raise _to_greptime_error(e) from None
        _absorb_wire_spans(resp.pop("trace_spans", None))
        if not resp.get("ok", False):
            err = resp.get("error", "unknown flight error")
            if resp.get("error_type") == "TableNotFoundError":
                raise TableNotFoundError(err)
            if resp.get("error_type") == "StaleRouteError":
                from ..errors import StaleRouteError
                raise StaleRouteError(err)
            if resp.get("error_type") == "OverloadedError":
                from ..errors import OverloadedError
                raise OverloadedError(err)
            raise GreptimeError(err)
        return resp

    def _put(self, command: dict, data: pa.Table) -> int:
        descriptor = flight.FlightDescriptor.for_command(
            json.dumps(_traced(command)).encode())
        try:
            writer, reader = self.conn.do_put(descriptor, data.schema)
            with writer:
                writer.write_table(data)
                writer.done_writing()
                buf = reader.read()
        except flight.FlightError as e:
            raise _to_greptime_error(e) from None
        meta = json.loads(buf.to_pybytes()) if buf is not None else {}
        _absorb_wire_spans(meta.pop("trace_spans", None))
        if meta.get("exec_stats"):
            try:
                exec_stats.absorb_remote(meta["exec_stats"])
            except (ValueError, TypeError, KeyError):
                pass         # advisory: a write that landed must not fail
        return int(meta.get("affected_rows", 0))


class FlightDatanodeClient(_FlightBase, DatanodeClient):
    """DatanodeClient over Arrow Flight — the multi-host router↔worker
    transport (drop-in for LocalDatanodeClient in DistInstance)."""

    def __init__(self, address: str, node_id: int):
        super().__init__(address)
        self.node_id = node_id

    def ddl_create_table(self, request: CreateTableRequest) -> None:
        from ..servers.flight import create_request_to_dict
        self._action("ddl_create_table",
                     {"request": create_request_to_dict(request)})

    def ddl_alter_table(self, request) -> None:
        from ..table.requests import alter_request_to_dict
        self._action("ddl_alter_table",
                     {"request": alter_request_to_dict(request)})

    def ddl_drop_table(self, catalog: str, schema: str, name: str) -> bool:
        return bool(self._action("ddl_drop_table", {
            "catalog": catalog, "schema": schema, "table": name})["dropped"])

    def write_region(self, catalog: str, schema: str, table: str,
                     region_number: int, columns: Dict[str, Sequence],
                     op: str = "put") -> int:
        return self._put(
            {"type": "write_region", "catalog": catalog, "schema": schema,
             "table": table, "region_number": region_number, "op": op},
            _columns_to_arrow(columns))

    def region_moments(self, catalog: str, schema: str, table: str,
                       plan, regions=None) -> List[pd.DataFrame]:
        from ..query.plan_codec import plan_to_dict
        ticket = flight.Ticket(json.dumps(_traced(
            {"type": "region_moments", "catalog": catalog,
             "schema": schema, "table": table,
             "plan": plan_to_dict(plan),
             "regions": list(regions) if regions is not None
             else None})).encode())
        frames = []
        wire_bytes = 0
        try:
            reader = self.conn.do_get(ticket)
            while True:
                try:
                    chunk = reader.read_chunk()
                except StopIteration:
                    break
                if chunk.data is not None:
                    wire_bytes += chunk.data.nbytes
                    frames.append(chunk.data.to_pandas())
            _absorb_stream_stats(reader.schema)
        except flight.FlightError as e:
            raise _to_greptime_error(e) from None
        # actual serialized partial-frame bytes off THIS hop — lands on
        # the per-RPC node sub-collector so the EXPLAIN ANALYZE node
        # block shows what the wire carried instead of raw rows
        exec_stats.record("partial_wire", bytes=wire_bytes,
                          frames=len(frames))
        return [f for f in frames if len(f)]

    def scan_batches(self, catalog: str, schema: str, table: str,
                     projection: Optional[Sequence[str]] = None,
                     time_range=None, limit: Optional[int] = None,
                     filters: Optional[Sequence] = None,
                     regions: Optional[Sequence[int]] = None) -> list:
        from ..query.plan_codec import expr_to_dict
        if time_range is not None and hasattr(time_range, "start"):
            time_range = (time_range.start, time_range.end)
        ticket = flight.Ticket(json.dumps(_traced(
            {"type": "scan", "catalog": catalog, "schema": schema,
             "table": table, "projection": list(projection)
             if projection is not None else None,
             "time_range": list(time_range)
             if time_range is not None else None,
             "limit": limit,
             "filters": [expr_to_dict(f) for f in filters]
             if filters else None,
             "regions": list(regions)
             if regions is not None else None})).encode())
        out = []
        try:
            reader = self.conn.do_get(ticket)
            while True:
                try:
                    chunk = reader.read_chunk()
                except StopIteration:
                    break
                if chunk.data is not None:
                    out.append(RecordBatch.from_arrow(chunk.data))
            _absorb_stream_stats(reader.schema)
        except flight.FlightError as e:
            raise _to_greptime_error(e) from None
        return out

    def flush_table(self, catalog: str, schema: str, table: str) -> None:
        self._action("flush_table", {"catalog": catalog, "schema": schema,
                                     "table": table})

    def describe_table(self, catalog: str, schema: str, name: str):
        resp = self._action("describe_table", {
            "catalog": catalog, "schema": schema, "table": name})
        if resp.get("info") is None:
            return None
        from ..mito.engine import _deserialize_rule
        info = TableInfo.from_dict(resp["info"])
        return info, _deserialize_rule(info.meta.partition_rule)

    def ping(self) -> int:
        return int(self._action("ping", {})["node_id"])

    def repl_apply(self, catalog: str, schema: str, table: str,
                   region_number: int, entries: list,
                   leader_flushed: int = 0) -> dict:
        """Ship WAL records to this node's standby replica of the region
        (leader shipper → follower, the continuous replication hop)."""
        return self._action("repl_apply", {
            "catalog": catalog, "schema": schema, "table": table,
            "region_number": int(region_number), "entries": entries,
            "leader_flushed": int(leader_flushed)})

    def background_jobs(self) -> list:
        """This datanode's live + recent background jobs (the
        cluster-merged information_schema.background_jobs view)."""
        return list(self._action("background_jobs", {}).get("jobs", []))

    def profile(self, *, seconds=None, hz=None, drain: bool = False
                ) -> list:
        """This datanode's profiler rows: a timed high-rate burst
        (`seconds`/`hz`) or a drain of its pending sample aggregate —
        either way the frontend absorbs the rows and owns the flush."""
        body: dict = {}
        if seconds is not None:
            body["seconds"] = float(seconds)
            if hz is not None:
                body["hz"] = float(hz)
        elif drain:
            body["drain"] = True
        return list(self._action("profile", body).get("rows", []))


class Database(_FlightBase):
    """User-facing client (reference `Database`, client/src/database.rs)."""

    def sql(self, sql: str):
        """Run SQL; returns list[RecordBatch] for queries, int affected
        rows for DML/DDL."""
        ticket = flight.Ticket(json.dumps(_traced(
            {"type": "sql", "sql": sql})).encode())
        try:
            reader = self.conn.do_get(ticket)
            table = reader.read_all()
        except flight.FlightError as e:
            raise _to_greptime_error(e) from None
        meta = table.schema.metadata or {}
        if meta.get(b"gdb.kind") == b"affected_rows":
            return int(table.column(0)[0].as_py()) if table.num_rows else 0
        return [RecordBatch.from_arrow(b)
                for b in table.combine_chunks().to_batches()]

    def insert(self, table: str, columns: Dict[str, Sequence],
               tag_columns: Sequence[str] = (),
               timestamp_column: str = "greptime_timestamp") -> int:
        """gRPC-style row insert with auto table create / alter."""
        return self._put(
            {"type": "row_insert", "table": table,
             "tag_columns": list(tag_columns),
             "timestamp_column": timestamp_column},
            _columns_to_arrow(columns))

    def bulk_load(self, table: str, columns: Dict[str, Sequence],
                  tag_columns: Sequence[str] = (),
                  timestamp_column: str = "greptime_timestamp") -> int:
        """WAL-less bulk load (loader path): rows go straight to sorted
        SSTs server-side, skipping the WAL+memtable write path — same
        auto create/alter as insert(), ~an order of magnitude faster for
        large batches."""
        return self._put(
            {"type": "bulk_load", "table": table,
             "tag_columns": list(tag_columns),
             "timestamp_column": timestamp_column},
            _columns_to_arrow(columns))
