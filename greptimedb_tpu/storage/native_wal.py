"""ctypes binding for the native group-commit WAL (native/wal.cpp).

Reference behavior: the reference's WAL is raft-engine — a native log
store with batched fsync — behind the `LogStore` trait
(src/log-store/src/raft_engine/log_store.rs:46-120). `NativeWal` is a
drop-in for the Python `Wal` (same directory, same record format, same
API) with appends and group commit in C++: concurrent writers share one
fdatasync instead of paying one each.

The shared library builds on first use with g++ (cached next to the
source, keyed by source mtime). If the toolchain is unavailable the
caller falls back to the Python Wal — `load_library()` returns None.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

from ..errors import StorageError
from .wal import Wal

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "wal.cpp")
_LIB = os.path.join(_NATIVE_DIR, "libgdbwal.so")

_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _build() -> Optional[str]:
    if os.path.exists(_LIB) and \
            os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return _LIB
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           "-o", _LIB + ".tmp", _SRC, "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        from ..utils import atomic_publish
        atomic_publish(_LIB + ".tmp", _LIB, fsync=False)  # build artifact
        return _LIB
    except (subprocess.SubprocessError, OSError) as e:
        logger.warning("native WAL build failed (%s); using Python WAL", e)
        return None


def load_library() -> Optional[ctypes.CDLL]:
    """Build (if needed) + load the native WAL library; None on failure."""
    global _lib, _lib_failed
    if _lib is not None:
        return _lib
    if _lib_failed:
        return None
    with _build_lock:
        if _lib is not None:
            return _lib
        path = _build()
        if path is None:
            _lib_failed = True
            return None
        lib = ctypes.CDLL(path)
        lib.wal_open.restype = ctypes.c_void_p
        lib.wal_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                 ctypes.c_uint32]
        lib.wal_append.restype = ctypes.c_int64
        lib.wal_append.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.c_uint32, ctypes.c_char_p,
                                   ctypes.c_uint32]
        lib.wal_wait.restype = ctypes.c_int
        lib.wal_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                 ctypes.c_uint32]
        lib.wal_sync.restype = ctypes.c_int
        lib.wal_sync.argtypes = [ctypes.c_void_p]
        lib.wal_obsolete.restype = ctypes.c_int
        lib.wal_obsolete.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.wal_close.restype = None
        lib.wal_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


class NativeWal(Wal):
    """Same surface as `Wal`; append/sync/obsolete run in C++.

    `sync_on_write=True` maps to "append then wait for the group-commit
    epoch" — N concurrent writers pay ONE fdatasync, not N.
    Reads (`read_from`) reuse the Python segment parser: the format is
    shared and replay is a cold path.
    """

    def __init__(self, dir_path: str, *, sync_on_write: bool = False,
                 segment_bytes: Optional[int] = None,
                 group_interval_us: int = 500):
        lib = load_library()
        if lib is None:
            raise StorageError("native WAL library unavailable")
        super().__init__(dir_path, sync_on_write=sync_on_write,
                         segment_bytes=segment_bytes)
        self._libref = lib
        self._handle = lib.wal_open(
            dir_path.encode(), self.segment_bytes, group_interval_us)
        if not self._handle:
            raise StorageError(f"wal_open failed for {dir_path}")

    # ---- overridden hot path ----
    def append_async(self, seq: int, payload: bytes,
                     schema_version: int = 0) -> int:
        """Write one record in C++; returns the native group-commit
        ticket for :meth:`wait_durable` (no durability wait here)."""
        from ..common.failpoint import fail_point
        fail_point("wal_append")
        handle = self._handle
        if handle is None:
            raise StorageError("append on closed NativeWal")
        ticket = self._libref.wal_append(handle, seq, schema_version,
                                         payload, len(payload))
        if ticket < 0:
            raise StorageError(f"wal_append failed: errno {-ticket}")
        from ..common.telemetry import increment_counter
        increment_counter("wal_bytes", len(payload))
        return ticket

    def append(self, seq: int, payload: bytes,
               schema_version: int = 0) -> None:
        ticket = self.append_async(seq, payload, schema_version)
        if self.sync_on_write:
            self._wait_ticket(ticket)

    def wait_durable(self, ticket: int) -> None:
        """Wait for the native group-commit epoch covering `ticket` —
        N concurrent writers share ONE fdatasync in C++."""
        from ..common.failpoint import fail_point
        fail_point("wal_group_commit")
        self._wait_ticket(ticket)

    def _wait_ticket(self, ticket: int) -> None:
        from ..common.failpoint import fail_point
        from ..common.telemetry import timer
        handle = self._handle
        if handle is None:
            raise StorageError("wait on closed NativeWal")
        fail_point("wal_fsync")
        with timer("wal_fsync"):
            rc = self._libref.wal_wait(handle, ticket, 30_000)
        if rc != 0:
            raise StorageError(f"wal_wait failed: {rc}")

    def sync(self) -> None:
        if self._handle is not None:
            rc = self._libref.wal_sync(self._handle)
            if rc != 0:
                raise StorageError(f"wal_sync failed: {rc}")

    def read_from(self, start_seq: int):
        # flush C++ buffers (appends use unbuffered write(2); a sync makes
        # everything visible+durable before replay reads the files)
        self.sync()
        # bypass Wal's file-handle bookkeeping; segments live on disk
        segs = self._segments()
        for i, (first, path) in enumerate(segs):
            if i + 1 < len(segs) and segs[i + 1][0] <= start_seq:
                continue
            records, clean, good_pos = self._read_segment(path, start_seq)
            yield from records
            if not clean:
                if i + 1 < len(segs):
                    raise StorageError(
                        f"corrupt WAL record mid-log in {path}; refusing "
                        f"to replay past the gap")
                self._repair_torn_tail(path, good_pos)
                return

    def obsolete(self, seq: int) -> None:
        if self._handle is not None:
            rc = self._libref.wal_obsolete(self._handle, seq)
            if rc != 0:
                raise StorageError(f"wal_obsolete failed: {rc}")

    def close(self) -> None:
        handle, self._handle = self._handle, None
        if handle is not None:
            self._libref.wal_close(handle)

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:  # greptlint: disable=GL01 — finalizers must
            # never raise; at interpreter teardown even logging can fail
            pass


def make_wal(dir_path: str, *, sync_on_write: bool = False,
             segment_bytes: Optional[int] = None,
             backend: str = "auto") -> Wal:
    """WAL factory: 'native' | 'python' | 'auto' (native with fallback)."""
    if backend in ("auto", "native") and load_library() is not None:
        return NativeWal(dir_path, sync_on_write=sync_on_write,
                         segment_bytes=segment_bytes)
    if backend == "native":
        raise StorageError("native WAL requested but unavailable")
    return Wal(dir_path, sync_on_write=sync_on_write,
               segment_bytes=segment_bytes)
