"""SST files: Parquet on object storage with stats-based pruning.

Reference behavior: src/storage/src/sst.rs + sst/parquet.rs — two LSM levels,
`FileMeta` with per-file time ranges, ParquetWriter with row-group stats,
reader with row-group pruning + time-range row filtering.

File layout: tag columns (dictionary-encoded), the time index, field columns,
plus internal columns `__series_id` (int32, stable via the region's persisted
SeriesDict), `__sequence` (int64), `__op_type` (int8). Rows are stored sorted
by (series_id, ts, seq), so scans feed the device merge kernel directly and
row groups cover disjoint-ish series/time ranges for pruning.
"""

from __future__ import annotations

import io
import os
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from ..common import failpoint as _fp
from ..common.time import TimestampRange
from ..datatypes import RecordBatch, Schema, Vector
from ..datatypes.vector import compat_column, null_column
from .index import (SstIndex, index_file_name, load_sst_index,
                    sst_index_enabled)
from .object_store import ObjectStore

_fp.register("sst_write")
_fp.register("sst_write_after")

SERIES_COL = "__series_id"
SEQ_COL = "__sequence"
OP_COL = "__op_type"
MAX_LEVEL = 2
#: rows per parquet row group. Large groups encode ~3x faster (fewer
#: page/stat boundaries) and slice planning only needs row-group stats at
#: slice granularity (millions of rows); the reference uses 4Mi-row
#: groups for the same reason (src/storage/src/sst/parquet.rs
#: DEFAULT_ROW_GROUP_SIZE).
DEFAULT_ROW_GROUP_SIZE = 1 << 20


@dataclass(frozen=True)
class FileMeta:
    file_name: str
    level: int
    time_range: Tuple[int, int]       # inclusive min/max ts
    num_rows: int
    file_size: int
    max_sequence: int = 0
    #: delete tombstones in the file; None = unknown (pre-upgrade files)
    num_deletes: Optional[int] = None
    #: inclusive min/max __series_id; None = unknown (pre-upgrade files).
    #: With time_range it bounds the file's key rectangle — two files
    #: disjoint on either axis cannot hold competing versions of a key
    #: (compaction's trivial move and scan planning rely on this).
    sid_range: Optional[Tuple[int, int]] = None
    #: adjacent rows sharing a (series_id, ts) key (MVCC versions inside
    #: this file); None = unknown (pre-upgrade files). A slice covering
    #: only dup-free, delete-free, key-disjoint files needs no merge
    #: dedup at all — the streamed cold scan skips the per-row key
    #: comparison pass (and the ts decode, when the query never reads
    #: time) on that proof.
    num_dup_keys: Optional[int] = None
    #: secondary-index sidecar (storage/index.py: sid bloom + per-row-
    #: group sid summaries) in the same sst/ dir; None = pre-upgrade
    #: file or index disabled at write time — stats-only pruning then.
    #: Set only AFTER the sidecar is durable, so the manifest can never
    #: reference a sidecar that was not written (torture point 16).
    index_file: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "file_name": self.file_name, "level": self.level,
            "time_range": list(self.time_range), "num_rows": self.num_rows,
            "file_size": self.file_size, "max_sequence": self.max_sequence,
            "num_deletes": self.num_deletes,
            "sid_range": list(self.sid_range)
            if self.sid_range is not None else None,
            "num_dup_keys": self.num_dup_keys,
            "index_file": self.index_file,
        }

    @staticmethod
    def from_dict(d: dict) -> "FileMeta":
        return FileMeta(d["file_name"], d["level"], tuple(d["time_range"]),
                        d["num_rows"], d["file_size"],
                        d.get("max_sequence", 0), d.get("num_deletes"),
                        tuple(d["sid_range"])
                        if d.get("sid_range") is not None else None,
                        d.get("num_dup_keys"),
                        d.get("index_file"))

    def keys_overlap(self, other: "FileMeta") -> bool:
        """Whether the two files' key rectangles intersect — i.e. some
        (series, ts) key could live in both."""
        if self.time_range[1] < other.time_range[0] or \
                other.time_range[1] < self.time_range[0]:
            return False
        a, b = self.sid_range, other.sid_range
        if a is not None and b is not None and (a[1] < b[0] or b[1] < a[0]):
            return False
        return True


class LevelMetas:
    """Files per level (0 = fresh flushes, 1 = compacted)."""

    def __init__(self, levels: Optional[List[List[FileMeta]]] = None):
        self.levels: List[List[FileMeta]] = levels or [[] for _ in range(MAX_LEVEL)]

    def add_files(self, files: Sequence[FileMeta]) -> "LevelMetas":
        new = [list(l) for l in self.levels]
        for f in files:
            new[f.level].append(f)
        return LevelMetas(new)

    def remove_files(self, names: Sequence[str]) -> "LevelMetas":
        drop = set(names)
        return LevelMetas([[f for f in l if f.file_name not in drop]
                           for l in self.levels])

    def all_files(self) -> List[FileMeta]:
        return [f for l in self.levels for f in l]

    def files_in_range(self, rng: Optional[TimestampRange]) -> List[FileMeta]:
        files = self.all_files()
        if rng is None:
            return files
        out = []
        for f in files:
            lo, hi = f.time_range
            if rng.intersects(TimestampRange(lo, hi + 1, rng.unit)):
                out.append(f)
        return out

    def to_dict(self) -> dict:
        return {"levels": [[f.to_dict() for f in l] for l in self.levels]}

    @staticmethod
    def from_dict(d: dict) -> "LevelMetas":
        return LevelMetas([[FileMeta.from_dict(f) for f in l]
                           for l in d["levels"]])


@dataclass
class SstData:
    """Decoded SST contents (SoA, ready for the device merge kernel)."""
    series_ids: np.ndarray
    ts: np.ndarray
    seq: np.ndarray
    op_types: np.ndarray
    fields: Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]]
    num_rows: int


def new_sst_name() -> str:
    return f"{uuid.uuid4().hex}.parquet"


class AccessLayer:
    """Writes/reads SSTs for one region directory on an object store
    (reference: src/storage/src/sst.rs AccessLayer/FsAccessLayer)."""

    def __init__(self, store: ObjectStore, sst_dir: str, schema: Schema,
                 row_group_size: int = DEFAULT_ROW_GROUP_SIZE,
                 compression: str = "lz4",
                 field_encoding: str = "dictionary"):
        self.store = store
        self.sst_dir = sst_dir.rstrip("/")
        self.schema = schema
        self.row_group_size = row_group_size
        #: metric-column encoding: "dictionary" (parquet-adaptive, decodes
        #: fastest when values repeat — e.g. fixed-precision telemetry) or
        #: "byte_stream_split" (uniform encode cost on full-entropy floats)
        self.field_encoding = field_encoding
        #: parquet codec. lz4 decodes ~1.7x faster than zstd on mostly-
        #: incompressible float telemetry at near-identical file size —
        #: and single-core decode rate bounds the cold streamed scan.
        #: (The reference defaults to zstd, src/storage/src/sst/parquet.rs;
        #: we trade a few % of ratio for scan throughput.)
        self.compression = compression
        #: per-file row-group time stats, keyed by (immutable) file name
        self._rg_stats: Dict[str, List[Tuple[int, int, int]]] = {}
        #: parsed index sidecars, keyed by file name; the None sentinel
        #: pins a missing/corrupt verdict so a poisoned sidecar is not
        #: re-read (and re-logged) on every query — reopening the region
        #: (a fresh layer) retries
        self._sst_index: Dict[str, Optional[SstIndex]] = {}

    def _key(self, file_name: str) -> str:
        return f"{self.sst_dir}/{file_name}"

    # ---- secondary index sidecars ----
    def _cache_index(self, file_name: str, idx: Optional[SstIndex]) -> None:
        if len(self._sst_index) > 4096:      # bound like the footer cache
            self._sst_index.clear()
        self._sst_index[file_name] = idx

    def load_index(self, meta: FileMeta) -> Optional[SstIndex]:
        """The file's parsed index sidecar, or None (stats-only pruning:
        pre-upgrade file, index disabled, or corrupt/missing sidecar —
        the degrade path, counted by greptime_sst_index_degrade_total)."""
        if meta.index_file is None or not sst_index_enabled():
            return None
        if meta.file_name in self._sst_index:
            return self._sst_index[meta.file_name]
        idx = load_sst_index(self.store.read, self._key(meta.index_file),
                             meta.num_rows)
        self._cache_index(meta.file_name, idx)
        return idx

    # ---- write ----
    def write_sst(self, *, level: int, series_ids: np.ndarray, ts: np.ndarray,
                  seq: np.ndarray, op_types: np.ndarray,
                  fields: Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]],
                  tag_columns: Dict[str, list],
                  schema: Optional[Schema] = None) -> Optional[FileMeta]:
        """Write one SST from sorted SoA arrays. Returns None for empty
        input. `schema` overrides the layer's current schema (background
        flush of a memtable frozen before an ALTER)."""
        n = len(ts)
        if n == 0:
            return None
        from ..common.telemetry import timer as _timer
        with _timer("sst_write"):
            return self._write_sst_inner(
                level=level, series_ids=series_ids, ts=ts, seq=seq,
                op_types=op_types, fields=fields, tag_columns=tag_columns,
                schema=schema)

    def _write_sst_inner(self, *, level, series_ids, ts, seq, op_types,
                         fields, tag_columns, schema) -> Optional[FileMeta]:
        _fp.fail_point("sst_write")
        n = len(ts)
        schema = schema if schema is not None else self.schema
        arrays: List[pa.Array] = []
        names: List[str] = []
        for c in schema.column_schemas:
            if c.is_tag:
                tc = tag_columns[c.name]
                if isinstance(tc, tuple):
                    # (per-row value ids, dictionary values) from the
                    # SeriesDict: build the DictionaryArray directly
                    idx, vals = tc
                    arr = pa.DictionaryArray.from_arrays(
                        pa.array(np.asarray(idx, dtype=np.int32)),
                        pa.array(list(vals), type=c.dtype.pa_type))
                else:
                    arr = pa.array(tc, type=c.dtype.pa_type) \
                        .dictionary_encode()
                arrays.append(arr)
                names.append(c.name)
            elif c.is_time_index:
                arrays.append(pa.array(ts, type=pa.int64()).cast(c.dtype.pa_type))
                names.append(c.name)
            else:
                data, validity = fields[c.name]
                vec = Vector(c.dtype, data, validity)
                arrays.append(vec.to_arrow())
                names.append(c.name)
        arrays.append(pa.array(series_ids, type=pa.int32()))
        names.append(SERIES_COL)
        arrays.append(pa.array(seq, type=pa.int64()))
        names.append(SEQ_COL)
        arrays.append(pa.array(op_types, type=pa.int8()))
        names.append(OP_COL)
        table = pa.table(dict(zip(names, arrays)))
        ts_name = schema.timestamp_column.name
        # Encode/stat choices are ingest-rate critical (profiled in
        # BASELINE.md): stats only on the two pruning columns (ts, sid) —
        # per-page min/max on the metric columns bought nothing and cost
        # ~35% of encode; dictionary encoding stays OFF for ts/sid (mostly
        # unique / already dense — hashing them is pure waste) and ON
        # elsewhere, where parquet's adaptive fallback bounds the cost on
        # incompressible metrics. byte_stream_split is the configurable
        # alternative for float metrics (field_encoding knob): it encodes
        # fast on any distribution but decodes ~20% slower than dict-hit
        # columns, and the cold scan is decode-bound.
        no_dict = {ts_name, SERIES_COL}
        bss_cols = []
        if self.field_encoding == "byte_stream_split":
            for c in schema.field_columns():
                if c.dtype.np_dtype is not None and \
                        np.issubdtype(c.dtype.np_dtype, np.floating):
                    no_dict.add(c.name)
                    bss_cols.append(c.name)
        opts = dict(
            row_group_size=self.row_group_size,
            compression=self.compression,
            write_statistics=[ts_name, SERIES_COL],
            use_dictionary=[nm for nm in names if nm not in no_dict],
        )
        if bss_cols:
            opts["use_byte_stream_split"] = bss_cols
        file_name = new_sst_name()
        key = self._key(file_name)
        put = getattr(self.store, "put_path", None)
        if put is not None:
            # stream pages straight to the destination file — the
            # BytesIO spool + getvalue + write() round trip copied the
            # whole file twice
            with put(key) as tmp:
                pq.write_table(table, tmp, **opts)
                size = os.path.getsize(tmp)
        else:
            sink = io.BytesIO()
            pq.write_table(table, sink, **opts)
            data = sink.getvalue()
            size = len(data)
            self.store.write(key, data)
        # the parquet file is durable but unreferenced: a crash HERE
        # leaves an orphan SST for the reopen sweep to collect
        _fp.fail_point("sst_write_after")
        index_file = None
        if sst_index_enabled():
            try:
                # crash HERE = SST data durable, index sidecar not:
                # neither is referenced yet (the manifest edit commits
                # later), so the reopen sweep collects both — a committed
                # FileMeta can never name a sidecar that is not on disk
                # (torture point 16). A SimulatedCrash is a BaseException
                # and propagates; an injected err degrades below.
                _fp.fail_point("sst_index_write")
                sidx = SstIndex.build(series_ids, self.row_group_size)
                candidate = index_file_name(file_name)
                self.store.write(self._key(candidate), sidx.to_bytes())
                index_file = candidate
                # the freshly built object serves reads until evicted —
                # no reason to re-parse our own bytes on first consult
                self._cache_index(file_name, sidx)
            except Exception as e:  # noqa: BLE001 — the index is an
                # optimization: a failed sidecar write degrades this
                # file to stats-only pruning, it must not fail the flush
                from ..common.telemetry import increment_counter
                increment_counter("sst_index_degrade")
                import logging
                logging.getLogger(__name__).warning(
                    "SST %s: index sidecar write failed (%s); file "
                    "stays stats-only", file_name, e)
        dups = 0
        if n > 1:
            # rows are (sid, ts, seq)-sorted: duplicate keys are adjacent
            dups = int(np.count_nonzero(
                (series_ids[1:] == series_ids[:-1]) & (ts[1:] == ts[:-1])))
        return FileMeta(
            file_name=file_name, level=level,
            time_range=(int(ts.min()), int(ts.max())),
            num_rows=n, file_size=size,
            max_sequence=int(seq.max()) if n else 0,
            num_deletes=int(np.count_nonzero(op_types)),
            sid_range=(int(series_ids.min()), int(series_ids.max())),
            num_dup_keys=dups, index_file=index_file)

    # ---- read ----
    def read_sst(self, meta: FileMeta, *,
                 projection: Optional[Sequence[str]] = None,
                 time_range: Optional[TimestampRange] = None,
                 series_range: Optional[Tuple[int, int]] = None,
                 sid_set: Optional[np.ndarray] = None,
                 synthetic_seq: bool = False,
                 need_ts: bool = True) -> SstData:
        """Read an SST with column projection and row-group pruning on
        the time index and/or the series id (`series_range` is a
        half-open [lo, hi) over __series_id — the storage sort order,
        so series pruning is tight on every file layout).

        `sid_set` is a SORTED array of candidate series ids (a resolved
        point/IN tag predicate): row groups are selected through the
        index sidecar's per-group sid summary when present — exact
        membership, no footer stats consulted — and through footer
        min/max otherwise. Row-level filtering stays with the caller
        (RegionSnapshot.scan masks by membership).

        synthetic_seq=True skips decoding the 8-byte __sequence column
        and fills meta.max_sequence instead: per-file sequence ranges
        are disjoint (flushes cover consecutive windows; compaction
        replaces its inputs), so the file rank orders cross-file MVCC
        versions exactly, and within-file versions are already stored
        seq-ascending (stable sort keeps them). Only valid for readers
        that never filter by sequence value (the streamed scan); the
        incremental cache needs real sequences. When the file records
        zero deletes the __op_type column is skipped too.

        need_ts=False additionally skips decoding the time index (the
        widest internal column) and returns a 0-stride zero ts. Only
        valid when the caller proved it will never consult row times:
        no time filter/bucket in the query and no merge dedup needed
        (dup-free, delete-free, key-disjoint files — see
        FileMeta.num_dup_keys). Row-group pruning still works — it
        reads footer stats, not the column."""
        key = self._key(meta.file_name)
        path = self.store.local_path(key)
        src = path if path is not None else pa.BufferReader(self.store.read(key))
        pf = pq.ParquetFile(src)
        ts_name = self.schema.timestamp_column.name
        ts_idx = pf.schema_arrow.get_field_index(ts_name)
        groups = self._prune_row_groups(pf, ts_idx, time_range)
        if series_range is not None and groups:
            sid_idx = pf.schema_arrow.get_field_index(SERIES_COL)
            s0, s1 = series_range
            kept = []
            for g in groups:
                stats = pf.metadata.row_group(g).column(sid_idx).statistics
                if stats is None or not stats.has_min_max:
                    kept.append(g)
                    continue
                if int(stats.max) >= s0 and int(stats.min) < s1:
                    kept.append(g)
            groups = kept
        if sid_set is not None and groups:
            idx = self.load_index(meta)
            if idx is not None and \
                    len(idx.rg_lo) == pf.metadata.num_row_groups:
                gk = idx.row_groups_for(sid_set)
                groups = [g for g in groups if gk[g]]
            else:
                # stats-only degrade: footer min/max per group
                sid_idx = pf.schema_arrow.get_field_index(SERIES_COL)
                s = np.asarray(sid_set, dtype=np.int64)
                kept = []
                for g in groups:
                    stats = pf.metadata.row_group(g).column(
                        sid_idx).statistics
                    if stats is None or not stats.has_min_max:
                        kept.append(g)
                        continue
                    i = int(np.searchsorted(s, int(stats.min)))
                    if i < len(s) and int(s[i]) <= int(stats.max):
                        kept.append(g)
                groups = kept
        from ..common import exec_stats
        exec_stats.record("prune", files=1,
                          row_groups=pf.metadata.num_row_groups,
                          row_groups_kept=len(groups))
        field_names = [c.name for c in self.schema.field_columns()
                       if projection is None or c.name in projection]
        # schema-compat: an SST written before an ALTER may lack new columns —
        # absent columns read as nulls (reference: src/storage/src/schema/compat.rs)
        present = set(pf.schema_arrow.names)
        missing = [n for n in field_names if n not in present]
        skip_seq = synthetic_seq
        skip_op = synthetic_seq and meta.num_deletes == 0
        cols = [n for n in field_names if n in present] + [SERIES_COL]
        if need_ts:
            cols.append(ts_name)
        if not skip_seq:
            cols.append(SEQ_COL)
        if not skip_op:
            cols.append(OP_COL)
        if not groups:
            empty_fields = {
                name: null_column(self.schema.column_schema(name).dtype, 0)
                for name in field_names}
            z64 = np.zeros(0, np.int64)
            return SstData(np.zeros(0, np.int32), z64, z64,
                           np.zeros(0, np.int8), empty_fields, 0)
        import time as _time
        _t0 = _time.perf_counter()
        table = pf.read_row_groups(groups, columns=cols, use_threads=True)
        _dt = _time.perf_counter() - _t0
        exec_stats.record("decode", rows=table.num_rows, elapsed_s=_dt)
        from ..common.telemetry import _observe
        _observe("sst_read", _dt)
        if need_ts:
            tcol = table.column(ts_name)
            if pa.types.is_timestamp(tcol.type):
                # reinterpret, don't cast: the compute cast pays arrow's
                # kernel-registry init on first use and a copy after
                tcol = pa.chunked_array([c.view(pa.int64())
                                         for c in tcol.chunks])
            elif tcol.type != pa.int64():
                tcol = tcol.cast(pa.int64())
            ts = np.asarray(tcol)
        else:
            ts = np.broadcast_to(np.int64(0), (table.num_rows,))
        sids = np.asarray(table.column(SERIES_COL))
        # synthetic columns are constant: 0-stride broadcast views cost
        # no allocation or fill (8 MB+ per million rows otherwise)
        seq = np.broadcast_to(np.int64(meta.max_sequence),
                              (table.num_rows,)) \
            if skip_seq else np.asarray(table.column(SEQ_COL))
        op = np.broadcast_to(np.int8(0), (table.num_rows,)) \
            if skip_op else np.asarray(table.column(OP_COL))
        # copy=False: arrow hands back correctly-typed arrays already —
        # the astype calls below are layout/dtype *assertions*, and an
        # unconditional copy costs ~0.25s per 8M-row cold slice
        fields = {}
        for name in field_names:
            cs = self.schema.column_schema(name)
            if name in missing:
                # added after this SST was written: default-fill
                fields[name] = compat_column(cs, table.num_rows)
                continue
            col = table.column(name)
            want = cs.dtype.pa_type
            if want is not None and col.type != want:
                # dropped + re-added under a different type (the reference
                # disambiguates by column id, compat.rs): cast when the
                # values convert, otherwise treat as a fresh column
                try:
                    col = col.cast(want)
                except pa.ArrowInvalid:
                    fields[name] = compat_column(cs, table.num_rows)
                    continue
            vec = Vector.from_arrow(col)
            fields[name] = (vec.data, vec.validity)
        return SstData(sids.astype(np.int32, copy=False),
                       ts.astype(np.int64, copy=False),
                       seq.astype(np.int64, copy=False),
                       op.astype(np.int8, copy=False),
                       fields, table.num_rows)

    def read_tag_columns(self, meta: FileMeta,
                         tag_names: Sequence[str]) -> Dict[str, list]:
        key = self._key(meta.file_name)
        path = self.store.local_path(key)
        src = path if path is not None else pa.BufferReader(self.store.read(key))
        table = pq.read_table(src, columns=list(tag_names) + [SERIES_COL])
        return {n: table.column(n).to_pylist() for n in tag_names} | {
            SERIES_COL: np.asarray(table.column(SERIES_COL)).astype(np.int32)}

    def _np_dtype(self, field_name: str):
        dt = self.schema.column_schema(field_name).dtype
        return dt.np_dtype if dt.np_dtype is not None else object

    def _prune_row_groups(self, pf: pq.ParquetFile, ts_idx: int,
                          time_range: Optional[TimestampRange]) -> List[int]:
        ngroups = pf.metadata.num_row_groups
        if time_range is None:
            return list(range(ngroups))
        unit = self.schema.timestamp_column.dtype.time_unit
        out = []
        for g in range(ngroups):
            col = pf.metadata.row_group(g).column(ts_idx)
            stats = col.statistics
            if stats is None or not stats.has_min_max:
                out.append(g)
                continue
            lo = _ts_stat_to_int(stats.min, unit)
            hi = _ts_stat_to_int(stats.max, unit)
            if time_range.intersects(TimestampRange(lo, hi + 1, time_range.unit)):
                out.append(g)
        return out

    def row_group_stats(self, meta: FileMeta
                        ) -> List[Tuple[int, int, int, int, int]]:
        """(min_ts, max_ts, min_sid, max_sid, num_rows) per row group,
        from parquet footer statistics — the density profiles the
        streamed cold scan uses to cut slices (reference: sst/parquet.rs
        row-group readers). SSTs sort by (series, ts), so series stats
        are tight on files that span long time ranges (compaction
        output) while time stats are tight on short-window flush files;
        the slice planner picks whichever dimension prunes better.
        Cached per file name (SSTs are immutable)."""
        cached = self._rg_stats.get(meta.file_name)
        if cached is not None:
            return cached
        key = self._key(meta.file_name)
        path = self.store.local_path(key)
        src = path if path is not None \
            else pa.BufferReader(self.store.read(key))
        pf = pq.ParquetFile(src)
        ts_name = self.schema.timestamp_column.name
        ts_idx = pf.schema_arrow.get_field_index(ts_name)
        sid_idx = pf.schema_arrow.get_field_index(SERIES_COL)
        unit = self.schema.timestamp_column.dtype.time_unit
        out: List[Tuple[int, int, int, int, int]] = []
        for g in range(pf.metadata.num_row_groups):
            rg = pf.metadata.row_group(g)
            tstats = rg.column(ts_idx).statistics
            if tstats is None or not tstats.has_min_max:
                tlo, thi = meta.time_range
            else:
                tlo = _ts_stat_to_int(tstats.min, unit)
                thi = _ts_stat_to_int(tstats.max, unit)
            sstats = rg.column(sid_idx).statistics \
                if sid_idx >= 0 else None
            if sstats is None or not sstats.has_min_max:
                slo, shi = 0, 1 << 30
            else:
                slo, shi = int(sstats.min), int(sstats.max)
            out.append((tlo, thi, slo, shi, rg.num_rows))
        if len(self._rg_stats) > 4096:     # bound the footer cache
            self._rg_stats.clear()
        self._rg_stats[meta.file_name] = out
        return out

    def delete_sst(self, file_name: str) -> None:
        self.store.delete(self._key(file_name))
        # the sidecar lives and dies with its SST (best-effort: an
        # index orphaned by a crash mid-delete is swept at reopen)
        self._sst_index.pop(file_name, None)
        try:
            self.store.delete(self._key(index_file_name(file_name)))
        except FileNotFoundError:
            pass                             # stats-only file: no sidecar
        except Exception as e:  # noqa: BLE001 — the data file is gone; a
            # stale sidecar is harmless garbage the reopen sweep collects
            import logging
            logging.getLogger(__name__).warning(
                "could not delete index sidecar of %s: %s", file_name, e)


def _ts_stat_to_int(v, unit) -> int:
    if isinstance(v, (int, np.integer)):
        return int(v)
    # pyarrow returns datetime for timestamp logical-typed stats
    import datetime as _dt
    from ..common.time import Timestamp
    if isinstance(v, _dt.datetime):
        return Timestamp.from_datetime(v, unit).value
    return int(v)
