"""Downsampling: aggregate a region's rows into coarser time buckets.

The north-star maintenance job (BASELINE config 5: 1s→1m downsample).
The reference has no downsample in v0.2 — its compaction only merges
files — so this is a capability extension: a background job that reduces
every (series, bucket) group with the scatter-free sorted-segment TPU
kernel and writes the result into a destination region whose time index
carries the bucket timestamps.

TPU-first data flow: the job rides the SAME device-resident merged-scan
cache the query path uses (`query/tpu_exec.SCAN_CACHE`) — on a region
that has been queried (or downsampled) before, the sorted/deduped column
arrays are already in HBM and the job ships only the run ids; on a cold
region the cache build it pays is then amortized by every later query.
All device work is dispatched asynchronously and fetched in ONE batched
device_get, so host-side prep for the destination write overlaps the
kernel execution instead of serializing behind it.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import numpy as np

logger = logging.getLogger(__name__)

_SUPPORTED = ("avg", "sum", "min", "max", "count", "first", "last")


def downsample_region(src, dst, *, stride_ms: int,
                      aggs: Optional[Dict[str, str]] = None,
                      time_range=None) -> int:
    """Aggregate `src` rows into `stride_ms` buckets and append to `dst`.

    aggs maps field name → op (default: avg for every numeric field).
    Destination schema must have the same tags, a timestamp column, and the
    aggregated field columns. Returns the number of rows written."""
    import jax

    from ..ops.kernels import shape_bucket, sorted_grouped_aggregate
    from ..query.tpu_exec import SCAN_CACHE
    from .write_batch import WriteBatch

    schema = src.schema
    field_names = [c.name for c in schema.field_columns()
                   if not schema.column_schema(c.name).dtype.is_string]
    if aggs is None:
        aggs = {f: "avg" for f in field_names}
    for f, op in aggs.items():
        if op not in _SUPPORTED:
            raise ValueError(f"unsupported downsample op {op}")

    # merged + MVCC-deduped view, sorted by (series, ts); PUT rows only
    # (tombstones are dropped by the merge). Device mirrors of ts/fields
    # are cached per region version and shared with the query path.
    scan = SCAN_CACHE.get(src)
    n = scan.num_rows
    if n == 0:
        return 0
    sids, ts = scan.series_ids, scan.ts

    mask_np = None
    if time_range is not None:
        mask_np = np.ones(n, dtype=bool)
        if time_range.start is not None:
            mask_np &= ts >= time_range.start
        if time_range.end is not None:
            mask_np &= ts < time_range.end
        if not mask_np.any():
            return 0

    # run ids over (series, bucket): rows are sorted by (series, ts) so
    # pair changes are run boundaries — vectorized host pass, and the
    # segment ends ship with the call (no device binary search)
    buckets = ts // stride_ms
    flags = np.empty(n, dtype=bool)
    flags[0] = True
    np.not_equal(sids[1:], sids[:-1], out=flags[1:])
    flags[1:] |= buckets[1:] != buckets[:-1]
    rid = np.cumsum(flags, dtype=np.int32) - 1
    nruns = int(rid[-1]) + 1
    run_starts = np.nonzero(flags)[0]

    nbucket = shape_bucket(nruns, minimum=256)
    d_mask = jax.device_put(mask_np) if mask_np is not None \
        else scan.device_valid_all()
    d_ts = scan.device_ts()
    # with host-precomputed ends the kernel reads gids only for first/last
    # (arg-extreme tie-break); every other op works off the segment bounds,
    # so the O(n) rid upload is skipped and ts stands in for shape
    needs_gids = any(op in ("first", "last") for op in aggs.values())
    d_rid = jax.device_put(rid) if needs_gids else d_ts

    values, col_masks, ops, slots = [], [], [], []
    for fname in field_names:
        if fname not in aggs:
            continue
        op = aggs[fname]
        values.append(d_ts if op == "count" else scan.device_field(fname))
        col_masks.append(scan.device_valid(fname))
        ops.append(op)
        slots.append(fname)

    run_ends = np.full(nbucket, n, dtype=np.int32)
    run_ends[:nruns - 1] = run_starts[1:]
    results, counts = sorted_grouped_aggregate(
        d_rid, d_mask, d_ts, tuple(values), tuple(col_masks),
        num_groups=nbucket, ops=tuple(ops), has_col_masks=True,
        ends=run_ends)

    # host prep for the destination write runs while the device computes
    # (dispatch above is async); the single batched fetch below is the
    # only synchronization point
    out_sids = sids[run_starts]
    out_ts = buckets[run_starts] * stride_ms
    counts, results = jax.device_get((counts, list(results)))
    counts = counts[:nruns]
    live = counts > 0
    out_sids, out_ts = out_sids[live], out_ts[live]

    cols: Dict[str, list] = {}
    sd = src.series_dict
    for i, tag in enumerate(sd.tag_names):
        cols[tag] = sd.decode_tag_column(out_sids, i)
    ts_name = dst.schema.timestamp_column.name
    cols[ts_name] = out_ts
    for fname, op, res in zip(slots, ops, results):
        vals = np.asarray(res)[:nruns][live].astype(np.float64)
        nan = np.isnan(vals)
        cols[fname] = vals if not nan.any() else \
            [None if m else float(v) for v, m in zip(vals, nan)]

    n_out = len(out_ts)
    if n_out == 0:
        return 0
    wb = WriteBatch(dst.schema)
    wb.put(cols)
    dst.write(wb)
    logger.info("downsampled %s -> %s: %d rows into %d buckets (stride %dms)",
                src.name, dst.name, n, n_out, stride_ms)
    return n_out
