"""Downsampling: aggregate a region's rows into coarser time buckets.

The north-star maintenance job (BASELINE config 5: 1s→1m downsample).
The reference has no downsample in v0.2 — its compaction only merges
files — so this is a capability extension: a background job that reads a
source region (merged + deduped), reduces every (series, bucket) group
with the scatter-free sorted-segment TPU kernel, and writes the result
into a destination region whose time index carries the bucket timestamps.

Data flow (all static-shaped for XLA):
  merged scan (sorted by series, ts) → run ids over (series, bucket)
  → sorted_grouped_aggregate moments on device → host fold → WriteBatch.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

_SUPPORTED = ("avg", "sum", "min", "max", "count", "first", "last")


def downsample_region(src, dst, *, stride_ms: int,
                      aggs: Optional[Dict[str, str]] = None,
                      time_range=None) -> int:
    """Aggregate `src` rows into `stride_ms` buckets and append to `dst`.

    aggs maps field name → op (default: avg for every numeric field).
    Destination schema must have the same tags, a timestamp column, and the
    aggregated field columns. Returns the number of rows written."""
    import jax

    from ..ops.kernels import shape_bucket, sorted_grouped_aggregate
    from .write_batch import WriteBatch

    schema = src.schema
    field_names = [c.name for c in schema.field_columns()
                   if not schema.column_schema(c.name).dtype.is_string]
    if aggs is None:
        aggs = {f: "avg" for f in field_names}
    for f, op in aggs.items():
        if op not in _SUPPORTED:
            raise ValueError(f"unsupported downsample op {op}")

    data = src.snapshot().read_merged(time_range=time_range)
    if data.num_rows == 0:
        return 0
    # keep only PUT rows (tombstones end their keys' history)
    puts = data.op_types == 0
    sids = data.series_ids[puts]
    ts = data.ts[puts]
    if not len(ts):
        return 0

    buckets = (ts // stride_ms).astype(np.int64)
    # run ids over the (series, bucket) pairs — rows arrive sorted by
    # (series, ts) so pair changes are run boundaries (device-friendly ids)
    change = np.empty(len(ts), dtype=bool)
    change[0] = True
    change[1:] = (sids[1:] != sids[:-1]) | (buckets[1:] != buckets[:-1])
    rid = np.cumsum(change) - 1
    nruns = int(rid[-1]) + 1

    base = int(ts.min())
    rel = ts - base
    if rel.max(initial=0) >= 2**31:
        raise ValueError("downsample window exceeds int32 relative span")
    d_rid = jax.device_put(rid.astype(np.int32))
    d_ts = jax.device_put(rel.astype(np.int32))
    d_mask = jax.device_put(np.ones(len(ts), dtype=bool))

    values, col_masks, ops, slots = [], [], [], []
    for fname in field_names:
        if fname not in aggs:
            continue
        op = aggs[fname]
        vals, valid = data.fields[fname]
        vals = vals[puts]
        valid_p = valid[puts] if valid is not None else \
            np.ones(len(ts), dtype=bool)
        v = vals.astype(np.float64)
        x64 = jax.config.jax_enable_x64
        d_vals = jax.device_put(v.astype(np.float64 if x64 else np.float32))
        d_valid = jax.device_put(valid_p)
        if op == "avg":
            for sub in ("sum", "count"):
                values.append(d_vals)
                col_masks.append(d_valid)
                ops.append(sub)
                slots.append((fname, sub))
        else:
            values.append(d_vals)
            col_masks.append(d_valid)
            ops.append(op)
            slots.append((fname, op))

    nbucket = shape_bucket(nruns, minimum=256)
    run_starts = np.nonzero(change)[0]
    # segment ends are free on the host (run boundaries just computed);
    # shipping them skips the on-device binary search for bounds
    run_ends = np.full(nbucket, len(ts), dtype=np.int32)
    run_ends[:nruns - 1] = run_starts[1:]
    results, counts = sorted_grouped_aggregate(
        d_rid, d_mask, d_ts, tuple(values), tuple(col_masks),
        num_groups=nbucket, ops=tuple(ops), has_col_masks=True,
        ends=run_ends)
    counts = np.asarray(counts)[:nruns]
    res = {slot: np.asarray(r)[:nruns] for slot, r in zip(slots, results)}
    out_sids = sids[run_starts]
    out_ts = buckets[run_starts] * stride_ms
    live = counts > 0
    out_sids, out_ts = out_sids[live], out_ts[live]

    cols: Dict[str, list] = {}
    sd = src.series_dict
    for i, tag in enumerate(sd.tag_names):
        cols[tag] = sd.decode_tag_column(out_sids, i)
    ts_name = dst.schema.timestamp_column.name
    cols[ts_name] = out_ts.tolist()
    for fname in field_names:
        if fname not in aggs:
            continue
        op = aggs[fname]
        if op == "avg":
            s = res[(fname, "sum")][live]
            c = res[(fname, "count")][live]
            vals = np.where(c > 0, s / np.maximum(c, 1), np.nan)
        elif op == "count":
            vals = res[(fname, "count")][live].astype(np.float64)
        else:
            vals = res[(fname, op)][live].astype(np.float64)
        cols[fname] = [None if np.isnan(v) else float(v) for v in
                       np.asarray(vals, dtype=np.float64)]

    n = len(out_ts)
    if n == 0:
        return 0
    wb = WriteBatch(dst.schema)
    wb.put(cols)
    dst.write(wb)
    logger.info("downsampled %s -> %s: %d rows into %d buckets (stride %dms)",
                src.name, dst.name, len(ts), n, stride_ms)
    return n
