"""Downsampling: aggregate a region's rows into coarser time buckets.

The north-star maintenance job (BASELINE config 5: 1s→1m downsample).
The reference has no downsample in v0.2 — its compaction only merges
files — so this is a capability extension: a background job that reduces
every (series, bucket) group with the scatter-free sorted-segment TPU
kernel and writes the result into a destination whose time index carries
the bucket timestamps. The continuous-flow subsystem (flow/manager.py)
drives the same reducer incrementally from a per-flow watermark.

TPU-first data flow: the job rides the SAME device-resident merged-scan
cache the query path uses (`query/tpu_exec.SCAN_CACHE`) — on a region
that has been queried (or downsampled) before, the sorted/deduped column
arrays are already in HBM and the job ships only the run ids; on a cold
region the cache build it pays is then amortized by every later query.
All device work is dispatched asynchronously and fetched in ONE batched
device_get, so host-side prep for the destination write overlaps the
kernel execution instead of serializing behind it.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

logger = logging.getLogger(__name__)

_SUPPORTED = ("avg", "sum", "min", "max", "count", "first", "last")

#: one output column: (destination column name, op, source field or None).
#: A None source means count-rows — the op must be "count" (count(*)).
AggSpec = Tuple[str, str, Optional[str]]


def _normalize_aggs(src_schema, aggs: Union[None, Dict[str, str],
                                            Sequence[AggSpec]]
                    ) -> List[AggSpec]:
    """Accept the legacy field→op dict (dest column = field name) or the
    flow-style (dest, op, src) triples; default to avg of every numeric
    field."""
    if aggs is None:
        fields = [c.name for c in src_schema.field_columns()
                  if not src_schema.column_schema(c.name).dtype.is_string]
        return [(f, "avg", f) for f in fields]
    if isinstance(aggs, dict):
        return [(f, op, f) for f, op in aggs.items()]
    return [tuple(a) for a in aggs]


def downsample_region(src, dst, *, stride_ms: int,
                      aggs: Union[None, Dict[str, str],
                                  Sequence[AggSpec]] = None,
                      time_range=None, origin_ms: int = 0) -> int:
    """Aggregate `src` rows into `stride_ms` buckets and write to `dst`.

    `dst` may be a Region (direct WriteBatch) or a Table — a partitioned
    table routes destination rows through its partition rule
    (partition/splitter.py), so multi-region rollup tables work.
    Re-running over an already-folded window is idempotent: bucket rows
    carry the same (tags, bucket_ts) key, so MVCC dedup keeps the newest
    fold. Returns the number of bucket rows written."""
    import jax

    from ..ops.kernels import shape_bucket, sorted_grouped_aggregate
    from ..query.tpu_exec import SCAN_CACHE
    from .write_batch import WriteBatch

    schema = src.schema
    agg_specs = _normalize_aggs(schema, aggs)
    for dest, op, col in agg_specs:
        if op not in _SUPPORTED:
            raise ValueError(f"unsupported downsample op {op}")
        if col is None and op != "count":
            raise ValueError(f"{op} needs a source column")

    # merged + MVCC-deduped view, sorted by (series, ts); PUT rows only
    # (tombstones are dropped by the merge). Device mirrors of ts/fields
    # are cached per region version and shared with the query path.
    scan = SCAN_CACHE.get(src)
    n = scan.num_rows
    if n == 0:
        return 0
    sids, ts = scan.series_ids, scan.ts

    mask_np = None
    if time_range is not None:
        mask_np = np.ones(n, dtype=bool)
        if time_range.start is not None:
            mask_np &= ts >= time_range.start
        if time_range.end is not None:
            mask_np &= ts < time_range.end
        if not mask_np.any():
            return 0

    # run ids over (series, bucket): rows are sorted by (series, ts) so
    # pair changes are run boundaries — vectorized host pass, and the
    # segment ends ship with the call (no device binary search)
    buckets = (ts - origin_ms) // stride_ms
    flags = np.empty(n, dtype=bool)
    flags[0] = True
    np.not_equal(sids[1:], sids[:-1], out=flags[1:])
    flags[1:] |= buckets[1:] != buckets[:-1]
    rid = np.cumsum(flags, dtype=np.int32) - 1
    nruns = int(rid[-1]) + 1
    run_starts = np.nonzero(flags)[0]

    nbucket = shape_bucket(nruns, minimum=256)
    d_mask = jax.device_put(mask_np) if mask_np is not None \
        else scan.device_valid_all()
    d_ts = scan.device_ts()
    # with host-precomputed ends the kernel reads gids only for first/last
    # (arg-extreme tie-break); every other op works off the segment bounds,
    # so the O(n) rid upload is skipped and ts stands in for shape
    needs_gids = any(op in ("first", "last") for _, op, _ in agg_specs)
    d_rid = jax.device_put(rid) if needs_gids else d_ts

    values, col_masks, ops, slots = [], [], [], []
    for dest, op, col in agg_specs:
        if col is None:
            values.append(d_ts)            # count(*): mask-only reduce
            col_masks.append(scan.device_valid_all())
        else:
            values.append(d_ts if op == "count"
                          else scan.device_field(col))
            col_masks.append(scan.device_valid(col))
        ops.append(op)
        slots.append(dest)

    run_ends = np.full(nbucket, n, dtype=np.int32)
    run_ends[:nruns - 1] = run_starts[1:]
    results, counts = sorted_grouped_aggregate(
        d_rid, d_mask, d_ts, tuple(values), tuple(col_masks),
        num_groups=nbucket, ops=tuple(ops), has_col_masks=True,
        ends=run_ends)

    # host prep for the destination write runs while the device computes
    # (dispatch above is async); the single batched fetch below is the
    # only synchronization point
    out_sids = sids[run_starts]
    out_ts = buckets[run_starts] * stride_ms + origin_ms
    counts, results = jax.device_get((counts, list(results)))
    counts = counts[:nruns]
    live = counts > 0
    out_sids, out_ts = out_sids[live], out_ts[live]

    cols: Dict[str, list] = {}
    sd = src.series_dict
    for i, tag in enumerate(sd.tag_names):
        cols[tag] = sd.decode_tag_column(out_sids, i)
    ts_name = dst.schema.timestamp_column.name
    cols[ts_name] = out_ts
    for dest, res in zip(slots, results):
        vals = np.asarray(res)[:nruns][live].astype(np.float64)
        nan = np.isnan(vals)
        cols[dest] = vals if not nan.any() else \
            [None if m else float(v) for v, m in zip(vals, nan)]

    n_out = len(out_ts)
    if n_out == 0:
        return 0
    if hasattr(dst, "regions"):
        # table destination: insert() splits rows per the partition rule
        dst.insert(cols)
    else:
        wb = WriteBatch(dst.schema)
        wb.put(cols)
        dst.write(wb)
    logger.info("downsampled %s -> %s: %d rows into %d buckets (stride %dms)",
                src.name, getattr(dst, "name", dst.info.name
                                  if hasattr(dst, "info") else "?"),
                n, n_out, stride_ms)
    return n_out
