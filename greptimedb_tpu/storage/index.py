"""Per-SST secondary index: bloom + inverted sid pruning for point reads.

The missing pruning tier between region-level partition pruning
(frontend scatter, PR 5) and per-row-group footer stats (PR 1): a
compact sidecar written next to every SST at flush/compaction time,
holding

- a **bloom filter over the file's ``__series_id`` set** — point and
  ``IN`` tag predicates resolve to series-id sets through the region's
  SeriesDict (the inverted tag→sid mapping that already exists), and a
  negative bloom answer drops the *whole file* before its parquet
  footer is ever opened;
- a **per-row-group sid-membership summary** — per-group ``[lo, hi]``
  sid bounds plus (when the file's distinct-sid count is modest) the
  exact sorted sid set per group, so the groups of a kept file are
  selected without a footer read either.

Both are built from arrays already in hand during encode: SSTs sort by
``(series, ts)``, so the per-group sid sets fall out of one pass.

Why a bloom when ``FileMeta.sid_range`` exists: after compaction (and
for any flush of a scattered active-series subset) the min/max range
spans nearly the whole keyspace while the file holds a small fraction
of the series — the range keeps everything, the bloom keeps ~nothing.
The win *grows* with series cardinality, unlike every row-count-shaped
optimization before it.

Degrade semantics (the PR 4 read-cache pattern): a missing or corrupt
sidecar — torn write, failpoint ``sst_index_read``, version skew —
never fails a query. The file silently falls back to stats-only
pruning (footer row-group stats), ``greptime_sst_index_degrade_total``
counts it, and the verdict is cached per access layer so a poisoned
sidecar is not re-read per query. Sidecar reads go through the
region's ObjectStore, so they ride the LRU disk read cache like any
SST page.

Knobs: ``SET sst_index = 0|1`` (env twin ``GREPTIME_SST_INDEX``)
gates both sidecar writes and every index consult; off reproduces the
pre-index read path exactly — the bench differential's kill switch.
"""

from __future__ import annotations

import json
import logging
import struct
import zlib
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..common import failpoint as _fp
from ..errors import StorageError
from ..utils import env_flag

logger = logging.getLogger(__name__)

_fp.register("sst_index_read")
_fp.register("sst_index_write")

#: sidecar magic + format version (bump on incompatible layout changes;
#: unknown versions degrade to stats-only, never error)
_MAGIC = b"GTSIDX1\n"
#: bloom sizing: ~10 bits/key => ~1% false-positive rate at k=7
_BITS_PER_KEY = 10
_NUM_HASHES = 7
#: store exact per-row-group sid sets while the file's total distinct
#: sid count stays under this (400KB of int32 at the cap); larger files
#: keep the per-group [lo, hi] bounds only
_RG_EXACT_MAX_SIDS = 131072

#: SET sst_index / GREPTIME_SST_INDEX: single-slot swap, read lock-free
#: on the hot path (the scan_fusion knob pattern)
_INDEX_ENABLED = [env_flag("GREPTIME_SST_INDEX", True)]


def sst_index_enabled() -> bool:
    return _INDEX_ENABLED[0]


def configure_sst_index(*, enabled: Optional[bool] = None) -> None:
    if enabled is not None:
        _INDEX_ENABLED[0] = bool(enabled)


def index_file_name(sst_file_name: str) -> str:
    """The sidecar key for an SST, in the same sst/ directory (so the
    orphan sweep, DROP and the read cache all see one namespace)."""
    return f"{sst_file_name}.idx"


class SstIndexCorrupt(StorageError):
    """Sidecar failed validation (magic/crc/shape) — every consumer
    catches it and degrades to stats-only pruning, never a failed
    query; typed so it carries a real status if it ever crosses a
    wire surface."""


def _mix64(h: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer, vectorized (uint64 wraparound intended)."""
    h = (h ^ (h >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    h = (h ^ (h >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return h ^ (h >> np.uint64(31))


def _bloom_hashes(sids: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    u = sids.astype(np.uint64)
    h1 = _mix64(u)
    h2 = _mix64(u ^ np.uint64(0x9E3779B97F4A7C15)) | np.uint64(1)
    return h1, h2


class SstIndex:
    """Decoded sidecar: file-level bloom + per-row-group sid summaries.

    Immutable after build/parse; safe to share across reader threads.
    """

    __slots__ = ("num_rows", "nbits", "nhashes", "words",
                 "rg_lo", "rg_hi", "rg_sids")

    def __init__(self, num_rows: int, nbits: int, nhashes: int,
                 words: np.ndarray, rg_lo: np.ndarray, rg_hi: np.ndarray,
                 rg_sids: Optional[List[np.ndarray]]):
        self.num_rows = num_rows
        self.nbits = nbits                  # power of two
        self.nhashes = nhashes
        self.words = words                  # uint64 [nbits // 64]
        self.rg_lo = rg_lo                  # int64 [ngroups]
        self.rg_hi = rg_hi                  # int64 [ngroups], inclusive
        self.rg_sids = rg_sids              # sorted int32 per group, or None

    # ---- build ----
    @staticmethod
    def build(series_ids: np.ndarray, row_group_size: int) -> "SstIndex":
        """From the (sid, ts)-sorted sid column of one SST, pre-encode —
        the per-group slices are contiguous, so this is one pass."""
        n = len(series_ids)
        sids = np.asarray(series_ids, dtype=np.int64)
        uniq = np.unique(sids)
        nkeys = max(len(uniq), 1)
        nbits = 64
        while nbits < nkeys * _BITS_PER_KEY:
            nbits <<= 1
        words = np.zeros(nbits // 64, dtype=np.uint64)
        h1, h2 = _bloom_hashes(uniq)
        mask = np.uint64(nbits - 1)
        for i in range(_NUM_HASHES):
            pos = (h1 + np.uint64(i) * h2) & mask
            np.bitwise_or.at(words, (pos >> np.uint64(6)).astype(np.int64),
                             np.uint64(1) << (pos & np.uint64(63)))
        ngroups = max(1, -(-n // row_group_size)) if n else 0
        rg_lo = np.empty(ngroups, dtype=np.int64)
        rg_hi = np.empty(ngroups, dtype=np.int64)
        rg_sids: Optional[List[np.ndarray]] = \
            [] if len(uniq) <= _RG_EXACT_MAX_SIDS else None
        for g in range(ngroups):
            a, b = g * row_group_size, min((g + 1) * row_group_size, n)
            chunk = sids[a:b]
            rg_lo[g] = chunk[0]
            rg_hi[g] = chunk[-1]
            if rg_sids is not None:
                rg_sids.append(np.unique(chunk).astype(np.int32))
        return SstIndex(n, nbits, _NUM_HASHES, words, rg_lo, rg_hi,
                        rg_sids)

    # ---- queries ----
    def may_contain(self, sids: np.ndarray) -> np.ndarray:
        """Per-sid bloom membership (True = maybe present)."""
        if not len(sids):
            return np.zeros(0, dtype=bool)
        h1, h2 = _bloom_hashes(np.asarray(sids, dtype=np.int64))
        mask = np.uint64(self.nbits - 1)
        out = np.ones(len(sids), dtype=bool)
        one = np.uint64(1)
        for i in range(self.nhashes):
            pos = (h1 + np.uint64(i) * h2) & mask
            bit = self.words[(pos >> np.uint64(6)).astype(np.int64)] \
                & (one << (pos & np.uint64(63)))
            out &= bit != 0
        return out

    def may_contain_any(self, sids: np.ndarray) -> bool:
        return bool(self.may_contain(sids).any())

    def row_groups_for(self, sids: np.ndarray) -> np.ndarray:
        """Boolean keep-mask over the file's row groups for a sorted
        candidate sid set — [lo, hi] bound intersect, tightened to exact
        membership when the per-group sid sets were stored."""
        ngroups = len(self.rg_lo)
        if not len(sids):
            return np.zeros(ngroups, dtype=bool)
        s = np.asarray(sids, dtype=np.int64)
        keep = np.empty(ngroups, dtype=bool)
        for g in range(ngroups):
            i = int(np.searchsorted(s, self.rg_lo[g], side="left"))
            keep[g] = i < len(s) and s[i] <= self.rg_hi[g]
            if keep[g] and self.rg_sids is not None:
                keep[g] = bool(np.isin(
                    s[i:int(np.searchsorted(s, self.rg_hi[g],
                                            side="right"))],
                    self.rg_sids[g], assume_unique=True).any())
        return keep

    # ---- codec ----
    def to_bytes(self) -> bytes:
        rg_counts = [len(a) for a in self.rg_sids] \
            if self.rg_sids is not None else None
        payload = self.words.tobytes() + self.rg_lo.tobytes() + \
            self.rg_hi.tobytes()
        if self.rg_sids is not None:
            for a in self.rg_sids:
                payload += a.tobytes()
        header = json.dumps({
            "version": 1, "num_rows": int(self.num_rows),
            "nbits": int(self.nbits), "nhashes": int(self.nhashes),
            "ngroups": int(len(self.rg_lo)), "rg_counts": rg_counts,
            "crc": zlib.crc32(payload) & 0xFFFFFFFF,
        }).encode()
        return _MAGIC + struct.pack("<I", len(header)) + header + payload

    @staticmethod
    def from_bytes(data: bytes) -> "SstIndex":
        if len(data) < len(_MAGIC) + 4 or not data.startswith(_MAGIC):
            raise SstIndexCorrupt("bad sidecar magic")
        off = len(_MAGIC)
        (hlen,) = struct.unpack_from("<I", data, off)
        off += 4
        if off + hlen > len(data):
            raise SstIndexCorrupt("truncated sidecar header")
        try:
            hdr = json.loads(data[off:off + hlen])
        except ValueError as e:
            raise SstIndexCorrupt(f"unparseable sidecar header: {e}")
        if hdr.get("version") != 1:
            raise SstIndexCorrupt(
                f"unknown sidecar version {hdr.get('version')!r}")
        off += hlen
        payload = data[off:]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != hdr.get("crc"):
            raise SstIndexCorrupt("sidecar payload crc mismatch")
        nbits = int(hdr["nbits"])
        ngroups = int(hdr["ngroups"])
        rg_counts = hdr.get("rg_counts")
        want = nbits // 64 * 8 + ngroups * 16 + \
            (sum(rg_counts) * 4 if rg_counts is not None else 0)
        if nbits < 64 or nbits & (nbits - 1) or len(payload) != want or \
                (rg_counts is not None and len(rg_counts) != ngroups):
            raise SstIndexCorrupt("sidecar shape mismatch")
        pos = 0
        words = np.frombuffer(payload, dtype=np.uint64,
                              count=nbits // 64, offset=pos)
        pos += nbits // 64 * 8
        rg_lo = np.frombuffer(payload, dtype=np.int64, count=ngroups,
                              offset=pos)
        pos += ngroups * 8
        rg_hi = np.frombuffer(payload, dtype=np.int64, count=ngroups,
                              offset=pos)
        pos += ngroups * 8
        rg_sids: Optional[List[np.ndarray]] = None
        if rg_counts is not None:
            rg_sids = []
            for c in rg_counts:
                rg_sids.append(np.frombuffer(payload, dtype=np.int32,
                                             count=int(c), offset=pos))
                pos += int(c) * 4
        return SstIndex(int(hdr["num_rows"]), nbits,
                        int(hdr["nhashes"]), words, rg_lo, rg_hi,
                        rg_sids)


def load_sst_index(read: Callable[[str], bytes], key: str,
                   expect_rows: int) -> Optional[SstIndex]:
    """Read + validate one sidecar; None (degrade to stats-only) on any
    failure. `read` is the region store's read (rides the LRU disk
    cache); `expect_rows` cross-checks the sidecar against the FileMeta
    it claims to describe."""
    from ..common.telemetry import increment_counter
    try:
        _fp.fail_point("sst_index_read")
        idx = SstIndex.from_bytes(read(key))
        if idx.num_rows != expect_rows:
            raise SstIndexCorrupt(
                f"sidecar covers {idx.num_rows} rows, SST has "
                f"{expect_rows}")
        return idx
    except Exception as e:  # noqa: BLE001 — degrade, don't fail the read
        increment_counter("sst_index_degrade")
        logger.warning("SST index sidecar %s unusable (%s); degrading "
                       "to stats-only pruning", key, e)
        return None


def _any_in_range(sids: np.ndarray, lo: int, hi: int) -> bool:
    """Whether the sorted sid set intersects [lo, hi] (inclusive)."""
    i = int(np.searchsorted(sids, lo, side="left"))
    return i < len(sids) and int(sids[i]) <= hi


def prune_files(load_index: Callable[[object], Optional[SstIndex]],
                files: Sequence, sids: np.ndarray
                ) -> Tuple[list, int, int]:
    """Index tier of the scan planner: drop whole SSTs that cannot hold
    any candidate series, without touching a parquet footer.

    Per file: the FileMeta's coarse sid_range first (free), then the
    sidecar bloom; files with no usable index are kept (stats-only
    degrade). Returns (kept, pruned, checked) and records the counts on
    the EXPLAIN ANALYZE prune stage — `files pruned by index a/b` reads
    as index_files_pruned=a / index_files_checked=b.
    """
    from ..common import exec_stats
    from ..common.telemetry import increment_counter
    s = np.asarray(sids, dtype=np.int64)
    kept: list = []
    pruned = hits = 0
    for f in files:
        r = f.sid_range
        if r is not None and not _any_in_range(s, int(r[0]), int(r[1])):
            pruned += 1
            continue
        idx = load_index(f)
        if idx is None:
            kept.append(f)
            continue
        if idx.may_contain_any(s):
            hits += 1
            kept.append(f)
        else:
            pruned += 1
    if pruned:
        increment_counter("sst_index_prune", pruned)
    if hits:
        increment_counter("sst_index_hit", hits)
    exec_stats.record("prune", index_files_pruned=pruned,
                      index_files_checked=len(files))
    return kept, pruned, len(files)
