"""LRU disk read-cache layer over any ObjectStore.

Reference behavior: src/object-store/src/cache_policy.rs:38-100 —
`LruCacheLayer` caches whole-object reads on local disk with LRU
eviction by total bytes and recovers its index by scanning the cache dir
on start. Reads hit the cache first; writes/deletes invalidate. The extra
capability here: `local_path` serves the cached file so Parquet readers
mmap remote SSTs — the NVMe cache feeds the TPU host scan path directly.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import List, Optional

from ..common import failpoint as _fp
from ..common.locks import TrackedLock
from .object_store import ObjectStore

_fp.register("cache_read")


class LruCacheLayer(ObjectStore):
    def __init__(self, inner: ObjectStore, cache_dir: str,
                 capacity_bytes: int = 512 * 1024 * 1024):
        self.inner = inner
        self.cache_dir = os.path.abspath(cache_dir)
        self.capacity_bytes = capacity_bytes
        os.makedirs(self.cache_dir, exist_ok=True)
        # NOT io_ok=False: _admit/_evict write and unlink blob files
        # while holding this lock (admission is serialized by design)
        self._lock = TrackedLock("storage.cache")
        from ..common.tracking import tracked_state
        self._entries: "OrderedDict[str, int]" = tracked_state(
            OrderedDict(), "storage.cache.entries")       # key→bytes
        self._size = 0
        self.hits = 0
        self.misses = 0
        self._recover()

    # ---- cache index ----
    def _cache_path(self, key: str) -> str:
        digest = hashlib.sha256(key.encode()).hexdigest()[:32]
        return os.path.join(self.cache_dir, digest)

    def _recover(self) -> None:
        """Rebuild the index from cache files surviving a restart
        (reference: recover_cache on layer init, cache_policy.rs:60)."""
        for fn in sorted(os.listdir(self.cache_dir)):
            path = os.path.join(self.cache_dir, fn)
            if not os.path.isfile(path) or not fn.endswith(".key"):
                continue
            with open(path) as f:
                key = f.read()
            blob = path[:-4]
            if os.path.isfile(blob):
                size = os.path.getsize(blob)
                self._entries[key] = size
                self._size += size

    def _touch(self, key: str) -> None:
        self._entries.move_to_end(key)

    def _admit(self, key: str, data: bytes) -> str:
        path = self._cache_path(key)
        with self._lock:
            if key not in self._entries:
                from ..utils import atomic_write
                # no fsync: a torn cache blob after a crash is re-fetched
                # from the backing store, but a HALF-torn one must never
                # be readable, hence the atomic publish
                atomic_write(path, data, fsync=False)
                with open(path + ".key", "w") as f:
                    f.write(key)
                self._entries[key] = len(data)
                self._size += len(data)
                self._evict()
            else:
                self._touch(key)
        return path

    def _evict(self) -> None:
        while self._size > self.capacity_bytes and len(self._entries) > 1:
            old_key, size = self._entries.popitem(last=False)
            self._size -= size
            p = self._cache_path(old_key)
            for suffix in ("", ".key"):
                try:
                    os.unlink(p + suffix)
                except OSError:
                    pass

    def _invalidate(self, key: str) -> None:
        with self._lock:
            size = self._entries.pop(key, None)
            if size is not None:
                self._size -= size
                p = self._cache_path(key)
                for suffix in ("", ".key"):
                    try:
                        os.unlink(p + suffix)
                    except OSError:
                        pass

    def hit_ratio(self) -> float:
        """Fraction of reads served from the local cache (0.0 when the
        layer has seen no traffic) — surfaced by /status and the
        information_schema.runtime_metrics gauges."""
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    # ---- ObjectStore surface ----
    def read(self, key: str) -> bytes:
        from ..common.telemetry import increment_counter
        with self._lock:
            if key in self._entries:
                self._touch(key)
                path = self._cache_path(key)
                expect_size = self._entries[key]
            else:
                path = None
        if path is not None:
            try:
                _fp.fail_point("cache_read")
                with open(path, "rb") as f:
                    data = f.read()
                if len(data) != expect_size:
                    # truncated/overwritten cache blob: disk corruption,
                    # not a miss — fall through to a cold read
                    raise OSError(
                        f"cache blob for {key} is {len(data)}B, "
                        f"expected {expect_size}B")
                # count the hit only once the blob actually served: a
                # corrupt entry must not inflate the hit ratio AND the
                # miss counter for one read
                self.hits += 1
                increment_counter("read_cache_hit")
                return data
            except FileNotFoundError:
                self._invalidate(key)
            except Exception as e:  # noqa: BLE001 — degrade, don't fail
                # a corrupt cache entry must never surface to the reader:
                # drop it and serve the authoritative backend copy cold
                import logging
                logging.getLogger(__name__).warning(
                    "read cache entry for %s unusable (%s); falling back "
                    "to cold read", key, e)
                increment_counter("read_cache_corrupt")
                self._invalidate(key)
        self.misses += 1
        increment_counter("read_cache_miss")
        data = self.inner.read(key)
        self._admit(key, data)
        return data

    def write(self, key: str, data: bytes) -> None:
        self.inner.write(key, data)
        self._invalidate(key)

    def delete(self, key: str) -> None:
        self.inner.delete(key)
        self._invalidate(key)

    def delete_dir(self, key: str) -> None:
        prefix = key if key.endswith("/") else key + "/"
        with self._lock:
            stale = [k for k in self._entries if k.startswith(prefix)]
        for k in stale:
            self._invalidate(k)
        if hasattr(self.inner, "delete_dir"):
            self.inner.delete_dir(key)
        else:
            for k in self.inner.list(prefix):
                self.inner.delete(k)

    def exists(self, key: str) -> bool:
        with self._lock:
            if key in self._entries:
                return True
        return self.inner.exists(key)

    def list(self, prefix: str) -> List[str]:
        return self.inner.list(prefix)

    def local_path(self, key: str) -> Optional[str]:
        """Cached objects are local files — Parquet readers mmap them."""
        inner_path = self.inner.local_path(key)
        if inner_path is not None:
            return inner_path
        try:
            self.read(key)                # pull through the cache
        except FileNotFoundError:
            return None
        return self._cache_path(key)
