"""Storage engine: creates/opens regions and shares their infrastructure.

Reference behavior: src/storage/src/engine.rs — `EngineImpl` keeps a region
map, wires the shared object store / WAL / flush machinery into each region,
and is the unit a table engine builds on.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..datatypes import Schema
from ..errors import RegionNotFoundError
from .object_store import FsObjectStore, ObjectStore
from .region import Region, RegionDescriptor
from .wal import NoopWal


@dataclass
class EngineConfig:
    data_home: str
    flush_size_bytes: int = 64 * 1024 * 1024
    wal_sync_on_write: bool = False
    disable_wal: bool = False           # benchmarks / ephemeral regions
    checkpoint_margin: int = 10
    row_group_size: int = 65536


class StorageEngine:
    def __init__(self, config: EngineConfig,
                 store: Optional[ObjectStore] = None):
        self.config = config
        self.store = store or FsObjectStore(os.path.join(config.data_home, "data"))
        self.wal_home = os.path.join(config.data_home, "wal")
        self._regions: Dict[str, Region] = {}
        self._lock = threading.Lock()

    def _descriptor(self, name: str, schema: Schema) -> RegionDescriptor:
        return RegionDescriptor(
            name=name, schema=schema,
            region_dir=name,
            wal_dir=os.path.join(self.wal_home, name))

    def _region_kwargs(self) -> dict:
        kwargs = dict(
            flush_size_bytes=self.config.flush_size_bytes,
            checkpoint_margin=self.config.checkpoint_margin,
            row_group_size=self.config.row_group_size)
        if self.config.disable_wal:
            kwargs["wal"] = NoopWal()
        return kwargs

    def create_region(self, name: str, schema: Schema) -> Region:
        with self._lock:
            if name in self._regions:
                return self._regions[name]
            region = Region.create(self._descriptor(name, schema), self.store,
                                   **self._region_kwargs())
            self._regions[name] = region
            return region

    def open_region(self, name: str, schema: Optional[Schema] = None
                    ) -> Optional[Region]:
        """Open an existing region (schema recovered from its manifest)."""
        with self._lock:
            if name in self._regions:
                return self._regions[name]
            desc = self._descriptor(name, schema)
            region = Region.open(desc, self.store, **self._region_kwargs())
            if region is not None:
                self._regions[name] = region
            return region

    def get_region(self, name: str) -> Region:
        with self._lock:
            region = self._regions.get(name)
        if region is None:
            raise RegionNotFoundError(f"region not found: {name}")
        return region

    def has_region(self, name: str) -> bool:
        with self._lock:
            return name in self._regions

    def drop_region(self, name: str) -> None:
        with self._lock:
            region = self._regions.pop(name, None)
        if region is not None:
            region.drop()

    def list_regions(self) -> Dict[str, Region]:
        with self._lock:
            return dict(self._regions)

    def close(self) -> None:
        with self._lock:
            for region in self._regions.values():
                region.close()
            self._regions.clear()
