"""Storage engine: creates/opens regions and shares their infrastructure.

Reference behavior: src/storage/src/engine.rs — `EngineImpl` keeps a region
map, wires the shared object store / WAL / flush machinery into each region,
and is the unit a table engine builds on.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..common.locks import TrackedLock
from ..common.tracking import tracked_state
from ..datatypes import Schema
from ..errors import RegionNotFoundError
from .object_store import FsObjectStore, ObjectStore
from .region import Region, RegionDescriptor
from .wal import NoopWal


@dataclass
class EngineConfig:
    data_home: str
    #: WAL root; defaults to <data_home>/wal. Distributed datanodes
    #: sharing one data_home (shared object store) MUST scope this per
    #: node: the WAL and the region fence marker are node-local state
    wal_home: Optional[str] = None
    flush_size_bytes: int = 64 * 1024 * 1024
    wal_sync_on_write: bool = False
    wal_backend: str = "auto"           # auto | native | python
    disable_wal: bool = False           # benchmarks / ephemeral regions
    checkpoint_margin: int = 10
    #: rows per parquet row group — 1Mi matches sst.DEFAULT_ROW_GROUP_SIZE:
    #: large groups encode ~2x and decode ~15% faster than the old 64Ki
    #: (fewer page/stat boundaries), and the streamed cold scan plans
    #: slices from row-group stats at multi-million-row granularity anyway
    row_group_size: int = 1 << 20
    # background machinery (reference: scheduler.rs + file_purger.rs)
    bg_workers: int = 4
    purge_grace_s: float = 60.0
    purge_interval_s: float = 30.0
    ttl_check_interval_s: float = 300.0
    max_l0_files: int = 4               # L0 count that triggers compaction
    ttl_ms: Optional[int] = None        # engine-wide default TTL
    compaction_time_window_ms: Optional[int] = None


class StorageEngine:
    def __init__(self, config: EngineConfig,
                 store: Optional[ObjectStore] = None):
        from .file_purger import FilePurger
        from .retry import RetryingObjectStore
        from .scheduler import LocalScheduler, RepeatedTask
        self.config = config
        if store is None:
            # default Fs store rides behind the retry layer too: local
            # disks rarely fault transiently, but injected faults (and
            # network filesystems) do — and the wrapper is one branch per
            # object op, invisible next to the IO it guards
            store = RetryingObjectStore(
                FsObjectStore(os.path.join(config.data_home, "data")))
        self.store = store
        self.wal_home = config.wal_home or \
            os.path.join(config.data_home, "wal")
        self._regions: Dict[str, Region] = tracked_state(
            {}, "storage.engine.regions")
        self._lock = TrackedLock("storage.engine")
        self.scheduler = LocalScheduler(max_inflight=config.bg_workers,
                                        name="storage-bg")
        self.purger = FilePurger(grace_s=config.purge_grace_s)
        self._purge_task = RepeatedTask(config.purge_interval_s,
                                        self.purger.sweep, name="file-purge")
        self._purge_task.start()
        # TTL is otherwise only enforced when write volume trips a
        # compaction — quiet regions must still expire (whole-file drops
        # here; row-level expiry rides the next compaction)
        self._ttl_task = RepeatedTask(config.ttl_check_interval_s,
                                      self._ttl_sweep, name="ttl-sweep")
        self._ttl_task.start()

    def _ttl_sweep(self) -> None:
        for region in self.list_regions().values():
            # fenced regions are mid-handoff: their shared dir belongs to
            # the adopting node, so no manifest edits from this process
            if region.ttl_ms is not None and not region.closed \
                    and not region.fenced:
                region.apply_ttl()
                if region.version_control.current.ssts.levels[0]:
                    region.schedule_compaction()

    def _descriptor(self, name: str, schema: Schema) -> RegionDescriptor:
        return RegionDescriptor(
            name=name, schema=schema,
            region_dir=name,
            wal_dir=os.path.join(self.wal_home, name))

    def _region_kwargs(self, opts: Optional[dict] = None) -> dict:
        kwargs = dict(
            flush_size_bytes=self.config.flush_size_bytes,
            checkpoint_margin=self.config.checkpoint_margin,
            row_group_size=self.config.row_group_size,
            scheduler=self.scheduler,
            purger=self.purger,
            ttl_ms=self.config.ttl_ms,
            max_l0_files=self.config.max_l0_files,
            compaction_time_window_ms=self.config.compaction_time_window_ms,
            wal_opts={"sync_on_write": self.config.wal_sync_on_write,
                      "backend": self.config.wal_backend})
        if self.config.disable_wal:
            kwargs["wal"] = NoopWal()
        if opts:
            kwargs.update(opts)
        return kwargs

    def create_region(self, name: str, schema: Schema,
                      opts: Optional[dict] = None) -> Region:
        with self._lock:
            if name in self._regions:
                return self._regions[name]
            region = Region.create(self._descriptor(name, schema), self.store,
                                   **self._region_kwargs(opts))
            self._regions[name] = region
            return region

    def open_region(self, name: str, schema: Optional[Schema] = None,
                    opts: Optional[dict] = None) -> Optional[Region]:
        """Open an existing region (schema recovered from its manifest)."""
        with self._lock:
            if name in self._regions:
                return self._regions[name]
            desc = self._descriptor(name, schema)
            region = Region.open(desc, self.store,
                                 **self._region_kwargs(opts))
            if region is not None:
                self._regions[name] = region
            return region

    def get_region(self, name: str) -> Region:
        with self._lock:
            region = self._regions.get(name)
        if region is None:
            raise RegionNotFoundError(f"region not found: {name}")
        return region

    def has_region(self, name: str) -> bool:
        with self._lock:
            return name in self._regions

    def drop_region(self, name: str) -> None:
        with self._lock:
            region = self._regions.pop(name, None)
        if region is not None:
            region.drop()

    def release_region(self, name: str) -> bool:
        """Drop the in-process region WITHOUT touching its shared data —
        the migrated region's new owner serves it now. Returns whether
        this engine actually hosted it."""
        with self._lock:
            region = self._regions.pop(name, None)
        if region is None:
            return False
        region.release()
        return True

    def reopen_region(self, name: str, schema: Optional[Schema] = None,
                      opts: Optional[dict] = None) -> Optional[Region]:
        """Close and reopen a region from its CURRENT shared manifest —
        the standby-replica refresh path: the leader's flushes advanced
        the manifest under this replica, so a plain reopen folds them in
        (local WAL replay rides on top of the new flushed sequence)."""
        with self._lock:
            region = self._regions.pop(name, None)
        if region is not None:
            region.close()
        return self.open_region(name, schema, opts=opts)

    def list_regions(self) -> Dict[str, Region]:
        with self._lock:
            return dict(self._regions)

    def close(self) -> None:
        self._ttl_task.stop()
        self._purge_task.stop()
        self.scheduler.stop(drain=True)
        # files pending purge would leak forever otherwise: nothing
        # re-discovers SSTs absent from the manifest after a restart, and
        # no reader can outlive the engine
        self.purger.sweep(force=True)
        with self._lock:
            for region in self._regions.values():
                region.close()
            self._regions.clear()
