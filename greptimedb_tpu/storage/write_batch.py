"""WriteBatch: typed mutations with an Arrow IPC wire codec.

Reference behavior: src/storage/src/write_batch.rs — a batch of Put/Delete
mutations validated against the region schema, encoded as arrow-ipc for the
WAL payload. Deletes carry only the row key (tags + timestamp).
"""

from __future__ import annotations

import io
import json

import numpy as np
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import pyarrow as pa
import pyarrow.ipc as pa_ipc

from ..datatypes import RecordBatch, Schema
from ..errors import InvalidArgumentsError

OP_PUT = 0
OP_DELETE = 1


@dataclass
class Mutation:
    op_type: int               # OP_PUT | OP_DELETE
    data: RecordBatch          # puts: full row schema; deletes: key columns only


class WriteBatch:
    def __init__(self, schema: Schema):
        self.schema = schema
        self.mutations: List[Mutation] = []

    @property
    def num_rows(self) -> int:
        return sum(m.data.num_rows for m in self.mutations)

    def put(self, data: Dict[str, Sequence] | RecordBatch) -> None:
        rb = self._coerce_put(data)
        self.mutations.append(Mutation(OP_PUT, rb))

    def delete(self, keys: Dict[str, Sequence] | RecordBatch) -> None:
        rb = self._coerce_delete(keys)
        self.mutations.append(Mutation(OP_DELETE, rb))

    # ---- validation/coercion ----
    def _coerce_put(self, data) -> RecordBatch:
        if isinstance(data, RecordBatch):
            rb = data
            if rb.schema.names() != self.schema.names():
                raise InvalidArgumentsError(
                    f"put batch columns {rb.schema.names()} != region schema "
                    f"{self.schema.names()}")
            for a, b in zip(rb.schema.column_schemas, self.schema.column_schemas):
                if a.dtype != b.dtype:
                    raise InvalidArgumentsError(
                        f"column {a.name}: type {a.dtype} != {b.dtype}")
        else:
            n = None
            cols = {}
            for c in self.schema.column_schemas:
                if c.name in data:
                    vals = data[c.name]
                    if not isinstance(vals, (list, np.ndarray)):
                        vals = list(vals)
                    if n is None:
                        n = len(vals)
                    elif len(vals) != n:
                        raise InvalidArgumentsError(
                            f"ragged column {c.name}: {len(vals)} vs {n}")
                    cols[c.name] = vals
            if n is None:
                raise InvalidArgumentsError("empty put")
            for c in self.schema.column_schemas:
                if c.name not in cols:
                    v = c.create_default_vector(n)
                    if v is None:
                        raise InvalidArgumentsError(
                            f"missing non-null column without default: {c.name}")
                    cols[c.name] = v.to_pylist()
            rb = RecordBatch.from_pydict(self.schema, cols)
        for c, vec in zip(rb.schema.column_schemas, rb.columns):
            if not c.nullable and vec.null_count:
                raise InvalidArgumentsError(f"null in non-nullable column {c.name}")
        return rb

    def _key_schema(self) -> Schema:
        names = self.schema.tag_names() + [self.schema.timestamp_column.name]
        return self.schema.project(names)

    def _coerce_delete(self, keys) -> RecordBatch:
        ks = self._key_schema()
        if isinstance(keys, RecordBatch):
            if keys.schema.names() != ks.names():
                raise InvalidArgumentsError(
                    f"delete batch columns {keys.schema.names()} != key "
                    f"columns {ks.names()}")
            return keys
        missing = [c.name for c in ks.column_schemas if c.name not in keys]
        if missing:
            raise InvalidArgumentsError(f"delete missing key columns: {missing}")
        return RecordBatch.from_pydict(ks, {c.name: list(keys[c.name])
                                            for c in ks.column_schemas})

    # ---- codec (WAL payload) ----
    def encode(self) -> bytes:
        """[json header][arrow IPC stream with one batch per mutation]"""
        header = {
            "schema_version": self.schema.version,
            "ops": [m.op_type for m in self.mutations],
        }
        hdr = json.dumps(header).encode()
        buf = io.BytesIO()
        buf.write(len(hdr).to_bytes(4, "little"))
        buf.write(hdr)
        # one IPC stream per mutation group (schemas differ between put/delete)
        for m in self.mutations:
            sink = io.BytesIO()
            table = m.data.to_arrow()
            with pa_ipc.new_stream(sink, table.schema) as w:
                w.write_batch(table)
            payload = sink.getvalue()
            buf.write(len(payload).to_bytes(4, "little"))
            buf.write(payload)
        return buf.getvalue()

    @staticmethod
    def decode(data: bytes, schema: Schema) -> "WriteBatch":
        view = memoryview(data)
        hlen = int.from_bytes(view[:4], "little")
        header = json.loads(bytes(view[4:4 + hlen]))
        pos = 4 + hlen
        wb = WriteBatch(schema)
        for op in header["ops"]:
            plen = int.from_bytes(view[pos:pos + 4], "little")
            pos += 4
            payload = view[pos:pos + plen]
            pos += plen
            with pa_ipc.open_stream(pa.BufferReader(payload)) as r:
                table = r.read_all()
            batches = table.to_batches()
            rb_schema = Schema.from_arrow(table.schema)
            if batches:
                rb = RecordBatch.from_arrow(batches[0], rb_schema)
                if len(batches) > 1:
                    rb = RecordBatch.concat(
                        [rb] + [RecordBatch.from_arrow(b, rb_schema) for b in batches[1:]])
            else:
                rb = RecordBatch.empty(rb_schema)
            wb.mutations.append(Mutation(op, rb))
        wb._decoded_schema_version = header.get("schema_version", 0)
        return wb
