"""Background job scheduler with request dedup and inflight limits.

Reference behavior: src/storage/src/scheduler.rs — `LocalScheduler` drains a
`DedupDeque` (re-submitting a queued key is a no-op) through a
`MaxInflightTaskLimiter`; jobs run on a small worker pool shared by flush
and compaction. Here the pool is a plain thread pool: these jobs are
host-side IO (Parquet encode, manifest writes) and kernel launches, so
Python threads overlap fine.
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict

from ..common.locks import TrackedLock
from typing import Callable, Dict, Optional

logger = logging.getLogger(__name__)


class JobHandle:
    """Completion handle for a scheduled job."""

    def __init__(self):
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        self._result = None

    def wait(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("job did not complete in time")
        if self._error is not None:
            raise self._error
        return self._result

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def _finish(self, result=None, error: Optional[BaseException] = None):
        self._result = result
        self._error = error
        self._done.set()


class LocalScheduler:
    """Deduplicating background scheduler.

    - `submit(key, fn)`: runs fn on a worker thread. While a job with the
      same key is *queued*, further submits coalesce into it (both callers
      get the same handle). A job whose key is currently *running* queues
      one follow-up run (the reference's DedupDeque semantics).
    - at most `max_inflight` jobs run concurrently; the queue is unbounded.
    """

    def __init__(self, max_inflight: int = 4, name: str = "bg"):
        self.max_inflight = max(1, max_inflight)
        self.name = name
        self._lock = TrackedLock("storage.scheduler", io_ok=False)
        from ..common.tracking import tracked_state
        self._queue: "OrderedDict[str, tuple]" = tracked_state(
            OrderedDict(), "storage.scheduler.queue")
        self._running: Dict[str, bool] = tracked_state(
            {}, "storage.scheduler.running")
        self._workers: list = []
        self._wake = threading.Condition(self._lock)
        self._stopped = False
        for i in range(self.max_inflight):
            t = threading.Thread(target=self._worker_loop,
                                 name=f"{name}-worker-{i}", daemon=True)
            t.start()
            self._workers.append(t)

    def submit(self, key: str, fn: Callable[[], object]) -> JobHandle:
        with self._lock:
            if self._stopped:
                from ..errors import SchedulerStoppedError
                raise SchedulerStoppedError(
                    f"scheduler {self.name} stopped")
            if key in self._queue:
                return self._queue[key][1]        # coalesce
            handle = JobHandle()
            self._queue[key] = (fn, handle)
            self._wake.notify()
            return handle

    def submit_later(self, key: str, fn: Callable[[], object],
                     delay_s: float) -> None:
        """Queue `fn` under `key` after a delay — the retry-with-backoff
        hook for failed background jobs. Fire-and-forget: if the
        scheduler stops before the timer fires, the submit is dropped
        (shutdown must not resurrect work)."""
        def fire():
            try:
                self.submit(key, fn)
            except RuntimeError:
                pass                      # scheduler stopped meanwhile
        t = threading.Timer(delay_s, fire)
        t.daemon = True
        t.start()

    def _worker_loop(self):
        while True:
            with self._lock:
                while True:
                    key = next((k for k in self._queue
                                if k not in self._running), None)
                    if key is not None:
                        break
                    if self._stopped:
                        return            # drained (or cancelled) queue
                    self._wake.wait()
                fn, handle = self._queue.pop(key)
                self._running[key] = True
            try:
                result = fn()
                handle._finish(result)
            # a SimulatedCrash lands in handle.wait(), which re-raises it
            # in the waiter — delivery, not survival (and the bg retry
            # path counts it via _finish)
            except BaseException as e:  # greptlint: disable=GL02
                logger.exception("%s job %s failed", self.name, key)
                handle._finish(error=e)
            finally:
                with self._lock:
                    self._running.pop(key, None)
                    self._wake.notify_all()

    def stop(self, drain: bool = True) -> None:
        with self._lock:
            self._stopped = True
            if not drain:
                from ..errors import SchedulerStoppedError
                for _, handle in self._queue.values():
                    handle._finish(
                        error=SchedulerStoppedError("scheduler stopped"))
                self._queue.clear()
            self._wake.notify_all()
        for t in self._workers:
            t.join(timeout=30)

    def wait_idle(self, timeout: Optional[float] = None) -> None:
        """Block until the queue is empty and nothing is running (tests)."""
        import time
        deadline = None if timeout is None else time.time() + timeout
        with self._lock:
            while self._queue or self._running:
                rem = None if deadline is None else deadline - time.time()
                if rem is not None and rem <= 0:
                    raise TimeoutError("scheduler not idle")
                self._wake.wait(rem if rem is None or rem > 0 else 0.01)


class RepeatedTask:
    """Fixed-interval background task (reference:
    src/common/runtime/src/repeated_task.rs)."""

    def __init__(self, interval_s: float, fn: Callable[[], None],
                 name: str = "repeated"):
        self.interval_s = interval_s
        self.fn = fn
        self.name = name
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, name=self.name,
                                        daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.fn()
            except Exception:  # noqa: BLE001
                logger.exception("repeated task %s failed", self.name)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
