"""Region: one shard of a table — the durable LSM unit.

Reference behavior: src/storage/src/region.rs + region/writer.rs — a region
owns a WAL namespace, memtables, SST levels and a manifest. Writes are
serialized (WAL append → memtable insert → sequence bump); flush freezes the
mutable memtable and dumps it to Parquet; recovery replays WAL from
`flushed_sequence + 1` after restoring the manifest.

TPU-first deltas from the reference:
- memtables are unordered SoA buffers; ordering/dedup is a device sort kernel
  at scan/flush time (see storage/memtable.py docstring);
- the series dictionary (string tags → dense ids) is part of durable state,
  persisted on flush next to the manifest so SST series ids stay stable;
- scans return SoA runs ready for device transfer, not row iterators.
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import failpoint as _fp
from ..common.locks import TrackedRLock
from ..common.time import TimestampRange
from ..datatypes import RecordBatch, Schema, Vector
from ..datatypes.vector import compat_column, null_column
from ..errors import (InvalidArgumentsError, RegionClosedError,
                      StorageError)
from .memtable import Memtable, MemtableSnapshot, MemtableVersion
from .manifest import RegionManifest
from .object_store import ObjectStore
from .series import SeriesDict
from .sst import (AccessLayer, DEFAULT_ROW_GROUP_SIZE, FileMeta, LevelMetas,
                  SERIES_COL)
from .version import Version, VersionControl
from .wal import NoopWal, Wal
from .write_batch import OP_DELETE, OP_PUT, WriteBatch
from ..ops.kernels import merge_dedup_numpy

logger = logging.getLogger(__name__)

_fp.register("flush_commit")
_fp.register("bulk_commit")
_fp.register("compaction_commit")
_fp.register("dict_persist")
_fp.register("region_write_memtable")
_fp.register("balancer_wal_tail_replay")
_fp.register("balancer_handoff_fence")

#: node-local fence marker (lives in the region's WAL dir, NOT on the
#: shared object store: the fence is about THIS node's serving state —
#: the adopting node must open the same shared region dir writable)
FENCE_MARKER = "FENCED"


@dataclass
class RegionDescriptor:
    name: str
    schema: Schema
    region_dir: str               # key prefix on the object store
    wal_dir: str                  # local filesystem dir for the WAL


@dataclass
class IngestProfile:
    """Stage-by-stage wall-clock breakdown of one bulk_ingest call
    (published in BASELINE.md; the perf-smoke test asserts the machinery).
    `sst_write` covers the parallel parquet encode + fsync of all chunks,
    so with N concurrent writers it is wall time, not CPU time."""
    rows: int = 0
    total_s: float = 0.0
    stages: Dict[str, float] = field(default_factory=dict)

    def mrows_per_s(self) -> float:
        return self.rows / self.total_s / 1e6 if self.total_s else 0.0

    def merge(self, other: "IngestProfile") -> None:
        """Accumulate another call's profile (multi-batch loads)."""
        self.rows += other.rows
        self.total_s += other.total_s
        for k, v in other.stages.items():
            self.stages[k] = self.stages.get(k, 0.0) + v

    def describe(self) -> str:
        parts = ", ".join(f"{k}={v:.3f}s"
                          for k, v in sorted(self.stages.items(),
                                             key=lambda kv: -kv[1]))
        return (f"{self.rows} rows in {self.total_s:.3f}s "
                f"({self.mrows_per_s():.2f} Mrows/s): {parts}")


@dataclass
class ScanProfile:
    """Stage-by-stage breakdown of the last aggregate scan over this
    region — the scan twin of IngestProfile (published via EXPLAIN
    ANALYZE, /status and bench.py; the observability tests assert the
    two views agree). `path` names the route taken: "resident" (scan
    cache + device kernel) or "streamed" (cold slice streaming).
    `counters` carries path facts (slices, lean vs merged, cache hit)
    under the same names EXPLAIN ANALYZE prints."""
    path: str = ""
    rows: int = 0
    total_s: float = 0.0
    stages: Dict[str, float] = field(default_factory=dict)
    counters: Dict[str, int] = field(default_factory=dict)

    def mark(self, stage: str, seconds: float) -> None:
        self.stages[stage] = self.stages.get(stage, 0.0) + seconds

    def bump(self, counter: str, n: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + n

    def describe(self) -> str:
        parts = ", ".join(f"{k}={v:.3f}s"
                          for k, v in sorted(self.stages.items(),
                                             key=lambda kv: -kv[1]))
        cnts = ", ".join(f"{k}={v}" for k, v in sorted(
            self.counters.items()))
        return (f"{self.path}: {self.rows} rows in {self.total_s:.3f}s"
                f" ({parts})" + (f" [{cnts}]" if cnts else ""))


@dataclass
class ScanData:
    """Concatenated unsorted runs from memtables + SSTs (SoA).

    Consumers run the device merge/dedup kernel (query path) or the numpy
    twin (host paths) before interpreting rows."""
    schema: Schema
    series_dict: SeriesDict
    series_ids: np.ndarray
    ts: np.ndarray
    seq: np.ndarray
    op_types: np.ndarray
    fields: Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]]

    @property
    def num_rows(self) -> int:
        return len(self.ts)


class RegionSnapshot:
    """A consistent read view (reference: src/storage/src/snapshot.rs)."""

    def __init__(self, region: "Region", version: Version, visible_seq: int):
        self._region = region
        self._version = version
        self.visible_sequence = visible_seq

    @property
    def schema(self) -> Schema:
        return self._version.schema

    def scan(self, *, projection: Optional[Sequence[str]] = None,
             time_range: Optional[TimestampRange] = None,
             series_range: Optional[Tuple[int, int]] = None,
             sid_set: Optional[np.ndarray] = None,
             synthetic_seq: bool = False,
             need_ts: bool = True,
             need_mvcc: bool = True) -> ScanData:
        """need_ts=False / need_mvcc=False let a caller that PROVED it
        will not consult row times / sequence+op values (dup-free,
        delete-free, key-disjoint slice — the streamed cold scan's
        fast path) skip decoding and materializing those columns; the
        returned arrays are 0-stride placeholders. need_ts=False also
        skips the per-file time-range mask: the caller asserts every
        selected row group lies inside its requested range.

        `sid_set` is a SORTED candidate series-id array (a point/IN tag
        predicate resolved through the series dictionary): whole SSTs
        are dropped through their index sidecars (bloom over the file's
        sid set — storage/index.py) before any parquet footer is read,
        surviving files prune row groups through the sidecar's per-group
        sid summary, and rows are masked to exact membership. Files
        without a usable index degrade to stats-only pruning."""
        region = self._region
        v = self._version
        schema = v.schema
        # cooperative KILL: a killed statement stops before (and between)
        # file reads instead of decoding the rest of the region
        from ..common import process_list
        process_list.check_cancelled()
        field_names = [c.name for c in schema.field_columns()
                       if projection is None or c.name in projection]
        runs: List[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray,
                         Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]]]] = []
        # memtables (filter by visible sequence + time range, host-side)
        for mt in v.memtables.all_memtables():
            snap = mt.snapshot()
            if snap.num_rows == 0:
                continue
            sel = snap.seq <= self.visible_sequence
            if time_range is not None:
                if time_range.start is not None:
                    sel &= snap.ts >= time_range.start
                if time_range.end is not None:
                    sel &= snap.ts < time_range.end
            if series_range is not None:
                sel &= (snap.series_ids >= series_range[0]) & \
                       (snap.series_ids < series_range[1])
            if sid_set is not None:
                sel &= np.isin(snap.series_ids, sid_set)
            if not sel.any():
                continue
            fields = {}
            for name in field_names:
                if name in snap.fields:
                    data, valid = snap.fields[name]
                    fields[name] = (data[sel], valid[sel] if valid is not None else None)
                else:  # column added after this memtable was created
                    fields[name] = compat_column(
                        schema.column_schema(name), int(sel.sum()))
            runs.append((snap.series_ids[sel], snap.ts[sel], snap.seq[sel],
                         snap.op_types[sel], fields))
        # SSTs (row-group pruned; concurrent readers — parquet decode
        # drops the GIL, so IO and decompression overlap across files;
        # in-order streaming consumption keeps at most the decoded-but-
        # unprocessed files alive, not the whole region)
        from ..common.runtime import parallel_imap
        candidates = v.ssts.files_in_range(time_range)
        if sid_set is not None and candidates:
            # the index pruning tier: drop whole files through their
            # sid blooms before any footer is opened (stats-only
            # degrade keeps un-indexed files); the prune stage reports
            # files pruned by index as index_files_pruned/_checked
            from .index import prune_files, sst_index_enabled
            if sst_index_enabled():
                candidates, _, _ = prune_files(
                    region.access_layer.load_index, candidates, sid_set)
        for sst in parallel_imap(
                lambda m: region.access_layer.read_sst(
                    m, projection=field_names, time_range=time_range,
                    series_range=series_range, sid_set=sid_set,
                    synthetic_seq=synthetic_seq,
                    need_ts=need_ts),
                candidates):
            process_list.check_cancelled()     # per-file batch boundary
            if sst.num_rows == 0:
                continue
            sel = None
            need_mask = False
            if time_range is not None and need_ts:
                # skip the mask (and the per-column copies it forces) when
                # every surviving row group lies inside the range — the
                # common case for slice reads cut on row-group edges
                tmin, tmax = int(sst.ts.min()), int(sst.ts.max())
                need_mask |= (time_range.start is not None and
                              tmin < time_range.start) or \
                             (time_range.end is not None and
                              tmax >= time_range.end)
            if series_range is not None:
                smin = int(sst.series_ids.min())
                smax = int(sst.series_ids.max())
                need_mask |= smin < series_range[0] or \
                    smax >= series_range[1]
            sid_mask = None
            if sid_set is not None:
                sid_mask = np.isin(sst.series_ids, sid_set)
                need_mask |= not sid_mask.all()
            if need_mask:
                sel = np.ones(sst.num_rows, dtype=bool)
                if time_range is not None and need_ts:
                    if time_range.start is not None:
                        sel &= sst.ts >= time_range.start
                    if time_range.end is not None:
                        sel &= sst.ts < time_range.end
                if series_range is not None:
                    sel &= (sst.series_ids >= series_range[0]) & \
                           (sst.series_ids < series_range[1])
                if sid_mask is not None:
                    sel &= sid_mask
                if not sel.any():
                    continue
            def take(a):
                return a if sel is None else a[sel]
            fields = {name: (take(d), take(vd) if vd is not None else None)
                      for name, (d, vd) in sst.fields.items()}
            runs.append((take(sst.series_ids), take(sst.ts), take(sst.seq),
                         take(sst.op_types), fields))

        if not runs:
            empty = {name: null_column(schema.column_schema(name).dtype, 0)
                     for name in field_names}
            z = np.zeros(0, np.int64)
            return ScanData(schema, region.series_dict, np.zeros(0, np.int32),
                            z, z.copy(), np.zeros(0, np.int8), empty)
        if len(runs) == 1:
            # single source: no concat copies (np.concatenate of one
            # array still copies — measurable on multi-million-row slices)
            sids1, ts1, seq1, op1, fields1 = runs[0]
            return ScanData(schema, region.series_dict, sids1, ts1, seq1,
                            op1, fields1)
        # order runs by their first (sid, ts): key-disjoint sorted runs
        # (sid-chunked bulk loads, series-sliced reads) then concatenate
        # into a globally sorted array and downstream consumers skip the
        # merge sort entirely; overlapping runs are unaffected (they get
        # merge-sorted anyway)
        runs.sort(key=lambda r: (int(r[0][0]), int(r[1][0]))
                  if len(r[0]) else (0, 0))
        series_ids = np.concatenate([r[0] for r in runs])
        total = len(series_ids)
        # placeholder columns stay 0-stride through the concat — a lean
        # scan of N runs must not pay an 8B×rows materialize per column
        # it promised never to read
        ts = np.concatenate([r[1] for r in runs]) if need_ts \
            else np.broadcast_to(np.int64(0), (total,))
        if need_mvcc:
            seq = np.concatenate([r[2] for r in runs])
            op = np.concatenate([r[3] for r in runs])
        else:
            seq = np.broadcast_to(np.int64(0), (total,))
            op = np.broadcast_to(np.int8(0), (total,))
        fields = {}
        for name in field_names:
            datas = [r[4][name][0] for r in runs]
            valids = [r[4][name][1] for r in runs]
            data = np.concatenate(datas)
            if any(vd is not None for vd in valids):
                valid = np.concatenate([
                    vd if vd is not None else np.ones(len(d), dtype=bool)
                    for vd, d in zip(valids, datas)])
            else:
                valid = None
            fields[name] = (data, valid)
        return ScanData(schema, region.series_dict, series_ids, ts, seq, op, fields)

    def read_merged(self, **kwargs) -> ScanData:
        """Host-side merged+deduped view (numpy kernel twin) — used by
        compaction, protocol rows paths and tests."""
        data = self.scan(**kwargs)
        if data.num_rows == 0:
            return data
        kept = merge_dedup_numpy(data.series_ids, data.ts, data.seq,
                                 data.op_types)
        data.series_ids = data.series_ids[kept]
        data.ts = data.ts[kept]
        data.seq = data.seq[kept]
        data.op_types = data.op_types[kept]
        data.fields = {n: (d[kept], v[kept] if v is not None else None)
                       for n, (d, v) in data.fields.items()}
        return data



class Region:
    """See module docstring. All mutating entry points are serialized by
    `_writer_lock` (reference: single-writer-per-region mutex,
    src/storage/src/region/writer.rs:55-101)."""

    def __init__(self, descriptor: RegionDescriptor, store: ObjectStore,
                 *, wal: Optional[Wal] = None,
                 flush_size_bytes: int = 64 * 1024 * 1024,
                 checkpoint_margin: int = 10,
                 row_group_size: int = DEFAULT_ROW_GROUP_SIZE,
                 scheduler=None,
                 purger=None,
                 ttl_ms: Optional[int] = None,
                 compaction_time_window_ms: Optional[int] = None,
                 max_l0_files: int = 4,
                 stall_bytes: Optional[int] = None,
                 wal_opts: Optional[dict] = None,
                 sweep_orphans: bool = True):
        self.descriptor = descriptor
        self.name = descriptor.name
        # unique per in-process region object: cache keys must not collide
        # across engines whose regions share names (same table ids in
        # different data homes)
        import uuid
        self.uid = uuid.uuid4().hex
        self.store = store
        self.flush_size_bytes = flush_size_bytes
        # background machinery (None = synchronous inline fallback)
        self.scheduler = scheduler
        self.purger = purger
        self.ttl_ms = ttl_ms
        self.compaction_time_window_ms = compaction_time_window_ms
        self.max_l0_files = max_l0_files
        # open-time orphan-SST sweep switch: failover adoption on a SHARED
        # object store must not sweep (an unfenced old owner may still be
        # mid-flush; deleting its yet-uncommitted output would poison the
        # manifest edit it is about to write)
        self.sweep_orphans = sweep_orphans
        # writers stall when frozen-but-unflushed memtables pile up past
        # this (reference write-stall: src/storage/src/region/writer.rs:584)
        self.stall_bytes = stall_bytes if stall_bytes is not None \
            else 4 * flush_size_bytes
        self._flush_done = threading.Event()
        self._flush_done.set()
        # bumped whenever committed data is *retracted* (TTL expiry) rather
        # than superseded — incremental scan caches must rebuild then
        self.retraction_epoch = 0
        # elastic-region handoff fence: a fenced region rejects writes
        # with StaleRouteError and suppresses flush/compaction so the
        # adopting node's view of the shared region dir stays stable.
        # Persisted as a node-local marker file so a restart mid-handoff
        # cannot resurrect an unfenced old owner (see fence()).
        self.fenced = False
        # read-replica standby: the region serves reads and applies
        # shipped WAL records at their original sequences, but never
        # accepts client writes and never flushes/compacts — the shared
        # region dir and its manifest belong to the LEADER. Implies
        # fenced; persisted as marker content "standby" (see
        # make_standby()) so a restarted replica datanode comes back in
        # the same role.
        self.standby = False
        #: post-commit replication hook (datanode/replication.py): called
        #: with the region after a write's durability wait. The hook only
        #: nudges the shipper thread — acks NEVER wait on followers.
        self.on_commit = None
        self._writer_lock = TrackedRLock("storage.region_writer")
        if wal is not None:
            self.wal = wal
        else:
            # native group-commit WAL when the toolchain allows, Python
            # twin otherwise (same on-disk format either way)
            from .native_wal import make_wal
            self.wal = make_wal(descriptor.wal_dir, **(wal_opts or {}))
        self.manifest = RegionManifest(
            store, f"{descriptor.region_dir}/manifest",
            checkpoint_margin=checkpoint_margin)
        # schema may be None when opening (recovered from the manifest)
        self.series_dict = (SeriesDict.for_schema(descriptor.schema)
                            if descriptor.schema is not None else None)
        self.access_layer = AccessLayer(
            store, f"{descriptor.region_dir}/sst", descriptor.schema,
            row_group_size=row_group_size)
        self._dict_version = 0
        self._persisted_series = 0
        self.version_control: Optional[VersionControl] = None
        self.last_ingest_profile: Optional[IngestProfile] = None
        self.last_scan_profile: Optional[ScanProfile] = None
        # background-job health: consecutive failures drive retry backoff,
        # lifetime counts + last error surface in /status
        self._bg_failures: Dict[str, int] = {}
        self.bg_errors: Dict[str, Dict] = {}
        self.closed = False

    # ---- lifecycle ----
    @classmethod
    def create(cls, descriptor: RegionDescriptor, store: ObjectStore,
               **kwargs) -> "Region":
        region = cls(descriptor, store, **kwargs)
        # manifest must be virgin: restarting the version counter over an
        # existing region would leave stale higher-version deltas that
        # resurrect on the next open
        state, actions = region.manifest.load()
        if state is not None or actions:
            raise StorageError(
                f"region {descriptor.name} already exists on storage; "
                f"open it instead of creating")
        mutable = Memtable(descriptor.schema, region.series_dict)
        version = Version(schema=descriptor.schema,
                          memtables=MemtableVersion(mutable),
                          ssts=LevelMetas(), flushed_sequence=0,
                          manifest_version=-1)
        region.version_control = VersionControl(version)
        # manifest-first create: the change action makes the region durable
        mv = region.manifest.save([{
            "type": "change", "schema": descriptor.schema.to_dict(),
            "committed_sequence": 0}])
        version_after = Version(schema=descriptor.schema,
                                memtables=version.memtables,
                                ssts=version.ssts, flushed_sequence=0,
                                manifest_version=mv)
        region.version_control = VersionControl(version_after)
        return region

    @classmethod
    def open(cls, descriptor: RegionDescriptor, store: ObjectStore,
             **kwargs) -> Optional["Region"]:
        """Recover a region: manifest → series dict → WAL replay.
        Returns None if the region was never created."""
        region = cls(descriptor, store, **kwargs)
        state, actions = region.manifest.load()
        schema: Optional[Schema] = None
        ssts = LevelMetas()
        flushed_sequence = 0
        committed_sequence = 0
        dict_file: Optional[str] = None
        if state is not None:
            schema = Schema.from_dict(state["schema"])
            ssts = LevelMetas.from_dict(state["ssts"])
            flushed_sequence = state["flushed_sequence"]
            committed_sequence = state.get("committed_sequence", flushed_sequence)
            dict_file = state.get("series_dict_file")
        seen_any = state is not None
        for a in actions:
            seen_any = True
            if a["type"] == "change":
                schema = Schema.from_dict(a["schema"])
                committed_sequence = max(committed_sequence,
                                         a.get("committed_sequence", 0))
            elif a["type"] == "edit":
                ssts = ssts.remove_files(a.get("removed", [])).add_files(
                    [FileMeta.from_dict(f) for f in a.get("added", [])])
                flushed_sequence = max(flushed_sequence,
                                       a.get("flushed_sequence", 0))
                # bulk loads burn sequences into SSTs without WAL entries
                # and may cap flushed_sequence below them — recovery must
                # not re-issue those sequences (equal (sid, ts, seq) keys
                # have an undefined MVCC winner)
                committed_sequence = max(committed_sequence,
                                         a.get("committed_sequence", 0))
                if a.get("series_dict_file"):
                    dict_file = a["series_dict_file"]
            elif a["type"] == "remove":
                return None
        if not seen_any:
            return None
        assert schema is not None
        region.descriptor.schema = schema
        region.series_dict = SeriesDict.for_schema(schema)
        if dict_file is not None:
            raw = json.loads(store.read(f"{descriptor.region_dir}/{dict_file}"))
            region.series_dict = SeriesDict.from_dict(raw)
            region._persisted_series = region.series_dict.num_series
            region._dict_version = int(dict_file.rsplit("-", 1)[-1].split(".")[0]) + 1
        region.access_layer = AccessLayer(
            store, f"{descriptor.region_dir}/sst", schema,
            row_group_size=region.access_layer.row_group_size,
            field_encoding=region.access_layer.field_encoding)
        mutable = Memtable(schema, region.series_dict)
        version = Version(schema=schema, memtables=MemtableVersion(mutable),
                          ssts=ssts, flushed_sequence=flushed_sequence,
                          manifest_version=region.manifest._version)
        region.version_control = VersionControl(
            version, committed_sequence=max(committed_sequence, flushed_sequence))
        if region.sweep_orphans:
            region._sweep_orphan_ssts()
        region._replay_wal(flushed_sequence)
        import os as _os
        marker = region._fence_marker_path()
        if _os.path.exists(marker):
            # this node fenced the region mid-handoff and then restarted:
            # it must come back fenced (an unfenced resurrection could
            # ack writes the migration target will never see). The marker
            # CONTENT distinguishes a mid-migration fence from a standby
            # replica, which reopens fenced-for-writes but read-serving.
            region.fenced = True
            try:
                with open(marker, encoding="utf-8") as fh:
                    kind = fh.read().strip()
            except OSError:
                kind = "fenced"
            if kind == "standby":
                region.standby = True
                logger.info("region %s reopened as a STANDBY replica",
                            region.name)
            else:
                logger.warning("region %s reopened FENCED (handoff marker "
                               "present)", region.name)
        return region

    def _sweep_orphan_ssts(self) -> int:
        """Delete SST files the recovered manifest does not reference.

        At open the region is exclusive and the manifest is authoritative,
        so an unreferenced parquet file is garbage from a crash: a flush /
        compaction / bulk-ingest output whose manifest commit never landed,
        or a compaction victim whose purger delete never ran. Sweeping here
        keeps crashes from leaking storage forever (nothing else ever
        revisits unreferenced files)."""
        referenced = set()
        for f in self.version_control.current.ssts.all_files():
            referenced.add(f.file_name)
            if f.index_file is not None:
                referenced.add(f.index_file)
        prefix = f"{self.descriptor.region_dir}/sst/"
        removed = 0
        try:
            keys = self.store.list(prefix)
        except Exception as e:  # noqa: BLE001 — sweep must not fail open
            logger.warning("region %s: orphan sweep list failed: %s",
                           self.name, e)
            return 0
        for key in keys:
            if key.rsplit("/", 1)[-1] in referenced:
                continue
            try:
                self.store.delete(key)
                removed += 1
            except Exception as e:  # noqa: BLE001
                logger.warning("region %s: orphan sweep could not delete "
                               "%s: %s", self.name, key, e)
        if removed:
            from ..common.telemetry import increment_counter
            increment_counter("orphan_ssts_purged", removed)
            logger.warning("region %s: purged %d orphan SST file(s) left "
                           "by a crash", self.name, removed)
        return removed

    def _replay_wal(self, flushed_sequence: int) -> None:
        vc = self.version_control
        replayed = skipped = 0
        for seq, schema_version, payload in self.wal.read_from(flushed_sequence + 1):
            if seq <= flushed_sequence:
                continue
            # a malformed record must not brick the region forever: count the
            # sequence as consumed, log, and continue (write-side validation
            # makes this unreachable in normal operation)
            try:
                wb = WriteBatch.decode(payload, vc.current.schema)
                vc.current.memtables.mutable.write(seq, wb)
                replayed += 1
            except Exception:  # noqa: BLE001
                logger.exception(
                    "region %s: skipping unreplayable WAL record seq=%d",
                    self.name, seq)
                skipped += 1
            vc.set_committed_sequence(max(vc.committed_sequence, seq))
        if replayed or skipped:
            logger.info("region %s replayed %d WAL entries (%d skipped)",
                        self.name, replayed, skipped)

    # ---- write path ----
    def write(self, batch: WriteBatch) -> int:
        """WAL append → memtable insert → sequence bump. Returns rows written.

        With WAL group commit active (sync_on_write + `SET
        wal_group_commit`), the record is appended under the writer lock
        but the fsync wait happens OUTSIDE it: N concurrent writers
        overlap their appends and share ONE fsync. The ack-side contract
        is unchanged — success returns only after the shared fsync
        covers this write's record. The FAILURE path differs from
        per-append mode: the memtable insert precedes the durability
        wait (visibility must precede the committed-sequence bump the
        incremental scan cache watermarks on), so a write whose shared
        fsync FAILS surfaces its error un-acked but leaves its rows
        visible until restart — the same may-be-durable, never-acked
        class recovery already legally resurfaces (torture invariant:
        "unacked rows appear at most once, or not at all")."""
        from ..common.telemetry import increment_counter, timer
        stall = False
        wal_ticket = None
        with timer("region_write"), self._writer_lock:
            if self.closed:
                raise RegionClosedError(f"region {self.name} closed")
            if self.fenced:
                from ..errors import StaleRouteError
                raise StaleRouteError(
                    f"region {self.name} is fenced for migration")
            vc = self.version_control
            seq = vc.next_sequence()
            with timer("wal_append"):
                try:
                    if self.wal.group_commit_active():
                        wal_ticket = self.wal.append_async(
                            seq, batch.encode(),
                            schema_version=vc.current.schema.version)
                    else:
                        self.wal.append(
                            seq, batch.encode(),
                            schema_version=vc.current.schema.version)
                except BaseException:
                    # the record may already be durable (fsync failed AFTER
                    # the write, an injected wal_fsync fault, a torn tail):
                    # burn the sequence — reusing it would put two different
                    # batches at one seq and make the replay winner undefined
                    vc.set_committed_sequence(
                        max(vc.committed_sequence, seq))
                    raise
            # committed_sequence advances only after the memtable insert:
            # snapshot readers sample it without the writer lock, so rows
            # must be visible in the memtable before their sequence is —
            # the incremental scan cache records `visible` as its permanent
            # high-watermark and would otherwise skip the batch forever.
            # The finally still consumes the sequence on insert failure
            # (it hit the WAL; reuse would corrupt replay).
            try:
                # crash HERE = killed between WAL append and memtable
                # insert: the row is unacked but durable, so recovery may
                # legally surface it (once) — the torture matrix asserts
                # exactly that
                _fp.fail_point("region_write_memtable")
                vc.current.memtables.mutable.write(seq, batch)
            finally:
                vc.set_committed_sequence(seq)
            mts = vc.current.memtables
            if mts.mutable_bytes >= self.flush_size_bytes:
                if self.scheduler is None:
                    self.flush()          # no background pool: inline
                else:
                    self._freeze_and_schedule_flush(background=True)
            stall = (self.version_control.current.memtables.total_bytes -
                     self.version_control.current.memtables.mutable_bytes
                     ) >= self.stall_bytes
        if wal_ticket is not None:
            # group commit: park for the shared fsync OUTSIDE the writer
            # lock so concurrent writers can append meanwhile. A failure
            # here reaches the caller un-acked; the sequence is already
            # consumed and the record replays (at most once) like any
            # other durable-but-unacked write.
            with timer("wal_group_wait"):
                self.wal.wait_durable(wal_ticket)
        if stall and self.scheduler is not None:
            # write stall: block (outside the writer lock so the flush
            # worker can commit) until the backlog drains
            increment_counter("region_write_stalls")
            self._flush_done.wait(timeout=300)
        hook = self.on_commit
        if hook is not None:
            # continuous replica ship: the hook only wakes the shipper
            # thread, after durability — a hook failure must never turn
            # an acked write into an error
            try:
                hook(self)
            except Exception:  # noqa: BLE001
                logger.exception("region %s on_commit hook failed",
                                 self.name)
        increment_counter("region_write_rows", batch.num_rows)
        return batch.num_rows

    def bulk_ingest(self, data, *,
                    chunk_rows: Optional[int] = None) -> int:
        """WAL-less bulk load: sort, series-encode, and write the batch
        straight to L0 SSTs — in parallel chunks — then commit one
        manifest edit. Durability comes from the SSTs themselves (the
        manifest edit is the commit point; a crash before it leaves only
        orphan files), so the WAL append, memtable copy, and later flush
        of the normal write path disappear. The LSM "direct part write"
        pattern; the reference reaches similar rates by keeping its
        write path native end-to-end (src/storage/src/region/writer.rs).

        Any buffered memtable rows are flushed first so the manifest's
        flushed_sequence may advance past this batch's sequence without
        orphaning their WAL entries at replay.

        Each call records its stage breakdown in `self.last_ingest_profile`
        (series encode / sort / parquet+fsync / manifest — the profile
        BASELINE.md publishes)."""
        import os as _os
        import time as _time

        from ..common.runtime import parallel_map
        from ..common.telemetry import increment_counter
        from ..ops.kernels import _merge_order

        prof = IngestProfile()
        _t = _time.perf_counter()
        _t0 = _t

        def mark(stage: str) -> None:
            nonlocal _t
            now = _time.perf_counter()
            prof.stages[stage] = prof.stages.get(stage, 0.0) + (now - _t)
            _t = now

        if chunk_rows is None:
            # one SST per writer core: chunking only pays when parquet
            # encodes run concurrently, and fewer files mean single-run
            # (merge-free) scan slices later
            cpus = _os.cpu_count() or 1
            n_in = len(next(iter(data.values()))) if data else 0
            chunk_rows = max(2_000_000, -(-n_in // cpus))

        if self.fenced:
            from ..errors import StaleRouteError
            raise StaleRouteError(
                f"region {self.name} is fenced for migration")
        vc = self.version_control
        schema0 = vc.current.schema
        # all-ndarray batches skip the WriteBatch/Vector coercion (string
        # <U→object conversion alone costs ~0.2s per 2M rows); anything
        # else goes through the validating path
        raw = isinstance(data, dict) and \
            all(isinstance(v, np.ndarray) for v in data.values()) and \
            all(c.name in data for c in schema0.column_schemas) and \
            all(not (c.dtype.is_string or c.dtype.is_binary) or c.is_tag
                for c in schema0.column_schemas if c.name in data)
        if raw:
            rb = None
            n = len(next(iter(data.values())))
            if any(len(v) != n for v in data.values()):
                raise InvalidArgumentsError("ragged bulk_ingest columns")
        else:
            wb = WriteBatch(schema0)
            wb.put(data)
            rb = wb.mutations[0].data
            n = rb.num_rows
        if n == 0:
            return 0
        prof.rows = n
        mark("coerce")
        if any(mt.num_rows for mt in vc.current.memtables.all_memtables()):
            self.flush()
            mark("pre_flush")
        with self._writer_lock:
            if self.closed:
                raise RegionClosedError(f"region {self.name} closed")
            if self.fenced:
                # RE-checked under the lock: the early check races the
                # fence — a bulk commit slipping past it would land rows
                # in neither the pre-fence flush nor the shipped WAL
                # tail (acked-write loss across the migration)
                from ..errors import StaleRouteError
                raise StaleRouteError(
                    f"region {self.name} is fenced for migration")
            schema = vc.current.schema
            seq = vc.next_sequence()
            vc.set_committed_sequence(seq)
            tag_names = schema.tag_names()
            if tag_names:
                tag_cols = []
                for t in tag_names:
                    if rb is None:
                        tag_cols.append(data[t])
                    else:
                        vec = rb.column(t)
                        tag_cols.append(vec.data if vec.validity is None
                                        else vec.to_pylist())
                sids = self.series_dict.encode_rows(tag_cols)
            else:
                sids = self.series_dict.encode_zero_tags(n)
            mark("series_encode")
            ts_name = schema.timestamp_column.name
            ts = np.asarray(data[ts_name] if rb is None
                            else rb.column(ts_name).data, dtype=np.int64)
            # loaders usually present rows grouped by tag in time order —
            # already (sid, ts)-sorted, so the sort AND the per-column
            # gather copies can be skipped entirely
            pre_sorted = n <= 1 or bool(np.all(
                (sids[1:] > sids[:-1]) |
                ((sids[1:] == sids[:-1]) & (ts[1:] >= ts[:-1]))))
            if pre_sorted:
                order = None
                mark("sort_check")
            else:
                mark("sort_check")
                order = _merge_order(sids, ts, np.zeros(n, np.int64))
                sids = sids[order]
                ts = ts[order]
                mark("sort")
            fields = {}
            for c in schema.field_columns():
                if rb is None:
                    want = c.dtype.np_dtype
                    d = data[c.name]
                    if want is not None and d.dtype != want:
                        d = d.astype(want)
                    vd = None
                elif rb.schema.contains(c.name):
                    vec = rb.column(c.name)
                    d = np.asarray(vec.data)
                    vd = vec.validity
                else:
                    d, vd = compat_column(c, n)
                    fields[c.name] = (d, vd)
                    continue
                if order is not None:
                    d = d[order]
                    vd = vd[order] if vd is not None else None
                fields[c.name] = (d, vd)
            seq_arr = np.full(n, seq, dtype=np.int64)
            op_arr = np.zeros(n, dtype=np.int8)
            mark("field_prep")

            # chunk at SERIES boundaries: a (sid, ts) key must not span
            # two files (same sequence → undefined MVCC winner), and
            # keeping whole series per file makes the chunks' key
            # rectangles disjoint — so compaction trivially moves them
            # instead of rewriting the region. Write SSTs concurrently;
            # parquet encode drops the GIL.
            cuts = [0]
            pos = chunk_rows
            while pos < n:
                while pos < n and sids[pos] == sids[pos - 1]:
                    pos += 1
                if pos < n:
                    cuts.append(pos)
                pos += chunk_rows
            cuts.append(n)
            tag_id_cols = {
                name: self.series_dict.tag_id_column(sids, i)
                for i, name in enumerate(self.series_dict.tag_names)}

            def write_chunk(k):
                a, b = cuts[k], cuts[k + 1]
                return self.access_layer.write_sst(
                    level=0, series_ids=sids[a:b], ts=ts[a:b],
                    seq=seq_arr[a:b], op_types=op_arr[a:b],
                    fields={nm: (d[a:b],
                                 vd[a:b] if vd is not None else None)
                            for nm, (d, vd) in fields.items()},
                    tag_columns={nm: (idx[a:b], vals)
                                 for nm, (idx, vals) in tag_id_cols.items()},
                    schema=schema)

            mark("chunk_plan")
            files = [f for f in parallel_map(write_chunk,
                                             range(len(cuts) - 1))
                     if f is not None]
            mark("sst_write")
            flushed_seq = max(seq, vc.current.flushed_sequence)
            # a write() may have landed between the pre-lock flush and
            # acquiring the lock: its WAL entry carries a lower sequence,
            # and advancing flushed_sequence past it would skip it at
            # replay (WAL replays from flushed_sequence + 1). Cap below
            # the lowest unflushed memtable sequence; the bulk rows need
            # no WAL replay (they are durable in the SSTs just written).
            unflushed = [int(ms.seq.min()) for ms in
                         (mt.snapshot()
                          for mt in vc.current.memtables.all_memtables())
                         if ms.num_rows]
            if unflushed:
                flushed_seq = min(flushed_seq, min(unflushed) - 1)
            dict_file = self._persist_series_dict()
            mark("dict_persist")
            edit = {
                "type": "edit",
                "added": [f.to_dict() for f in files],
                "removed": [],
                "flushed_sequence": flushed_seq,
                # the batch's sequence is durable in the SSTs even when
                # flushed_sequence is capped below it (unflushed racing
                # write) — persist it so recovery never re-issues it
                "committed_sequence": seq,
            }
            if dict_file:
                edit["series_dict_file"] = dict_file
            # crash HERE = SSTs durable but uncommitted: the batch was
            # never acked, reopen must sweep the orphans and show nothing
            _fp.fail_point("bulk_commit")
            mv = self.manifest.save([edit])
            vc.apply_flush(memtable_ids=[], files=files,
                           flushed_sequence=flushed_seq,
                           manifest_version=mv)
            self._maybe_checkpoint()
            l0_count = len(vc.current.ssts.levels[0])
            mark("manifest")
            prof.total_s = _time.perf_counter() - _t0
            self.last_ingest_profile = prof
        increment_counter("ingest_rows", n)
        increment_counter("ingest_sst_files", len(files))
        from ..common.telemetry import _observe
        _observe("bulk_ingest", prof.total_s)
        if self.scheduler is not None and l0_count >= self.max_l0_files:
            self.schedule_compaction()
        return n

    # ---- flush ----
    #: background flush/compaction failures retry this many times with
    #: exponential backoff before standing down until the next trigger
    BG_MAX_RETRIES = 8

    def _freeze_and_schedule_flush(self, background: bool = False):
        """Freeze the mutable memtable and queue a background flush.
        Caller holds the writer lock. background=True (the write-path
        trigger, no caller waits) routes through the retrying wrapper:
        a transient failure backs off and re-runs instead of wedging
        the region behind a memtable backlog forever; the synchronous
        flush() path keeps raw error propagation through its handle."""
        vc = self.version_control
        if vc.current.memtables.mutable.num_rows:
            vc.freeze_mutable(Memtable(vc.current.schema, self.series_dict))
        if not vc.current.memtables.immutables:
            return None
        self._flush_done.clear()
        try:
            job = self._flush_job_bg if background else self._flush_job
            return self.scheduler.submit(f"flush:{self.uid}", job)
        except RuntimeError:
            # engine shutting down: skip — the WAL keeps the frozen data
            # durable and replay restores it on the next open
            self._flush_done.set()
            return None

    # ---- background-job degradation ----
    def _flush_job_bg(self) -> List[FileMeta]:
        try:
            files = self._flush_job()
        except Exception as e:  # noqa: BLE001 — retried below
            self._note_bg_failure("flush", e)
            return []
        self._note_bg_success("flush")
        return files

    def _compact_job_bg(self) -> List[FileMeta]:
        try:
            files = self._compact_job()
        except Exception as e:  # noqa: BLE001 — retried below
            self._note_bg_failure("compaction", e)
            return []
        self._note_bg_success("compaction")
        return files

    def _note_bg_success(self, op: str) -> None:
        self._bg_failures.pop(op, None)

    def _note_bg_failure(self, op: str, e: Exception) -> None:
        """A background flush/compaction failed: record it for /status,
        then re-queue with exponential backoff. After BG_MAX_RETRIES
        consecutive failures the job stands down (the next write-path
        trigger starts a fresh attempt cycle) instead of spinning."""
        from ..common.telemetry import increment_counter
        n = self._bg_failures.get(op, 0) + 1
        self._bg_failures[op] = n
        info = self.bg_errors.setdefault(op, {"count": 0, "last_error": ""})
        info["count"] += 1
        info["last_error"] = f"{type(e).__name__}: {e}"
        increment_counter(f"{op}_bg_failures")
        if self.closed or self.scheduler is None:
            return
        if n > self.BG_MAX_RETRIES:
            logger.error(
                "region %s: background %s failed %d times (%s); standing "
                "down until the next trigger", self.name, op, n, e)
            self._bg_failures.pop(op, None)
            return
        delay = min(0.05 * (2 ** (n - 1)), 30.0)
        increment_counter(f"{op}_bg_retries")
        logger.warning(
            "region %s: background %s failed (%s); retry %d/%d in %.2fs",
            self.name, op, e, n, self.BG_MAX_RETRIES, delay)
        if op == "flush":
            key, fn = f"flush:{self.uid}", self._flush_job_bg
        else:
            key, fn = f"compact:{self.uid}", self._compact_job_bg
        self.scheduler.submit_later(key, fn, delay)

    def flush(self) -> List[FileMeta]:
        """Flush all frozen + mutable data to L0 SSTs and wait for
        completion (reference: src/storage/src/flush.rs FlushJob). The
        write path instead schedules `_flush_job` asynchronously."""
        if self.fenced:
            # mid-handoff: the shared manifest belongs to the adopting
            # node; the WAL tail already shipped everything unflushed
            return []
        if self.scheduler is None:
            with self._writer_lock:
                vc = self.version_control
                if vc.current.memtables.mutable.num_rows:
                    vc.freeze_mutable(Memtable(vc.current.schema,
                                               self.series_dict))
                if not vc.current.memtables.immutables:
                    return []
                return self._flush_job()
        with self._writer_lock:
            handle = self._freeze_and_schedule_flush()
            frozen = {m.id for m in
                      self.version_control.current.memtables.immutables}
        files = handle.wait(timeout=600) if handle is not None else []
        # the submit may have coalesced onto an already-queued BACKGROUND
        # flush whose failure is swallowed for retry — a synchronous flush
        # must not report success while the memtables it froze are still
        # unflushed (callers like /v1/admin/flush rely on the contract)
        if not self.closed and not self.fenced and frozen & {
                m.id for m in
                self.version_control.current.memtables.immutables}:
            last = self.bg_errors.get("flush", {}).get("last_error",
                                                       "unknown error")
            raise StorageError(
                f"flush of region {self.name} failed: {last}")
        return files

    def _flush_job(self) -> List[FileMeta]:
        """Write every frozen memtable to L0 SSTs; record the edit in the
        manifest; truncate the WAL. Runs on a scheduler worker: SST encode
        happens outside the writer lock, only the commit takes it."""
        try:
            return self._flush_job_inner()
        finally:
            # a failed flush must not leave stalled writers blocking their
            # full timeout — they re-check the backlog and stall again if
            # it is still above the limit
            self._flush_done.set()

    def _flush_job_inner(self) -> List[FileMeta]:
        from ..common.telemetry import increment_counter, span, timer
        if self.closed or self.fenced:
            # a delayed retry may fire after DROP destroyed the region
            # dir: writing SSTs there would leak files forever (a dropped
            # region never reopens, so no sweep collects them). A FENCED
            # region's manifest belongs to the adopting node now — its
            # WAL tail already shipped, so flushing it here would race
            # the new owner's manifest edits with duplicate data.
            return []
        vc = self.version_control
        to_flush = list(vc.current.memtables.immutables)
        if not to_flush:
            return []
        # a background job roots its own trace (information_schema.
        # background_jobs + the durable trace store see it); the span
        # timer keeps feeding greptime_region_flush_seconds
        from ..common import background_jobs
        with background_jobs.job("flush", region=self.name), \
                span("region_flush", region=self.name), \
                timer("region_flush"):
            files = self._flush_memtables(to_flush)
        increment_counter("flush_files", len(files))
        increment_counter("flush_rows",
                          sum(f.num_rows for f in files))
        return files

    def _flush_memtables(self, to_flush) -> List[FileMeta]:
        vc = self.version_control
        # safe WAL truncation point: every row with seq <= the max sequence
        # in the frozen set lives in these memtables (the mutable only
        # receives later sequences)
        flushed_seq = 0
        files: List[FileMeta] = []
        for mt in to_flush:
            snap = mt.snapshot()
            if snap.num_rows:
                flushed_seq = max(flushed_seq, int(snap.seq.max()))
            meta = self._flush_memtable(mt)
            if meta is not None:
                files.append(meta)
        with self._writer_lock:
            if self.closed:
                return files
            flushed_seq = max(flushed_seq, vc.current.flushed_sequence)
            dict_file = self._persist_series_dict()
            edit = {
                "type": "edit",
                "added": [f.to_dict() for f in files],
                "removed": [],
                "flushed_sequence": flushed_seq,
            }
            if dict_file:
                edit["series_dict_file"] = dict_file
            # crash HERE = flush SSTs durable but uncommitted: the WAL
            # still covers every frozen row, so reopen replays them and
            # sweeps the orphan files — no loss, no duplication
            _fp.fail_point("flush_commit")
            mv = self.manifest.save([edit])
            vc.apply_flush(memtable_ids=[m.id for m in to_flush],
                           files=files, flushed_sequence=flushed_seq,
                           manifest_version=mv)
            self._maybe_checkpoint()
            self.wal.obsolete(flushed_seq)
            l0_count = len(vc.current.ssts.levels[0])
        if self.scheduler is not None and l0_count >= self.max_l0_files:
            self.schedule_compaction()
        return files

    def _flush_memtable(self, mt: Memtable) -> Optional[FileMeta]:
        snap = mt.snapshot()
        if snap.num_rows == 0:
            return None
        # sort by (series, ts, seq) but KEEP all sequences/ops: MVCC history
        # collapses only at compaction (dedup here would break snapshot reads
        # of older sequences — matches reference flush semantics)
        from ..ops.kernels import _merge_order
        order = _merge_order(snap.series_ids, snap.ts, snap.seq)
        sids = snap.series_ids[order]
        # (indices, values) pairs: write_sst builds DictionaryArrays
        # directly — no 2M-string materialize + re-encode round trip
        tag_cols = {
            name: self.series_dict.tag_id_column(sids, i)
            for i, name in enumerate(self.series_dict.tag_names)}
        fields = {}
        for name, (data, valid) in snap.fields.items():
            fields[name] = (data[order], valid[order] if valid is not None else None)
        return self.access_layer.write_sst(
            level=0, series_ids=sids, ts=snap.ts[order], seq=snap.seq[order],
            op_types=snap.op_types[order], fields=fields,
            tag_columns=tag_cols, schema=mt.schema)

    def _persist_series_dict(self) -> Optional[str]:
        if self.series_dict.num_series == self._persisted_series:
            return None
        _fp.fail_point("dict_persist")
        name = f"dict/series-{self._dict_version}.json"
        self.store.write(f"{self.descriptor.region_dir}/{name}",
                         json.dumps(self.series_dict.to_dict()).encode())
        self._dict_version += 1
        self._persisted_series = self.series_dict.num_series
        return name

    def _maybe_checkpoint(self) -> None:
        if not self.manifest.should_checkpoint():
            return
        vc = self.version_control
        v = vc.current
        dict_file = (f"dict/series-{self._dict_version - 1}.json"
                     if self._dict_version else None)
        self.manifest.save_checkpoint({
            "schema": v.schema.to_dict(),
            "ssts": v.ssts.to_dict(),
            "flushed_sequence": v.flushed_sequence,
            "committed_sequence": vc.committed_sequence,
            "series_dict_file": dict_file,
        })
        self.manifest.gc()

    # ---- compaction ----
    def schedule_compaction(self, wait: bool = False):
        """Queue a background compaction (dedup-keyed: repeat submits while
        one is queued coalesce). Returns the job handle."""
        if self.scheduler is None:
            return self._compact_job()
        try:
            # fire-and-forget submits degrade gracefully (retry with
            # backoff on failure); waited submits keep raw errors so the
            # caller sees them on handle.wait()
            job = self._compact_job if wait else self._compact_job_bg
            handle = self.scheduler.submit(f"compact:{self.uid}", job)
        except RuntimeError:
            return None                  # engine shutting down
        if wait:
            out = handle.wait(timeout=600)
            # the submit may have coalesced onto a queued BACKGROUND job
            # whose failure was swallowed for retry: a pending failure
            # count means the compaction the caller waited on did not land
            if not out and self._bg_failures.get("compaction"):
                raise StorageError(
                    f"compaction of region {self.name} failed: "
                    f"{self.bg_errors.get('compaction', {}).get('last_error', 'unknown error')}")
            return out
        return handle

    def compact(self, now_ms: Optional[int] = None) -> List[FileMeta]:
        """Synchronous manual compaction (reference: writer.rs:681 manual
        compact path; ALTER TABLE ... COMPACT / admin endpoint). Serialized
        with background compactions through the scheduler's dedup key —
        two concurrent runs over the same L0 inputs would each write an L1
        copy of every row."""
        if self.closed:
            return []
        if self.scheduler is not None:
            try:
                out = self.scheduler.submit(
                    f"compact:{self.uid}",
                    lambda: self._compact_job(min_l0_files=1,
                                              now_ms=now_ms)
                ).wait(timeout=600)
                if not out and \
                        self.version_control.current.ssts.levels[0]:
                    # the submit coalesced into an already-queued background
                    # job that declined (below its L0 threshold) — run the
                    # manual plan now that the key is free
                    out = self.scheduler.submit(
                        f"compact:{self.uid}",
                        lambda: self._compact_job(min_l0_files=1,
                                                  now_ms=now_ms)
                    ).wait(timeout=600)
                return out
            except RuntimeError:
                return []
        return self._compact_job(min_l0_files=1, now_ms=now_ms)

    def _compact_job(self, min_l0_files: Optional[int] = None,
                     now_ms: Optional[int] = None) -> List[FileMeta]:
        from .compaction import pick_compaction, run_compaction
        if self.closed or self.fenced:
            # fenced: the shared region dir belongs to the adopting node;
            # a compaction here would purge files its manifest references
            return []
        plan = pick_compaction(
            self.version_control.current.ssts, ttl_ms=self.ttl_ms,
            now_ms=now_ms,
            min_l0_files=self.max_l0_files if min_l0_files is None
            else min_l0_files,
            time_window_ms=self.compaction_time_window_ms)
        if plan is None:
            return []
        return run_compaction(self, plan, ttl_ms=self.ttl_ms, now_ms=now_ms)

    def commit_compaction(self, *, removed: List[str],
                          added: List[FileMeta],
                          retracts: bool = False,
                          purge: bool = True) -> None:
        """Swap compaction outputs into the version + manifest and hand the
        removed files to the purger (they stay readable until the grace
        period passes). retracts=True marks that visible rows disappeared
        (TTL expiry), invalidating incremental scan caches. purge=False is
        the trivial-move case: `removed` names reappear in `added` at a
        deeper level (same physical files), so nothing may be deleted."""
        with self._writer_lock:
            if self.closed:
                return
            # crash HERE = compaction outputs durable but uncommitted:
            # inputs stay referenced (still readable), outputs are
            # orphans for the reopen sweep — no data moves twice
            _fp.fail_point("compaction_commit")
            mv = self.manifest.save([{
                "type": "edit",
                "added": [f.to_dict() for f in added],
                "removed": list(removed),
            }])
            self.version_control.apply_compaction(
                removed=removed, added=added, manifest_version=mv)
            if retracts:
                self.retraction_epoch += 1
            self._maybe_checkpoint()
        if purge:
            for name in removed:
                if self.purger is not None:
                    self.purger.schedule(
                        (lambda n=name: self.access_layer.delete_sst(n)),
                        name)

    # ---- TTL ----
    def apply_ttl(self, now_ms: Optional[int] = None) -> int:
        """Drop whole SSTs past the region TTL (row-level expiry happens at
        compaction). Returns the number of files dropped."""
        if self.ttl_ms is None:
            return 0
        import time as _time
        now_ms = int(_time.time() * 1000) if now_ms is None else now_ms
        cutoff = now_ms - self.ttl_ms
        expired = [f for f in self.version_control.current.ssts.all_files()
                   if f.time_range[1] < cutoff]
        if not expired:
            return 0
        from ..common import background_jobs
        with background_jobs.job("ttl_sweep", region=self.name,
                                 files=len(expired)):
            self.commit_compaction(removed=[f.file_name for f in expired],
                                   added=[], retracts=True)
        return len(expired)

    # ---- alter ----
    def alter(self, new_schema: Schema) -> None:
        """Schema change: bump version, record in manifest, swap memtable.
        (reference: src/storage/src/region/writer.rs alter path)"""
        with self._writer_lock:
            vc = self.version_control
            new_schema = Schema(new_schema.column_schemas,
                                version=vc.current.schema.version + 1)
            mv = self.manifest.save([{
                "type": "change", "schema": new_schema.to_dict(),
                "committed_sequence": vc.committed_sequence}])
            # tags are immutable in v0 (same as reference): series dict unchanged
            new_mutable = Memtable(new_schema, self.series_dict)
            vc.apply_schema_change(new_schema, new_mutable, mv)
            self.descriptor.schema = new_schema
            self.access_layer.schema = new_schema
            self._maybe_checkpoint()

    @property
    def schema(self) -> Schema:
        """Current (possibly altered) region schema."""
        return self.version_control.current.schema

    # ---- read ----
    def snapshot(self) -> RegionSnapshot:
        vc = self.version_control
        return RegionSnapshot(self, vc.current, vc.committed_sequence)

    # ---- elastic handoff (meta/balancer.py drives these) ----
    def _fence_marker_path(self) -> str:
        import os as _os
        return _os.path.join(self.descriptor.wal_dir, FENCE_MARKER)

    def fence(self) -> None:
        """Stop accepting writes, durably: the marker file (node-local,
        next to the WAL) survives a restart, so a crashed-and-reopened
        old owner cannot ack a write the migration target never sees.
        Waits out any in-flight flush so the shared manifest is quiescent
        before the caller reads the WAL tail."""
        import os as _os
        from ..utils import atomic_write
        with self._writer_lock:
            if self.fenced:
                return
            _os.makedirs(self.descriptor.wal_dir, exist_ok=True)
            atomic_write(self._fence_marker_path(), "fenced\n",
                         tmp_prefix=".fence-")
            self.fenced = True
            # crash HERE (torture): the marker is durable, so the reopened
            # region comes back fenced and the balancer resumes the step
            _fp.fail_point("balancer_handoff_fence")
        # outside the writer lock: the flush worker needs it to commit
        self._flush_done.wait(timeout=60)
        logger.info("region %s fenced for handoff", self.name)

    def unfence(self) -> None:
        """Roll back a fence (aborted migration), or complete a standby
        promotion: the region starts accepting writes again."""
        import os as _os
        with self._writer_lock:
            try:
                _os.remove(self._fence_marker_path())
            except FileNotFoundError:
                pass
            self.fenced = False
            self.standby = False
        logger.info("region %s unfenced", self.name)

    def make_standby(self) -> None:
        """Mark this region a read-replica standby, durably: the marker
        (content "standby", same node-local file as fence()) survives a
        restart, so the replica reopens fenced-for-writes but
        read-serving. A standby never flushes or compacts — the shared
        region dir belongs to the leader — and catches up either from
        shipped WAL records (ingest_wal_tail) or by reopening from the
        leader's advanced manifest (StorageEngine.reopen_region)."""
        import os as _os
        from ..utils import atomic_write
        with self._writer_lock:
            _os.makedirs(self.descriptor.wal_dir, exist_ok=True)
            atomic_write(self._fence_marker_path(), "standby\n",
                         tmp_prefix=".fence-")
            self.fenced = True
            self.standby = True
        logger.info("region %s is now a standby replica", self.name)

    def wal_entries_since(self, after_seq: int,
                          max_records: Optional[int] = None) -> List[dict]:
        """WAL records in (after_seq, committed], wire-encodable — the
        continuous replica ship feed. Unlike wal_tail() this is safe on
        a LIVE region: records past the committed sequence (concurrent
        in-flight appends) are excluded, and the WAL's read path never
        truncates the active segment, so shipping proceeds under full
        write load without fencing."""
        import base64
        if isinstance(self.wal, NoopWal):
            return []        # disable_wal region: nothing to ship
        committed = self.version_control.committed_sequence
        out: List[dict] = []
        for seq, schema_version, payload in self.wal.read_from(
                after_seq + 1):
            if seq <= after_seq:
                continue
            if seq > committed:
                break
            out.append({"seq": int(seq), "schema_version": schema_version,
                        "payload": base64.b64encode(payload).decode()})
            if max_records is not None and len(out) >= max_records:
                break
        return out

    def wal_tail(self) -> List[dict]:
        """Every WAL record past the flushed sequence, wire-encodable —
        the delta the migration target replays on top of the shared
        object store's last-flushed state. Call only on a FENCED region
        (the tail must be final)."""
        import base64
        flushed = self.version_control.current.flushed_sequence
        out: List[dict] = []
        for seq, schema_version, payload in self.wal.read_from(flushed + 1):
            if seq <= flushed:
                continue
            out.append({"seq": int(seq), "schema_version": schema_version,
                        "payload": base64.b64encode(payload).decode()})
        return out

    def ingest_wal_tail(self, entries: List[dict]) -> int:
        """Replay a shipped WAL tail into this (adopted) region: each
        record appends to the LOCAL WAL for durability, then lands in
        the memtable at its ORIGINAL sequence so MVCC ordering matches
        the source exactly. Idempotent: records at or below the committed
        sequence are skipped, so a crash mid-replay resumes cleanly."""
        import base64
        replayed = 0
        with self._writer_lock:
            if self.closed:
                raise RegionClosedError(f"region {self.name} closed")
            vc = self.version_control
            for e in entries:
                seq = int(e["seq"])
                if seq <= vc.committed_sequence:
                    continue
                _fp.fail_point("balancer_wal_tail_replay")
                payload = base64.b64decode(e["payload"])
                self.wal.append(
                    seq, payload,
                    schema_version=int(e.get("schema_version") or 0))
                wb = WriteBatch.decode(payload, vc.current.schema)
                vc.current.memtables.mutable.write(seq, wb)
                vc.set_committed_sequence(seq)
                replayed += 1
        if replayed:
            logger.info("region %s replayed %d shipped WAL tail record(s)",
                        self.name, replayed)
        return replayed

    def release(self) -> None:
        """Hand the region off: close WITHOUT flushing (the new owner
        already has everything — last-flushed SSTs plus the shipped WAL
        tail) and delete the node-local WAL + fence marker. Shared
        object-store data is untouched: it belongs to the new owner."""
        with self._writer_lock:
            self.closed = True
            self.wal.close()
        import shutil
        shutil.rmtree(self.descriptor.wal_dir, ignore_errors=True)
        logger.info("region %s released to its new owner", self.name)

    # ---- misc ----
    def drop(self) -> None:
        """Tombstone the manifest, then physically delete region data + WAL.

        The remove action lands first so a crash mid-delete leaves a region
        that `open()` reports as gone; leftover files are garbage, never
        resurrected state. Physical removal lets the name be re-created
        (TRUNCATE = drop + create)."""
        with self._writer_lock:
            self.manifest.save([{"type": "remove"}])
            self.closed = True
            self.wal.close()
        for key in self.store.list(self.descriptor.region_dir):
            self.store.delete(key)
        import shutil
        shutil.rmtree(self.descriptor.wal_dir, ignore_errors=True)

    def close(self) -> None:
        with self._writer_lock:
            self.closed = True
            self.wal.close()


# ---- promotion-time WAL salvage (datanode repl_promote drives these; the
# old leader is DEAD, so its node-local WAL dir is operated on by path) ----

def fence_wal_dir(wal_dir: str) -> None:
    """Durably fence a region by WAL-directory path alone — written into
    a dead leader's node-local WAL dir before salvaging its tail: if the
    old owner resurrects, Region.open sees the marker and comes back
    fenced, so it can never ack a write the promoted replica misses."""
    import os as _os
    from ..utils import atomic_write
    _os.makedirs(wal_dir, exist_ok=True)
    atomic_write(_os.path.join(wal_dir, FENCE_MARKER), "fenced\n",
                 tmp_prefix=".fence-")


def salvage_wal_entries(wal_dir: str, after_seq: int) -> List[dict]:
    """Every record past after_seq from a dead node's WAL directory,
    wire-encodable. Opening a fresh Wal over the dir recovers its
    segments; a torn tail (the leader was killed mid-append) holds only
    never-acked records — the ack always follows the fsync — so the
    open-time repair-truncate cannot drop an acked row. A missing dir
    degrades to an empty salvage (a leader that never wrote)."""
    import base64
    import os as _os
    if not _os.path.isdir(wal_dir):
        return []
    wal = Wal(wal_dir)
    try:
        out: List[dict] = []
        for seq, schema_version, payload in wal.read_from(after_seq + 1):
            if seq <= after_seq:
                continue
            out.append({"seq": int(seq), "schema_version": schema_version,
                        "payload": base64.b64encode(payload).decode()})
        return out
    finally:
        wal.close()
