"""Retrying object-store wrapper: exponential backoff + jitter over
transient faults.

Reference behavior: opendal's retry layer (the reference wraps its S3
operator in `RetryLayer` with exponential backoff) — transient service
errors (HTTP 5xx/429, socket resets) retry transparently; logical errors
(404, signature mismatch) surface immediately. Only idempotent operations
retry: whole-object GET/PUT/DELETE/HEAD/LIST all are, which is every
operation this interface exposes.

Knobs (live — SET applies to in-flight stores):

- ``GREPTIME_OBJSTORE_MAX_RETRIES`` / ``SET objstore_max_retries`` —
  attempts AFTER the first try (default 3; 0 disables retry).
- ``GREPTIME_OBJSTORE_RETRY_BASE_MS`` / ``SET objstore_retry_base_ms`` —
  first backoff; doubles per attempt, capped at 5s, ±50% jitter.

Counters (runtime_metrics / /metrics): ``greptime_objstore_retry_total``
(sleeps taken), ``greptime_objstore_retry_giveup_total`` (transient
failures that exhausted the budget and surfaced).
"""

from __future__ import annotations

import logging
import random
import time
from typing import List, Optional

from ..common.runtime import env_int as _env_int
from .object_store import ObjectStore, _SpoolPut

logger = logging.getLogger(__name__)

_MAX_BACKOFF_MS = 5000

_max_retries: List[int] = [_env_int("GREPTIME_OBJSTORE_MAX_RETRIES", 3)]
_base_ms: List[int] = [_env_int("GREPTIME_OBJSTORE_RETRY_BASE_MS", 50)]


def configure_retry(*, max_retries: Optional[int] = None,
                    base_ms: Optional[int] = None) -> None:
    """SET objstore_max_retries / objstore_retry_base_ms."""
    if max_retries is not None:
        _max_retries[0] = max(0, int(max_retries))
    if base_ms is not None:
        _base_ms[0] = max(1, int(base_ms))


def retry_settings() -> dict:
    return {"max_retries": _max_retries[0], "base_ms": _base_ms[0]}


def is_transient(exc: BaseException) -> bool:
    """Transient ⇔ a later identical attempt can plausibly succeed.
    FileNotFoundError and friends are logical outcomes, not faults."""
    from ..common.failpoint import FailpointError
    if isinstance(exc, FailpointError):
        return exc.transient
    from ..errors import TransientRpcError
    if isinstance(exc, TransientRpcError):
        return True
    from .s3 import S3TransientError
    if isinstance(exc, S3TransientError):
        return True
    if isinstance(exc, (FileNotFoundError, NotADirectoryError,
                        IsADirectoryError, PermissionError)):
        return False
    return isinstance(exc, (ConnectionError, TimeoutError,
                            InterruptedError))


class RetryingObjectStore(ObjectStore):
    """Wrap any ObjectStore; every idempotent op retries transient
    faults with exponential backoff + jitter before surfacing."""

    def __init__(self, inner: ObjectStore):
        self.inner = inner

    def _with_retry(self, what: str, key: str, fn):
        from ..common.telemetry import increment_counter
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — classified below
                if not is_transient(e) or attempt >= _max_retries[0]:
                    if attempt:
                        increment_counter("objstore_retry_giveup")
                    raise
                attempt += 1
                delay_ms = min(_base_ms[0] * (2 ** (attempt - 1)),
                               _MAX_BACKOFF_MS)
                delay_s = delay_ms / 1e3 * (0.5 + random.random())
                increment_counter("objstore_retry")
                logger.warning(
                    "objstore %s %s failed transiently (%s); retry %d/%d "
                    "in %.0fms", what, key, e, attempt, _max_retries[0],
                    delay_s * 1e3)
                time.sleep(delay_s)

    # ---- ObjectStore surface ----
    def read(self, key: str) -> bytes:
        data = self._with_retry("read", key,
                                lambda: self.inner.read(key))
        # per-read byte accounting: lands on the active statement's
        # ExecStats collector (live `bytes_read` in the processes view);
        # a thread-local read when nobody collects, so the hot path
        # stays unobserved-free
        from ..common import exec_stats
        exec_stats.record("io_read", bytes=len(data))
        return data

    def write(self, key: str, data: bytes) -> None:
        return self._with_retry("write", key,
                                lambda: self.inner.write(key, data))

    def delete(self, key: str) -> None:
        return self._with_retry("delete", key,
                                lambda: self.inner.delete(key))

    def exists(self, key: str) -> bool:
        return self._with_retry("exists", key,
                                lambda: self.inner.exists(key))

    def list(self, prefix: str) -> List[str]:
        return self._with_retry("list", prefix,
                                lambda: self.inner.list(prefix))

    def local_path(self, key: str) -> Optional[str]:
        return self.inner.local_path(key)

    def put_path(self, key: str):
        """Local backends keep their atomic in-place rename (a local
        rename has no transient failure mode worth a spool copy); remote
        backends spool here so the final upload goes through write() —
        and therefore through the retry loop."""
        if type(self.inner).put_path is not ObjectStore.put_path:
            return self.inner.put_path(key)
        return _SpoolPut(self, key)

    def delete_dir(self, key: str) -> None:
        inner_delete = getattr(self.inner, "delete_dir", None)
        if inner_delete is not None:
            self._with_retry("delete_dir", key, lambda: inner_delete(key))
        else:
            for k in self.list(key if key.endswith("/") else key + "/"):
                self.delete(k)

    def __getattr__(self, name: str):
        # pass through backend extras (root, hit_ratio, config, ...);
        # 'inner' itself must miss normally or unpickling would recurse
        if name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)
