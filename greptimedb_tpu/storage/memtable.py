"""Memtables: append-only SoA column buffers.

Reference behavior: src/storage/src/memtable/ — the reference keeps a BTree
ordered by (row key, sequence, op). TPU-first redesign: writes append to
unordered structure-of-arrays numpy buffers (series_id, ts, seq, op, fields);
ordering/dedup happens at read or flush time via the sort-based device kernel
(ops.kernels.sort_merge_dedup) — sorts are what the accelerator is good at,
ordered maps are not. Snapshots are trivially consistent: buffers are
append-only, so a snapshot is just a row count.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common.locks import TrackedLock
from ..datatypes import RecordBatch, Schema
from .series import SeriesDict
from .write_batch import OP_DELETE, OP_PUT, WriteBatch


class _GrowBuf:
    """Amortized-growth numpy append buffer."""

    __slots__ = ("arr", "len")

    def __init__(self, dtype, capacity: int = 1024):
        self.arr = np.empty(capacity, dtype=dtype)
        self.len = 0

    def append(self, values: np.ndarray) -> None:
        n = len(values)
        need = self.len + n
        if need > len(self.arr):
            cap = max(len(self.arr) * 2, need)
            new = np.empty(cap, dtype=self.arr.dtype)
            new[:self.len] = self.arr[:self.len]
            self.arr = new
        self.arr[self.len:need] = values
        self.len = need

    def view(self, n: Optional[int] = None) -> np.ndarray:
        return self.arr[:self.len if n is None else n]


@dataclass
class MemtableSnapshot:
    """A consistent view: first `num_rows` rows of each buffer."""
    num_rows: int
    series_ids: np.ndarray
    ts: np.ndarray
    seq: np.ndarray
    op_types: np.ndarray
    fields: Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]]  # name -> (data, validity)
    min_ts: int
    max_ts: int


class Memtable:
    _next_id = 0

    def __init__(self, schema: Schema, series_dict: SeriesDict):
        self.schema = schema
        self.series_dict = series_dict
        Memtable._next_id += 1
        self.id = Memtable._next_id
        self._lock = TrackedLock("storage.memtable", io_ok=False)
        self._series = _GrowBuf(np.int32)
        self._ts = _GrowBuf(np.int64)
        self._seq = _GrowBuf(np.int64)
        self._op = _GrowBuf(np.int8)
        self._fields: Dict[str, Tuple[_GrowBuf, _GrowBuf]] = {}
        for c in schema.field_columns():
            self._fields[c.name] = (
                _GrowBuf(c.dtype.np_dtype if c.dtype.np_dtype is not None else object),
                _GrowBuf(np.bool_),
            )
        self._min_ts: Optional[int] = None
        self._max_ts: Optional[int] = None
        self._bytes = 0

    @property
    def num_rows(self) -> int:
        return self._ts.len

    @property
    def estimated_bytes(self) -> int:
        return self._bytes

    def time_range(self) -> Optional[Tuple[int, int]]:
        if self._min_ts is None:
            return None
        return (self._min_ts, self._max_ts)

    # ---- write path ----
    def write(self, seq: int, batch: WriteBatch) -> None:
        """Apply all mutations of a WriteBatch at the given sequence."""
        with self._lock:
            for m in batch.mutations:
                if m.op_type == OP_PUT:
                    self._insert(seq, m.data, OP_PUT)
                else:
                    self._insert(seq, m.data, OP_DELETE)

    def _insert(self, seq: int, rb: RecordBatch, op: int) -> None:
        n = rb.num_rows
        if n == 0:
            return
        schema = self.schema
        tag_names = schema.tag_names()
        if tag_names:
            tag_cols = []
            for t in tag_names:
                vec = rb.column(t)
                # object ndarray feeds Dictionary.encode directly; only
                # null-bearing tag columns pay the to_pylist walk
                tag_cols.append(vec.data if vec.validity is None
                                else vec.to_pylist())
            sids = self.series_dict.encode_rows(tag_cols)
        else:
            sids = self.series_dict.encode_zero_tags(n)
        ts_col = rb.column(schema.timestamp_column.name)
        ts = np.asarray(ts_col.data, dtype=np.int64)
        self._series.append(sids)
        self._ts.append(ts)
        self._seq.append(np.full(n, seq, dtype=np.int64))
        self._op.append(np.full(n, op, dtype=np.int8))
        for name, (dataf, validf) in self._fields.items():
            if op == OP_PUT and rb.schema.contains(name):
                vec = rb.column(name)
                dataf.append(np.asarray(vec.data, dtype=dataf.arr.dtype))
                validf.append(vec.validity if vec.validity is not None
                              else np.ones(n, dtype=bool))
            else:
                # delete rows / missing column: nulls
                fill = np.zeros(n, dtype=dataf.arr.dtype) \
                    if dataf.arr.dtype != object else np.full(n, None, dtype=object)
                dataf.append(fill)
                validf.append(np.zeros(n, dtype=bool))
        tmin, tmax = int(ts.min()), int(ts.max())
        self._min_ts = tmin if self._min_ts is None else min(self._min_ts, tmin)
        self._max_ts = tmax if self._max_ts is None else max(self._max_ts, tmax)
        self._bytes += n * (8 + 8 + 4 + 1) + sum(
            n * (8 if f.arr.dtype != object else 32) + n
            for f, _ in self._fields.values())

    # ---- read path ----
    def snapshot(self) -> MemtableSnapshot:
        n = self._ts.len  # append-only ⇒ first n rows are immutable
        return MemtableSnapshot(
            num_rows=n,
            series_ids=self._series.view(n),
            ts=self._ts.view(n),
            seq=self._seq.view(n),
            op_types=self._op.view(n),
            fields={name: (d.view(n), v.view(n))
                    for name, (d, v) in self._fields.items()},
            min_ts=self._min_ts if self._min_ts is not None else 0,
            max_ts=self._max_ts if self._max_ts is not None else -1,
        )


class MemtableVersion:
    """Current mutable memtable + frozen immutables awaiting flush
    (reference: src/storage/src/memtable/version.rs)."""

    def __init__(self, mutable: Memtable):
        self.mutable = mutable
        self.immutables: List[Memtable] = []

    def freeze(self, new_mutable: Memtable) -> "MemtableVersion":
        v = MemtableVersion(new_mutable)
        v.immutables = self.immutables + ([self.mutable]
                                          if self.mutable.num_rows else [])
        return v

    def remove_immutables(self, ids: Sequence[int]) -> "MemtableVersion":
        v = MemtableVersion(self.mutable)
        v.immutables = [m for m in self.immutables if m.id not in set(ids)]
        return v

    def all_memtables(self) -> List[Memtable]:
        return self.immutables + [self.mutable]

    @property
    def mutable_bytes(self) -> int:
        return self.mutable.estimated_bytes

    @property
    def total_bytes(self) -> int:
        return self.mutable.estimated_bytes + sum(
            m.estimated_bytes for m in self.immutables)
