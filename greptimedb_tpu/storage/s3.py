"""S3-compatible object store backend (stdlib SigV4 client).

Reference behavior: src/object-store — opendal's S3 service configured
with bucket/root/endpoint/credentials (src/datanode/src/instance.rs:
object store construction) gives the storage engine an S3 data home.
Here the same `ObjectStore` surface speaks the S3 REST API directly:
AWS Signature V4, path-style addressing (works against AWS, MinIO, GCS
interop, and the in-process mock used by tests).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..common.failpoint import register as _fp_register
from ..errors import GreptimeError
from .object_store import ObjectStore

_fp_register("objstore_request")


@dataclass
class S3Config:
    bucket: str
    root: str = ""                    # key prefix inside the bucket
    endpoint: Optional[str] = None    # http://host:port for non-AWS
    region: str = "us-east-1"
    access_key_id: str = ""
    secret_access_key: str = ""


class S3Error(GreptimeError):
    """Terminal S3 failure (4xx, signature mismatch, malformed reply)."""


class S3TransientError(S3Error):
    """Retryable S3 failure: HTTP 5xx/429 (service hiccup, throttling)
    or a socket-level error before a status line arrived. The
    RetryingObjectStore wrapper backs off and retries these; plain
    S3Error surfaces immediately."""


#: statuses worth retrying: server errors + explicit throttling
_TRANSIENT_STATUSES = frozenset({429, 500, 502, 503, 504, 509})


def _status_error(op: str, key: str, status: int, body: bytes = b"") -> S3Error:
    detail = f"S3 {op} {key}: HTTP {status}"
    if body:
        detail += f" {body[:200]!r}"
    if status in _TRANSIENT_STATUSES:
        return S3TransientError(detail)
    return S3Error(detail)


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class S3ObjectStore(ObjectStore):
    """ObjectStore over the S3 REST API."""

    def __init__(self, config: S3Config):
        self.config = config
        if config.endpoint:
            parsed = urllib.parse.urlparse(config.endpoint)
            self._host = parsed.netloc
            self._secure = parsed.scheme == "https"
        else:
            self._host = f"s3.{config.region}.amazonaws.com"
            self._secure = True
        self._root = config.root.strip("/")

    # ---- SigV4 ----
    def _sign(self, method: str, path: str, query: str,
              payload_hash: str, now: datetime.datetime) -> dict:
        cfg = self.config
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        headers = {
            "host": self._host,
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": amz_date,
        }
        signed_headers = ";".join(sorted(headers))
        canonical_headers = "".join(
            f"{k}:{headers[k]}\n" for k in sorted(headers))
        canonical_request = "\n".join([
            method, path, query, canonical_headers, signed_headers,
            payload_hash])
        scope = f"{datestamp}/{cfg.region}/s3/aws4_request"
        string_to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            _sha256(canonical_request.encode())])
        k = _hmac(b"AWS4" + cfg.secret_access_key.encode(), datestamp)
        k = _hmac(k, cfg.region)
        k = _hmac(k, "s3")
        k = _hmac(k, "aws4_request")
        signature = hmac.new(k, string_to_sign.encode(),
                             hashlib.sha256).hexdigest()
        headers["authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={cfg.access_key_id}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}")
        return headers

    def _request(self, method: str, key: str = "", query: str = "",
                 body: bytes = b"") -> Tuple[int, dict, bytes]:
        from ..common.failpoint import fail_point
        fail_point("objstore_request")
        path = "/" + urllib.parse.quote(self.config.bucket)
        if key:
            path += "/" + urllib.parse.quote(key, safe="/")
        payload_hash = _sha256(body)
        now = datetime.datetime.now(datetime.timezone.utc)
        headers = self._sign(method, path, query, payload_hash, now)
        conn_cls = http.client.HTTPSConnection if self._secure \
            else http.client.HTTPConnection
        conn = conn_cls(self._host, timeout=30)
        try:
            url = path + ("?" + query if query else "")
            conn.request(method, url, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        except (OSError, http.client.HTTPException) as e:
            # no S3 status line arrived: connection refused/reset, DNS
            # hiccup, short read — all worth a retry, none a 4xx
            raise S3TransientError(
                f"S3 {method} {key or path}: {e}") from e
        finally:
            conn.close()

    # ---- ObjectStore surface ----
    def _key(self, key: str) -> str:
        return f"{self._root}/{key}" if self._root else key

    def read(self, key: str) -> bytes:
        status, _, data = self._request("GET", self._key(key))
        if status == 404:
            raise FileNotFoundError(key)
        if status != 200:
            raise _status_error("GET", key, status)
        return data

    def write(self, key: str, data: bytes) -> None:
        status, _, body = self._request("PUT", self._key(key), body=data)
        if status not in (200, 201):
            raise _status_error("PUT", key, status, body)

    def delete(self, key: str) -> None:
        status, _, _ = self._request("DELETE", self._key(key))
        if status not in (200, 204, 404):
            raise _status_error("DELETE", key, status)

    def delete_dir(self, key: str) -> None:
        prefix = key if key.endswith("/") else key + "/"
        for k in self.list(prefix):
            self.delete(k)

    def exists(self, key: str) -> bool:
        status, _, _ = self._request("HEAD", self._key(key))
        if status == 200:
            return True
        if status in (404, 403):
            return False
        raise _status_error("HEAD", key, status)

    def list(self, prefix: str) -> List[str]:
        full_prefix = self._key(prefix) if prefix else self._root
        out: List[str] = []
        token: Optional[str] = None
        while True:
            q = {"list-type": "2", "prefix": full_prefix}
            if token:
                q["continuation-token"] = token
            query = urllib.parse.urlencode(sorted(q.items()))
            status, _, data = self._request("GET", "", query=query)
            if status != 200:
                raise _status_error("LIST", prefix, status)
            root = ET.fromstring(data)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[:root.tag.index("}") + 1]
            for contents in root.iter(f"{ns}Contents"):
                k = contents.find(f"{ns}Key").text
                if self._root and k.startswith(self._root + "/"):
                    k = k[len(self._root) + 1:]
                out.append(k)
            truncated = root.find(f"{ns}IsTruncated")
            if truncated is not None and truncated.text == "true":
                tok = root.find(f"{ns}NextContinuationToken")
                token = tok.text if tok is not None else None
                if token is None:
                    break
            else:
                break
        return sorted(out)

    def local_path(self, key: str) -> Optional[str]:
        return None                      # remote; wrap in LruCacheLayer
