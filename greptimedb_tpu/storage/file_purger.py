"""Deleted-SST garbage collection.

Reference behavior: src/storage/src/file_purger.rs — files removed from a
region version by compaction are deleted asynchronously once no reader holds
them. Snapshots here are short-lived and the scan cache is version-keyed, so
a grace delay stands in for the reference's handle refcounting: a file
becomes eligible `grace_s` seconds after it left the version (0 = purge on
the next sweep).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Tuple

from ..common import failpoint as _fp
from ..common.locks import TrackedLock

logger = logging.getLogger(__name__)

_fp.register("purger_delete")

#: failed deletes re-queue with this backoff ladder, then drop (the
#: region open-time orphan sweep is the backstop for dropped files)
_RETRY_BACKOFF_S = (5.0, 30.0, 120.0)


class FilePurger:
    def __init__(self, grace_s: float = 60.0):
        self.grace_s = grace_s
        self._lock = TrackedLock("storage.purger", io_ok=False)
        # (due_time, delete_fn, name, attempt)
        self._pending: List[Tuple[float, Callable[[], None], str, int]] = []

    def schedule(self, delete_fn: Callable[[], None], name: str) -> None:
        with self._lock:
            self._pending.append(
                (time.time() + self.grace_s, delete_fn, name, 0))

    def sweep(self, force: bool = False) -> int:
        """Delete everything whose grace period has passed (force=True:
        everything pending — engine shutdown, when no reader can remain).
        A failed delete re-queues with backoff instead of leaking the
        file on the first transient object-store error; after the backoff
        ladder is exhausted it drops (the reopen orphan sweep catches it).
        Returns the number deleted."""
        now = time.time()
        with self._lock:
            due = [item for item in self._pending
                   if force or item[0] <= now]
            self._pending = [] if force else \
                [item for item in self._pending if item[0] > now]
        if due:
            from ..common import background_jobs
            ctx = background_jobs.job("purge", files=len(due))
        else:
            import contextlib
            ctx = contextlib.nullcontext()
        with ctx:
            deleted, requeue = self._delete_due(due, force, now)
        if requeue:
            from ..common.telemetry import increment_counter
            increment_counter("purge_retries", len(requeue))
            with self._lock:
                self._pending.extend(requeue)
        return deleted

    def _delete_due(self, due, force: bool, now: float):
        deleted = 0
        requeue = []
        for _, fn, name, attempt in due:
            try:
                _fp.fail_point("purger_delete")
                fn()
                deleted += 1
            except FileNotFoundError:
                deleted += 1
            except Exception as e:  # noqa: BLE001
                if force or attempt >= len(_RETRY_BACKOFF_S):
                    logger.exception(
                        "purging %s failed after %d attempts; dropping "
                        "(reopen orphan sweep will collect it)", name,
                        attempt + 1)
                else:
                    delay = _RETRY_BACKOFF_S[attempt]
                    logger.warning(
                        "purging %s failed (%s); retry %d/%d in %.0fs",
                        name, e, attempt + 1, len(_RETRY_BACKOFF_S), delay)
                    requeue.append((now + delay, fn, name, attempt + 1))
        return deleted, requeue

    @property
    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)
