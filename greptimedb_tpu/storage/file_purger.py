"""Deleted-SST garbage collection.

Reference behavior: src/storage/src/file_purger.rs — files removed from a
region version by compaction are deleted asynchronously once no reader holds
them. Snapshots here are short-lived and the scan cache is version-keyed, so
a grace delay stands in for the reference's handle refcounting: a file
becomes eligible `grace_s` seconds after it left the version (0 = purge on
the next sweep).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Tuple

logger = logging.getLogger(__name__)


class FilePurger:
    def __init__(self, grace_s: float = 60.0):
        self.grace_s = grace_s
        self._lock = threading.Lock()
        self._pending: List[Tuple[float, Callable[[], None], str]] = []

    def schedule(self, delete_fn: Callable[[], None], name: str) -> None:
        with self._lock:
            self._pending.append((time.time() + self.grace_s, delete_fn, name))

    def sweep(self, force: bool = False) -> int:
        """Delete everything whose grace period has passed (force=True:
        everything pending — engine shutdown, when no reader can remain).
        Returns the number deleted."""
        now = time.time()
        with self._lock:
            due = [(t, fn, n) for t, fn, n in self._pending
                   if force or t <= now]
            self._pending = [] if force else \
                [(t, fn, n) for t, fn, n in self._pending if t > now]
        deleted = 0
        for _, fn, name in due:
            try:
                fn()
                deleted += 1
            except FileNotFoundError:
                deleted += 1
            except Exception:  # noqa: BLE001
                logger.exception("purging %s failed; dropping from queue",
                                 name)
        return deleted

    @property
    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)
