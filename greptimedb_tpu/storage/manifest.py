"""Region manifest: durable metadata action log with checkpoints.

Reference behavior: src/storage/src/manifest/ — every metadata mutation
(schema change, SST edit, removal) is an action appended to a versioned log
on object storage; a checkpoint summarizing state is written every
`checkpoint_margin` actions and old deltas are GC'd. Recovery = load last
checkpoint + replay later deltas.

Files under `{region}/manifest/`:
    {version:020d}.json            — one action list per version
    {version:020d}.checkpoint.json — full-state checkpoint at that version
"""

from __future__ import annotations

import json
import re
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..common import failpoint as _fp
from ..common.locks import TrackedLock
from .object_store import ObjectStore

_fp.register("manifest_commit")
_fp.register("manifest_checkpoint")

_DELTA_RE = re.compile(r"^(\d{20})\.json$")
_CKPT_RE = re.compile(r"^(\d{20})\.checkpoint\.json$")


class RegionManifest:
    def __init__(self, store: ObjectStore, manifest_dir: str,
                 checkpoint_margin: int = 10):
        self.store = store
        self.dir = manifest_dir.rstrip("/")
        self.checkpoint_margin = checkpoint_margin
        self._lock = TrackedLock("storage.manifest")
        self._version = -1           # last written version
        self._actions_since_ckpt = 0

    # ---- writing ----
    def save(self, actions: List[dict]) -> int:
        """Append an action list; returns the new manifest version."""
        with self._lock:
            _fp.fail_point("manifest_commit")
            self._version += 1
            v = self._version
            key = f"{self.dir}/{v:020d}.json"
            self.store.write(key, json.dumps(
                {"version": v, "actions": actions}).encode())
            self._actions_since_ckpt += 1
            return v

    def save_checkpoint(self, state: dict) -> None:
        with self._lock:
            _fp.fail_point("manifest_checkpoint")
            v = self._version
            if v < 0:
                return
            key = f"{self.dir}/{v:020d}.checkpoint.json"
            self.store.write(key, json.dumps(
                {"version": v, "state": state}).encode())
            self._actions_since_ckpt = 0

    def should_checkpoint(self) -> bool:
        return self._actions_since_ckpt >= self.checkpoint_margin

    def gc(self) -> None:
        """Delete deltas and older checkpoints covered by the newest
        checkpoint."""
        files = self._files()
        ckpts = sorted(v for v, _, is_c in files if is_c)
        if not ckpts:
            return
        latest = ckpts[-1]
        for v, name, is_c in files:
            if (is_c and v < latest) or (not is_c and v <= latest):
                self.store.delete(f"{self.dir}/{name}")

    # ---- recovery ----
    def load(self) -> Tuple[Optional[dict], List[dict]]:
        """Returns (checkpoint_state | None, actions newer than it, in order).
        Also positions the writer version past the last entry."""
        files = self._files()
        ckpt_versions = sorted(v for v, _, is_c in files if is_c)
        state = None
        start_after = -1
        if ckpt_versions:
            latest = ckpt_versions[-1]
            raw = json.loads(self.store.read(
                f"{self.dir}/{latest:020d}.checkpoint.json"))
            state = raw["state"]
            start_after = latest
        actions: List[dict] = []
        max_v = start_after
        for v, name, is_c in sorted(files):
            if is_c or v <= start_after:
                continue
            raw = json.loads(self.store.read(f"{self.dir}/{name}"))
            actions.extend(raw["actions"])
            max_v = max(max_v, v)
        with self._lock:
            self._version = max_v
            self._actions_since_ckpt = max_v - start_after
        return state, actions

    def _files(self) -> List[Tuple[int, str, bool]]:
        out = []
        for key in self.store.list(self.dir):
            name = key.rsplit("/", 1)[-1]
            m = _CKPT_RE.match(name)
            if m:
                out.append((int(m.group(1)), name, True))
                continue
            m = _DELTA_RE.match(name)
            if m:
                out.append((int(m.group(1)), name, False))
        return out
