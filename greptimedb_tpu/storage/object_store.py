"""Object store abstraction.

Reference behavior: src/object-store (opendal re-export with Fs/S3/OSS
backends plus LRU disk cache). Here: a minimal Operator interface with a
filesystem backend (atomic writes via rename); S3/GCS backends can slot in
behind the same interface. TPU hosts read SSTs through this layer; the
accelerator never touches it.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
from typing import List, Optional

from ..common import failpoint as _fp
from ..common.locks import TrackedLock

_fp.register("objstore_read")
_fp.register("objstore_write")
_fp.register("objstore_delete")


class ObjectStore:
    """Flat key → bytes store. Keys use '/' separators."""

    def read(self, key: str) -> bytes:
        raise NotImplementedError

    def write(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def list(self, prefix: str) -> List[str]:
        raise NotImplementedError

    def local_path(self, key: str) -> Optional[str]:
        """If the object is addressable as a local file (for mmap/parquet
        readers), return its path; else None and callers fall back to read()."""
        return None

    def put_path(self, key: str):
        """Context manager yielding a local filesystem path for the caller
        to write the object into directly (parquet writers stream pages to
        it instead of buffering the whole file in memory). The object
        becomes visible under `key` only when the context exits cleanly.
        Default implementation spools to a temp file and write()s it."""
        return _SpoolPut(self, key)


class _SpoolPut:
    def __init__(self, store: "ObjectStore", key: str):
        self._store = store
        self._key = key
        self._tmp: Optional[str] = None

    def __enter__(self) -> str:
        fd, self._tmp = tempfile.mkstemp(prefix=".gdb-put-")
        os.close(fd)
        return self._tmp

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            if exc_type is None:
                with open(self._tmp, "rb") as f:
                    self._store.write(self._key, f.read())
        finally:
            try:
                os.unlink(self._tmp)
            except OSError:
                pass


class _FsPut:
    """Direct put: write into a temp file in the destination directory,
    fsync, rename — the same atomicity as FsObjectStore.write without the
    intermediate whole-file buffer."""

    def __init__(self, store: "FsObjectStore", key: str):
        self._path = store._path(key)
        self._tmp: Optional[str] = None

    def __enter__(self) -> str:
        d = os.path.dirname(self._path)
        os.makedirs(d, exist_ok=True)
        fd, self._tmp = tempfile.mkstemp(dir=d, prefix=".tmp-")
        os.close(fd)
        return self._tmp

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            from ..utils import atomic_publish
            atomic_publish(self._tmp, self._path)  # unlinks tmp on failure
            return
        self._unlink_tmp()

    def _unlink_tmp(self) -> None:
        try:
            os.unlink(self._tmp)
        except OSError:
            pass


class FsObjectStore(ObjectStore):
    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = TrackedLock("storage.objstore")

    def _path(self, key: str) -> str:
        p = os.path.normpath(os.path.join(self.root, key))
        if not p.startswith(self.root):
            raise ValueError(f"key escapes root: {key}")
        return p

    def read(self, key: str) -> bytes:
        _fp.fail_point("objstore_read")
        with open(self._path(key), "rb") as f:
            return f.read()

    def write(self, key: str, data: bytes) -> None:
        _fp.fail_point("objstore_write")
        from ..utils import atomic_write
        atomic_write(self._path(key), data)

    def delete(self, key: str) -> None:
        _fp.fail_point("objstore_delete")
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass

    def delete_dir(self, key: str) -> None:
        shutil.rmtree(self._path(key), ignore_errors=True)

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def list(self, prefix: str) -> List[str]:
        base = self._path(prefix) if prefix else self.root
        out = []
        if not os.path.isdir(base):
            return out
        for dirpath, _dirs, files in os.walk(base):
            for fn in files:
                if fn.startswith(".tmp-"):
                    continue
                full = os.path.join(dirpath, fn)
                out.append(os.path.relpath(full, self.root).replace(os.sep, "/"))
        return sorted(out)

    def local_path(self, key: str) -> Optional[str]:
        p = self._path(key)
        return p if os.path.exists(p) else None

    def put_path(self, key: str) -> "_FsPut":
        return _FsPut(self, key)


def new_fs_object_store(root: str) -> FsObjectStore:
    return FsObjectStore(root)


def build_object_store(storage: dict, data_home: str) -> "ObjectStore":
    """Construct the configured backend (reference: datanode builds its
    object store from ObjectStoreConfig — Fs/S3/Oss — and optionally wraps
    the LRU disk cache, src/datanode/src/instance.rs:334-359)."""
    from .retry import RetryingObjectStore
    kind = str(storage.get("type", "File")).lower()
    if kind in ("file", "fs"):
        store: ObjectStore = FsObjectStore(
            storage.get("data_home", data_home))
    elif kind == "s3":
        from .s3 import S3Config, S3ObjectStore
        store = S3ObjectStore(S3Config(
            bucket=storage["bucket"],
            root=storage.get("root", ""),
            endpoint=storage.get("endpoint"),
            region=storage.get("region", "us-east-1"),
            access_key_id=storage.get("access_key_id", ""),
            secret_access_key=storage.get("secret_access_key", "")))
    else:
        raise ValueError(f"unknown storage type {storage.get('type')!r}")
    # transient faults (S3 5xx/429, socket resets, injected failpoints)
    # retry with backoff before any engine code sees them; the cache
    # layer stacks on top so cache hits never pay the wrapper
    store = RetryingObjectStore(store)
    cache = storage.get("cache_path")
    if cache:
        from .cache import LruCacheLayer
        store = LruCacheLayer(
            store, cache, int(storage.get("cache_capacity",
                                          512 * 1024 * 1024)))
    return store
