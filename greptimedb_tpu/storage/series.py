"""Region-level series dictionary: tag tuples ↔ dense series ids.

The TPU-first analogue of the reference's row keys (BTree keys in
src/storage/src/memtable/btree.rs): every distinct combination of tag values
gets a dense int32 `series_id`. Ids are insertion-ordered and append-only, so
they stay stable across flushes — SSTs persist series ids alongside tag
values, and the dictionary snapshot is persisted via the manifest so a
reopened region keeps the same mapping. All group-by/merge/window kernels
operate on these ids; strings never reach the device.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..datatypes import Schema
from ..ops.dictionary import Dictionary


class SeriesDict:
    def __init__(self, tag_names: Sequence[str]):
        self.tag_names = list(tag_names)
        self.tag_dicts: List[Dictionary] = [Dictionary() for _ in self.tag_names]
        self.series = Dictionary()          # tuple(tag ids) -> series id
        self._series_rows: List[Tuple[int, ...]] = []  # series id -> tag ids

    @property
    def num_series(self) -> int:
        return len(self.series)

    def encode_rows(self, tag_columns: Sequence[Sequence]) -> np.ndarray:
        """tag_columns: one sequence per tag (aligned rows) → series ids."""
        if not self.tag_names:
            return np.zeros(len(tag_columns[0]) if tag_columns else 0, np.int32)
        n = len(tag_columns[0])
        ids_per_tag = [d.encode(col) for d, col in zip(self.tag_dicts, tag_columns)]
        out = np.empty(n, dtype=np.int32)
        series = self.series
        rows = self._series_rows
        for i in range(n):
            key = tuple(int(ids[i]) for ids in ids_per_tag)
            sid = series.get(key)
            if sid is None:
                sid = series.get_or_insert(key)
                rows.append(key)
            out[i] = sid
        return out

    def encode_zero_tags(self, n: int) -> np.ndarray:
        """For tables without tags: every row is series 0."""
        if self.series.get(()) is None:
            self.series.get_or_insert(())
            self._series_rows.append(())
        return np.zeros(n, dtype=np.int32)

    def decode_tag_column(self, series_ids: np.ndarray, tag_index: int) -> List:
        d = self.tag_dicts[tag_index]
        rows = self._series_rows
        return [d.value(rows[int(s)][tag_index]) for s in series_ids]

    def series_tag_matrix(self) -> np.ndarray:
        """[num_series, num_tags] per-tag value ids — the device-side mapping
        for group-by over a subset of tags."""
        if not self._series_rows:
            return np.zeros((0, len(self.tag_names)), dtype=np.int32)
        return np.asarray(self._series_rows, dtype=np.int32)

    def tag_value_id(self, tag_index: int, value) -> Optional[int]:
        return self.tag_dicts[tag_index].get(value)

    # ---- persistence ----
    def to_dict(self) -> dict:
        return {
            "tag_names": self.tag_names,
            "tag_values": [d.to_list() for d in self.tag_dicts],
            "series": [list(t) for t in self._series_rows],
        }

    @staticmethod
    def from_dict(d: dict) -> "SeriesDict":
        sd = SeriesDict(d["tag_names"])
        sd.tag_dicts = [Dictionary.from_list(vals) for vals in d["tag_values"]]
        for row in d["series"]:
            key = tuple(row)
            sd.series.get_or_insert(key)
            sd._series_rows.append(key)
        return sd

    @staticmethod
    def for_schema(schema: Schema) -> "SeriesDict":
        return SeriesDict(schema.tag_names())
