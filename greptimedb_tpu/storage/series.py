"""Region-level series dictionary: tag tuples ↔ dense series ids.

The TPU-first analogue of the reference's row keys (BTree keys in
src/storage/src/memtable/btree.rs): every distinct combination of tag values
gets a dense int32 `series_id`. Ids are insertion-ordered and append-only, so
they stay stable across flushes — SSTs persist series ids alongside tag
values, and the dictionary snapshot is persisted via the manifest so a
reopened region keeps the same mapping. All group-by/merge/window kernels
operate on these ids; strings never reach the device.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..datatypes import Schema
from ..ops.dictionary import Dictionary


class SeriesDict:
    def __init__(self, tag_names: Sequence[str]):
        self.tag_names = list(tag_names)
        self.tag_dicts: List[Dictionary] = [Dictionary() for _ in self.tag_names]
        self.series = Dictionary()          # tuple(tag ids) -> series id
        self._series_rows: List[Tuple[int, ...]] = []  # series id -> tag ids
        # decode_tag_column staging (per tag): (num_series, id column,
        # num_values, values array) — rebuilt only when the dictionary grew
        self._decode_cache: Dict[int, Tuple[int, np.ndarray, int,
                                            np.ndarray]] = {}

    @property
    def num_series(self) -> int:
        return len(self.series)

    def encode_rows(self, tag_columns: Sequence[Sequence]) -> np.ndarray:
        """tag_columns: one sequence per tag (aligned rows) → series ids."""
        if not self.tag_names:
            return np.zeros(len(tag_columns[0]) if tag_columns else 0, np.int32)
        n = len(tag_columns[0])
        ids_per_tag = [d.encode(col) for d, col in zip(self.tag_dicts, tag_columns)]
        series = self.series
        rows = self._series_rows
        if n > 1024:
            # dedup tag-id combinations first: the per-row dict walk then
            # touches each distinct series once. Combinations pack into
            # ONE int64 key hashed by pandas factorize — O(n), no sort
            # (np.unique(axis=0) argsorts a structured view: 2.6s per 2M
            # rows; this path is ~50ms)
            bits = [max((int(ids.max()) + 1).bit_length(), 1)
                    for ids in ids_per_tag]
            if sum(bits) <= 63:
                import pandas as pd
                if len(ids_per_tag) == 1:
                    key = ids_per_tag[0].astype(np.int64)
                else:
                    key = np.zeros(n, np.int64)
                    for ids, b in zip(ids_per_tag, bits):
                        key = (key << b) | ids.astype(np.int64)
                # run-collapse first: series-grouped loader batches turn
                # the per-row factorize into one over run starts (int
                # adjacency compare is ~50x cheaper than hashing)
                flags = np.empty(n, dtype=bool)
                flags[0] = True
                np.not_equal(key[1:], key[:-1], out=flags[1:])
                starts = np.nonzero(flags)[0]
                lens = None
                if len(starts) * 16 <= n:
                    lens = np.diff(starts, append=n)
                    key = key[starts]
                codes, uniques = pd.factorize(key, sort=False)
                sids_u = np.empty(len(uniques), dtype=np.int32)
                for k, u in enumerate(uniques):
                    if len(ids_per_tag) == 1:
                        key_t = (int(u),)
                    else:
                        rem = int(u)
                        rev: List[int] = []
                        for b in reversed(bits):
                            rev.append(rem & ((1 << b) - 1))
                            rem >>= b
                        key_t = tuple(reversed(rev))
                    sid = series.get(key_t)
                    if sid is None:
                        sid = series.get_or_insert(key_t)
                        rows.append(key_t)
                    sids_u[k] = sid
                out = sids_u[codes].astype(np.int32, copy=False)
                return np.repeat(out, lens) if lens is not None else out
            mat = np.stack(ids_per_tag, axis=1)
            uniq, inv = np.unique(mat, axis=0, return_inverse=True)
            sids_u = np.empty(len(uniq), dtype=np.int32)
            for k, row in enumerate(uniq):
                key = tuple(int(x) for x in row)
                sid = series.get(key)
                if sid is None:
                    sid = series.get_or_insert(key)
                    rows.append(key)
                sids_u[k] = sid
            return sids_u[inv.reshape(-1)].astype(np.int32, copy=False)
        out = np.empty(n, dtype=np.int32)
        for i in range(n):
            key = tuple(int(ids[i]) for ids in ids_per_tag)
            sid = series.get(key)
            if sid is None:
                sid = series.get_or_insert(key)
                rows.append(key)
            out[i] = sid
        return out

    def encode_zero_tags(self, n: int) -> np.ndarray:
        """For tables without tags: every row is series 0."""
        if self.series.get(()) is None:
            self.series.get_or_insert(())
            self._series_rows.append(())
        return np.zeros(n, dtype=np.int32)

    def _decode_staging(self, tag_index: int):
        """[num_series] tag-id column + values array for one tag, cached;
        rebuilt only when the dictionary grew (ids are append-only)."""
        d = self.tag_dicts[tag_index]
        rows = self._series_rows
        cached = self._decode_cache.get(tag_index)
        if cached is None or cached[0] != len(rows) or cached[2] != len(d):
            col = np.fromiter((r[tag_index] for r in rows), np.int32,
                              len(rows))
            vals = np.asarray(d.values(), dtype=object)
            cached = (len(rows), col, len(d), vals)
            self._decode_cache[tag_index] = cached
        return cached[1], cached[3]

    def decode_tag_column(self, series_ids: np.ndarray, tag_index: int) -> List:
        d = self.tag_dicts[tag_index]
        rows = self._series_rows
        n = len(series_ids)
        if n > 1024 and rows:
            # gather through the [num_series] id column + values array
            # instead of a per-row Python walk
            col, vals = self._decode_staging(tag_index)
            sids = np.asarray(series_ids, dtype=np.int64)
            return vals[col[sids]].tolist()
        return [d.value(rows[int(s)][tag_index]) for s in series_ids]

    def tag_id_column(self, series_ids: np.ndarray, tag_index: int
                      ) -> Tuple[np.ndarray, list]:
        """(per-row tag value ids, dictionary values) — lets the SST
        writer build an arrow DictionaryArray directly instead of
        materializing and re-encoding the string column."""
        col, _ = self._decode_staging(tag_index)
        sids = np.asarray(series_ids, dtype=np.int64)
        return col[sids] if len(col) else np.zeros(len(sids), np.int32), \
            self.tag_dicts[tag_index].values()

    def series_tag_matrix(self) -> np.ndarray:
        """[num_series, num_tags] per-tag value ids — the device-side mapping
        for group-by over a subset of tags."""
        if not self._series_rows:
            return np.zeros((0, len(self.tag_names)), dtype=np.int32)
        return np.asarray(self._series_rows, dtype=np.int32)

    def tag_value_id(self, tag_index: int, value) -> Optional[int]:
        return self.tag_dicts[tag_index].get(value)

    def sids_for_value_ids(self, tag_index: int,
                           value_ids: Sequence[int]) -> np.ndarray:
        """Sorted series ids whose tag at `tag_index` takes any of the
        given dictionary value ids — the inverted (tag value → series)
        lookup behind per-SST index pruning: one vectorized pass over
        the [num_series] staging column, no per-row work."""
        if not value_ids or not self._series_rows:
            return np.zeros(0, dtype=np.int32)
        col, _ = self._decode_staging(tag_index)
        hits = np.isin(col, np.asarray(list(value_ids), dtype=np.int32))
        return np.nonzero(hits)[0].astype(np.int32)

    def sids_for_tag_values(self, tag_index: int,
                            values: Sequence) -> np.ndarray:
        """Sorted series ids whose tag equals any of `values` exactly —
        values absent from the dictionary match nothing (a point query
        for a never-seen tag value resolves to the empty set, which
        prunes every file)."""
        ids = [self.tag_dicts[tag_index].get(v) for v in values]
        return self.sids_for_value_ids(
            tag_index, [i for i in ids if i is not None])

    # ---- persistence ----
    def to_dict(self) -> dict:
        return {
            "tag_names": self.tag_names,
            "tag_values": [d.to_list() for d in self.tag_dicts],
            "series": [list(t) for t in self._series_rows],
        }

    @staticmethod
    def from_dict(d: dict) -> "SeriesDict":
        sd = SeriesDict(d["tag_names"])
        sd.tag_dicts = [Dictionary.from_list(vals) for vals in d["tag_values"]]
        for row in d["series"]:
            key = tuple(row)
            sd.series.get_or_insert(key)
            sd._series_rows.append(key)
        return sd

    @staticmethod
    def for_schema(schema: Schema) -> "SeriesDict":
        return SeriesDict(schema.tag_names())
